#!/usr/bin/env python
"""Headline benchmark: FedAvg rounds/sec, CIFAR-10 CNN, 64 simulated clients.

Matches the driver's north-star metric (BASELINE.json): one "round" is the
full reference round semantics — every client does one local epoch of SGD on
its shard (6 batches of 128 at world=64, mirroring ~391/64 batches of the
reference's round-robin split, ``src/main.py:140-144``) followed by the
FedAvg aggregate. The whole round is one XLA program; rounds/sec counts
end-to-end jitted steps including the aggregation.

Normalisation: the 200 rounds/sec north-star target assumes a v4-64 (64
chips, one client per chip), i.e. 200 client-epochs/sec *per chip*. This
bench runs on however many devices are visible (typically ONE chip simulating
all 64 clients), so the reported metric is per-chip client-epoch throughput:
``rounds/sec x num_clients / num_devices``, directly comparable to the
north-star's 200/s-per-chip. ``vs_baseline`` is the ratio to that target
(the reference publishes no numbers of its own — BASELINE.md). The JSON line
also carries the raw ``rounds_per_sec``, ``n_devices``, ``device_kind``,
``flops_per_round`` (XLA cost analysis) and ``mfu`` so the normalisation is
auditable.

Robustness: backend acquisition on the remote-tunnel TPU can wedge (observed:
bare ``jax.devices()`` hanging >120 s), so the measurement runs in a child
process with a bounded timeout and is retried with backoff; on terminal
failure this script STILL prints exactly one JSON line (with an ``error``
field, plus a ``live_artifact`` pointer to this round's most recent
builder-captured live measurement if one exists) and exits 0 so the
artifact is diagnostic rather than empty.

The measured program is the engine's fused multi-round scan
(:func:`fedtpu.data.device.make_multi_round_step`): each timed dispatch runs
``TIMED_ROUNDS`` complete FedAvg rounds on device — per-round batch
extraction from the HBM-resident presharded dataset (one contiguous rotated
slice per round; see ``fedtpu/data/device.py``), vmapped local SGD,
aggregation — with no host involvement between rounds. Timing is honest under the remote-tunnel device:
the stacked per-round losses (program outputs) are fetched after every
dispatch, which cannot complete before all rounds have executed
(``block_until_ready`` alone does not reliably block on the tunnel); the
median of several trials is reported to damp shared-device noise.

Prints exactly one JSON line.
"""

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NUM_CLIENTS = 64
BATCH = 128
STEPS_PER_ROUND = 391 // NUM_CLIENTS  # reference local-epoch share at world=64
TIMED_ROUNDS = 10  # rounds fused into one scanned program (= one dispatch)
TRIALS = 3
TARGET_PER_CHIP = 200.0  # client-epochs/sec/chip implied by the north star
METRIC = "fedavg_client_epochs_per_sec_per_chip_cifar10_cnn_64clients"
UNIT = "client-epochs/sec/chip"
# Variant knobs for perf experiments (BASELINE.md roofline attribution runs).
# The driver runs bench.py with a clean environment, so the headline metric is
# ALWAYS the parity config; variants only fire when the watcher sets these,
# and the output then carries a "variant" field so an experiment artifact can
# never masquerade as the headline.
BENCH_MODEL = os.environ.get("FEDTPU_BENCH_MODEL", "smallcnn")
MOMENTUM_DTYPE = os.environ.get("FEDTPU_MOMENTUM_DTYPE", "float32")
COMPUTE_DTYPE = os.environ.get("FEDTPU_COMPUTE_DTYPE", "float32")
MEGABATCH_CLIENTS = int(os.environ.get("FEDTPU_MEGABATCH_CLIENTS", "0") or 0)
_TIMED_ROUNDS_ENV = os.environ.get("FEDTPU_BENCH_TIMED_ROUNDS", "")
if _TIMED_ROUNDS_ENV:
    TIMED_ROUNDS = int(_TIMED_ROUNDS_ENV)

ATTEMPT_TIMEOUT_S = 1200  # first jit on the tunnel chip can take minutes
ATTEMPTS = 3
BACKOFF_S = 20
# Cheap reachability preflight: a bare jax.devices() against the tunnel
# backend either returns in seconds or wedges forever (observed: >180 s).
# Probing first turns a dead-relay run into a ~10-minute diagnostic instead
# of burning all three 20-minute measurement attempts.
PROBE_TIMEOUT_S = 240
PROBE_ATTEMPTS = 2

# Peak bf16 FLOPs/sec per chip by device kind (public figures), for MFU.
# Aliases cover the PJRT device_kind strings actually observed in the wild
# ("TPU v5 lite", "TPU v5e", "TPU v4", ...), matched on the space-stripped
# lowercase form.
_PEAK_FLOPS = (
    (("v6e", "v6lite", "trillium"), 918e12),
    (("v5p",), 459e12),
    (("v5e", "v5lite"), 197e12),
    (("v4",), 275e12),
    (("v3",), 123e12),
    (("v2",), 45e12),
)


def _peak_for(device_kind: str):
    kind = device_kind.lower().replace(" ", "").replace("-", "")
    for aliases, peak in _PEAK_FLOPS:
        if any(a in kind for a in aliases):
            return peak
    return None


def _measure():
    """Run the actual benchmark in this process and return the result dict."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.core.engine import Federation

    cfg = RoundConfig(
        model=BENCH_MODEL,
        num_classes=10,
        opt=OptimizerConfig(momentum_dtype=MOMENTUM_DTYPE),
        data=DataConfig(
            dataset="cifar10",
            batch_size=BATCH,
            partition="iid",
            num_examples=NUM_CLIENTS * STEPS_PER_ROUND * BATCH,
        ),
        fed=FedConfig(
            num_clients=NUM_CLIENTS,
            compute_dtype=COMPUTE_DTYPE,
            megabatch_clients=MEGABATCH_CLIENTS,
        ),
        steps_per_round=STEPS_PER_ROUND,
        dtype="bfloat16",
    )
    devices = jax.devices()
    n_dev = len(devices)
    flops_per_round = None
    if n_dev > 1 and NUM_CLIENTS % n_dev == 0:
        from fedtpu.parallel import client_mesh

        fed = Federation(cfg, seed=0, mesh=client_mesh(n_dev, cfg.mesh_axis))
        fed.run_on_device(TIMED_ROUNDS)  # compile + warmup dispatch
        np.asarray(fed.state.round_idx)

        def timed_dispatch():
            m = fed.run_on_device(TIMED_ROUNDS)
            np.asarray(m.loss)
    else:
        # Unsharded path executes on ONE device regardless of how many are
        # visible — normalise per-chip metrics accordingly. The measured
        # program is the engine's fused multi-round scan (TIMED_ROUNDS full
        # FedAvg rounds per dispatch: per-round on-device batch gather,
        # vmapped local SGD, aggregation), AOT-compiled so the timed loop
        # reuses ONE executable and cost analysis is available.
        n_dev = 1
        fed = Federation(cfg, seed=0)
        d_images, d_labels, d_idx, d_mask = fed._ensure_device_data()
        alive = jnp.ones((TIMED_ROUNDS, NUM_CLIENTS), bool)
        # AOT-compile the ENGINE's own fused program (single source of truth
        # with Federation.run_on_device — same shuffle/compressor wiring) so
        # the timed loop reuses one executable and cost analysis is available.
        multi = fed._multi_step(TIMED_ROUNDS)
        args = (fed.state, d_images, d_labels, d_idx, d_mask, fed.weights,
                alive, fed._data_key)
        step = multi.lower(*args).compile()
        # FLOPs/round from the SINGLE-round program: XLA cost analysis counts
        # a lax.scan body ONCE regardless of trip count (measured: the fused
        # 10-round program reports the same flops as one round), so dividing
        # the fused program's number by TIMED_ROUNDS — or trusting it to
        # already be multiplied — would silently mis-scale MFU if that
        # convention ever changes. The extra AOT compile is never executed.
        try:
            single = fed._data_step.lower(
                fed.state, d_images, d_labels, d_idx, d_mask, fed.weights,
                jnp.ones((NUM_CLIENTS,), bool), fed._data_key,
            ).compile()
            analysis = single.cost_analysis()
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else {}
            flops_per_round = float(analysis.get("flops", 0.0)) or None
        except Exception:
            pass
        carry = {"state": fed.state}

        def timed_dispatch():
            carry["state"], m = step(
                carry["state"], d_images, d_labels, d_idx, d_mask,
                fed.weights, alive, fed._data_key,
            )
            # Fetching the stacked per-round losses forces completion of the
            # whole scan (they are program outputs) — the honest sync point.
            np.asarray(m.loss)

        timed_dispatch()  # warmup dispatch on the compiled executable

    rates = []
    for _ in range(TRIALS):
        t0 = time.perf_counter()
        timed_dispatch()
        rates.append(TIMED_ROUNDS / (time.perf_counter() - t0))
    rounds_per_sec = sorted(rates)[len(rates) // 2]

    device_kind = devices[0].device_kind
    per_chip = rounds_per_sec * NUM_CLIENTS / n_dev
    result = {
        "metric": METRIC,
        "value": round(per_chip, 3),
        "unit": UNIT,
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
        "rounds_per_sec": round(rounds_per_sec, 4),
        "timed_rounds_per_dispatch": TIMED_ROUNDS,
        "n_devices": n_dev,
        "num_clients": NUM_CLIENTS,
        "device_kind": device_kind,
        "backend": jax.default_backend(),
    }
    result = _apply_variant_labels(result)
    if flops_per_round:
        result["flops_per_round"] = flops_per_round
        peak = _peak_for(device_kind)
        if peak:
            result["mfu"] = round(rounds_per_sec * flops_per_round / (n_dev * peak), 4)
    return result


def _apply_variant_labels(result):
    """Stamp variant runs so the artifact is self-distinguishing even to a
    consumer keyed on 'metric' alone (ADVICE r5): suffix the metric string
    AND drop vs_baseline — the 200/s target is defined for the parity
    config only, so a ratio against it would be meaningless here."""
    if (
        BENCH_MODEL != "smallcnn"
        or MOMENTUM_DTYPE != "float32"
        or COMPUTE_DTYPE != "float32"
        or MEGABATCH_CLIENTS
        or _TIMED_ROUNDS_ENV
    ):
        result["metric"] = METRIC + "_variant"
        result.pop("vs_baseline", None)
        result["variant"] = {
            "model": BENCH_MODEL, "momentum_dtype": MOMENTUM_DTYPE,
            "compute_dtype": COMPUTE_DTYPE,
            "megabatch_clients": MEGABATCH_CLIENTS,
        }
        if _TIMED_ROUNDS_ENV:
            # Deeper fusion changes the dispatch-amortisation denominator,
            # so a fused-40 figure must self-label too (the gate is the ENV
            # knob, not the test-shrunk module constant).
            result["variant"]["timed_rounds"] = TIMED_ROUNDS
    return result


def _compression_microbench():
    """``compression_packed_vs_per_leaf``: flat vs per-leaf delta pipeline.

    Compares the per-round codec + FedAvg-aggregation stage of the two
    ``FedConfig.delta_layout`` modes on a many-leaf zoo model. "Dispatches"
    = jaxpr primitive-equation count of that stage — the op count the
    per-leaf path pays PER LEAF (one top_k / quantize / reduce each) and the
    flat path pays once for the whole model; CPU-measurable, no accelerator
    needed. The flat path's once-per-round pack/unpack (pure data movement
    XLA folds into neighbouring fusions) is reported separately so the
    ratio is auditable. Host wall time of the full jitted pipelines
    (INCLUDING pack/unpack for flat) is recorded alongside.

    Run via ``python bench.py --compression-microbench``; prints one JSON
    line, separate from the headline metric.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu import models as zoo
    from fedtpu.core.round import _mean_over_clients
    from fedtpu.ops import compression, flat as flat_ops

    model_name = os.environ.get("FEDTPU_MB_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_MB_CLIENTS", "4"))
    reps = int(os.environ.get("FEDTPU_MB_REPS", "3"))
    fraction = 0.01

    model = zoo.create(model_name, num_classes=10)
    # eval_shape: leaf shapes without running the forward pass.
    params = jax.eval_shape(
        lambda r, x: model.init(r, x, train=False),
        jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.float32),
    )["params"]
    lay = flat_ops.make_layout(params)
    rng = np.random.default_rng(0)
    deltas = jax.tree.map(
        lambda s: jnp.asarray(
            rng.normal(size=(clients,) + tuple(s.shape)).astype(np.float32)
        ),
        params,
    )
    weights = jnp.ones((clients,), jnp.float32)

    def eqns(f, *args):
        return len(jax.make_jaxpr(f)(*args).eqns)

    def timed(fn, *args):
        out = fn(*args)  # compile + warmup
        jax.block_until_ready(out)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            times.append((time.perf_counter() - t0) * 1e3)
        return sorted(times)[len(times) // 2]

    codecs = {}
    for kind in ("topk", "int8"):
        if kind == "topk":
            per = compression.make_topk(fraction)
            fl = compression.make_topk(fraction, layout="flat")
        else:
            per = compression.make_int8()
            fl = compression.make_int8(layout="flat")
        st_per = per.init(params, clients)
        st_fl = fl.init(params, clients)

        def per_leaf_stage(d, s):
            out, new = per.apply(d, s)
            mean, _ = _mean_over_clients(out, weights, None)
            return mean, new

        def flat_stage(y, s):
            out, new = fl.apply_flat(y, s, lay)
            mean, _ = _mean_over_clients(out, weights, None)
            return mean, new

        def flat_pipeline(d, s):
            # End-to-end flat round stage including the once-per-round
            # pack (clients x P) and unpack (one [P] row) — the honest
            # wall-clock comparison.
            mean, new = flat_stage(flat_ops.pack_stacked(lay, d), s)
            return flat_ops.unpack(lay, mean), new

        y0 = flat_ops.pack_stacked(lay, deltas)
        n_per = eqns(per_leaf_stage, deltas, st_per)
        n_fl = eqns(flat_stage, y0, st_fl)
        codecs[kind] = {
            "per_leaf_dispatches": n_per,
            "flat_dispatches": n_fl,
            "dispatch_ratio": round(n_fl / max(n_per, 1), 4),
            "per_leaf_host_ms": round(
                timed(jax.jit(per_leaf_stage), deltas, st_per), 3
            ),
            "flat_host_ms": round(
                timed(jax.jit(flat_pipeline), deltas, st_fl), 3
            ),
        }

    mean_row = jnp.zeros((lay.padded,), jnp.float32)
    return {
        "metric": "compression_packed_vs_per_leaf",
        "unit": "jaxpr-eqns (codec + aggregation stage)",
        "model": model_name,
        "num_leaves": lay.num_leaves,
        "num_params": lay.total,
        "padded_row": lay.padded,
        "num_clients": clients,
        # Worst-case codec ratio — the acceptance headline (target <= 0.10).
        "value": max(c["dispatch_ratio"] for c in codecs.values()),
        "codecs": codecs,
        # Once-per-round flat packing cost, reported for auditability: the
        # pack touches [clients, P] once, the unpack ONE aggregated [P] row.
        "flat_pack_dispatches": eqns(
            lambda d: flat_ops.pack_stacked(lay, d), deltas
        ),
        "flat_unpack_dispatches": eqns(
            lambda v: flat_ops.unpack(lay, v), mean_row
        ),
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }


def _codec_frontier_microbench():
    """``codec_frontier``: wire bytes vs fidelity across the codec family,
    plus a convergence leg pinning the ≥10x operating point.

    Two legs in one artifact (``artifacts/CODEC_FRONTIER_MICROBENCH.json``):

    - **sweep**: every wire codec — dense / int8 / topk / rotq@{1,2,4,8}
      bits / randk — encodes the SAME synthetic delta at the densenet
      profile shape through the real ``fedtpu.transport.sparse`` / ``wire``
      encoders (not an analytic byte model). Per codec: payload bytes,
      reduction vs the dense baseline, encode/decode host-wall medians, and
      one-shot reconstruction relative L2 error — the fidelity axis of the
      frontier. One-shot error is the right sweep metric because it needs
      no training loop; error-FEEDBACK fidelity (residual carried across
      rounds) is what the convergence leg measures. rotq bytes include the
      power-of-two pad its Hadamard rotation needs — the honest wire
      number (~1.33x inflation at this shape, stamped as ``pad_ratio``).
    - **convergence** (the headline ``value``): the engine trained twice
      from the same seed — ``compression='none'`` vs the ≥10x operating
      point (randk, small keep-fraction, error feedback on, flat layout) —
      then evaluated on held-out synthetic test data. Per-round wire bytes
      come from genuinely encoding the run's aggregate model delta through
      ``sparse.encode_randk_flat`` vs a dense ``wire.encode`` of the same
      payload (both byte counts are shape-deterministic, so one encode IS
      the per-round figure). Gates, recorded in the JSON and pinned by
      tests/test_bench.py against the committed artifact: wire-byte
      ``reduction_x >= 10`` AND final test accuracy within
      ``FEDTPU_CF_ACC_TOL`` (default 0.05) of the uncompressed run.

    Env knobs (shrunk by tests/test_bench.py): FEDTPU_CF_MODEL / _REPS /
    _FRACTION (sweep + convergence keep-fraction) / _CONV_CLIENTS /
    _CONV_ROUNDS / _ACC_TOL. Run via ``python bench.py
    --codec-frontier-microbench``; prints one JSON line and writes the
    artifact.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fedtpu import models as zoo
    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig,
    )
    from fedtpu.core.engine import Federation
    from fedtpu.data import load
    from fedtpu.transport import sparse, wire

    model_name = os.environ.get("FEDTPU_CF_MODEL", "densenet_cifar")
    reps = int(os.environ.get("FEDTPU_CF_REPS", "3"))
    fraction = float(os.environ.get("FEDTPU_CF_FRACTION", "0.05"))
    conv_clients = int(os.environ.get("FEDTPU_CF_CONV_CLIENTS", "4"))
    conv_rounds = int(os.environ.get("FEDTPU_CF_CONV_ROUNDS", "20"))
    acc_tol = float(os.environ.get("FEDTPU_CF_ACC_TOL", "0.05"))

    # ------------------------------------------------------------- sweep
    model = zoo.create(model_name, num_classes=10)
    shapes = jax.eval_shape(
        lambda r, x: model.init(r, x, train=False),
        jax.random.PRNGKey(0),
        jnp.zeros((1, 32, 32, 3), jnp.float32),
    )["params"]
    rng = np.random.default_rng(0)
    deltas = jax.tree.map(
        lambda s: rng.normal(scale=1e-2, size=s.shape).astype(np.float32),
        shapes,
    )
    flat_ref = np.concatenate(
        [np.asarray(l).ravel() for l in jax.tree_util.tree_leaves(deltas)]
    )
    ref_norm = float(np.linalg.norm(flat_ref)) or 1.0

    def med(fn):
        fn()  # warmup (allocator, BLAS thread pools)
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append((time.perf_counter() - t0) * 1e3)
        return round(sorted(times)[len(times) // 2], 3)

    def rel_l2(tree):
        got = np.concatenate(
            [
                np.asarray(l, np.float32).ravel()
                for l in jax.tree_util.tree_leaves(tree)
            ]
        )
        return round(float(np.linalg.norm(got - flat_ref)) / ref_norm, 6)

    # collect_residual=False everywhere: the sweep measures the record a
    # client ships, not the EF bookkeeping around it (randk then applies
    # its unbiased total/k rescale — the no-EF wire semantics).
    specs = [
        ("dense", lambda: wire.encode(deltas)),
        (
            "int8",
            lambda: sparse.encode_int8_flat(deltas, collect_residual=False)[0],
        ),
        (
            "topk",
            lambda: sparse.encode_topk_flat(
                deltas, fraction, collect_residual=False
            )[0],
        ),
    ]
    for bits in sparse.ROTQ_BITS:
        specs.append(
            (
                f"rotq@{bits}b",
                lambda b=bits: sparse.encode_rotq_flat(
                    deltas, bits=b, collect_residual=False, seed=7
                )[0],
            )
        )
    specs.append(
        (
            "randk",
            lambda: sparse.encode_randk_flat(
                deltas, fraction, collect_residual=False, seed=7
            )[0],
        )
    )

    dense_bytes = len(wire.encode(deltas))
    sweep = {}
    for name, enc in specs:
        payload = enc()
        if name == "dense":
            decoded = wire.decode(payload, deltas)
            dec = lambda p=payload: wire.decode(p, deltas)
        else:
            decoded = sparse.decode(payload, deltas)[0]
            dec = lambda p=payload: sparse.decode(p, deltas)
        sweep[name] = {
            "wire_bytes": len(payload),
            "reduction_x": round(dense_bytes / max(len(payload), 1), 3),
            "encode_host_ms": med(enc),
            "decode_host_ms": med(dec),
            "rel_l2_error": rel_l2(decoded),
        }
    total = int(flat_ref.size)
    pad_ratio = round(sparse._next_pow2(max(total, 1)) / max(total, 1), 4)

    # ------------------------------------------------------- convergence
    def conv_cfg(compression):
        return RoundConfig(
            model="mlp",
            num_classes=10,
            opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
            data=DataConfig(
                dataset="synthetic",
                batch_size=8,
                eval_batch_size=64,
                num_examples=256,
                augment=False,
            ),
            fed=FedConfig(
                num_clients=conv_clients,
                telemetry="off",
                compression=compression,
                topk_fraction=fraction,
                error_feedback=True,
                delta_layout="flat",
            ),
            steps_per_round=2,
        )

    test_x, test_y = load("synthetic", "test", num=512)
    runs = {}
    conv_delta = None
    for name in ("none", "randk"):
        fed = Federation(conv_cfg(name), seed=0)
        init_params = jax.tree.map(np.asarray, fed.state.params)
        fed.run(conv_rounds)
        _, acc = fed.evaluate(test_x, test_y)
        runs[name] = {"final_test_acc": round(float(acc), 4)}
        if name == "randk":
            conv_delta = {
                "params": jax.tree.map(
                    lambda a, b: np.asarray(a, np.float32) - b,
                    fed.state.params,
                    init_params,
                )
            }
        del fed

    # The per-round uplink: dense fleets ship the full payload, randk
    # fleets ship the sparse record. Both sizes depend only on the model
    # shape and the keep budget, so encoding the run's genuine aggregate
    # delta once gives the exact per-round figure.
    conv_dense_bytes = len(wire.encode(conv_delta))
    conv_randk_bytes = len(
        sparse.encode_randk_flat(
            conv_delta["params"], fraction, collect_residual=False, seed=1
        )[0]
    )
    reduction_x = round(conv_dense_bytes / max(conv_randk_bytes, 1), 3)
    acc_gap = round(
        abs(runs["none"]["final_test_acc"] - runs["randk"]["final_test_acc"]),
        4,
    )

    result = {
        "metric": "codec_frontier",
        "unit": "x wire-byte reduction at the convergence operating point",
        "value": reduction_x,
        "gate_reduction_x": 10.0,
        "gate_acc_tol": acc_tol,
        "passes_gate": bool(reduction_x >= 10.0 and acc_gap <= acc_tol),
        "sweep": {
            "model": model_name,
            "num_params": total,
            "dense_bytes": dense_bytes,
            "fraction": fraction,
            "rotq_pad_ratio": pad_ratio,
            "codecs": sweep,
        },
        "convergence": {
            "model": "mlp",
            "codec": "randk",
            "fraction": fraction,
            "error_feedback": True,
            "clients": conv_clients,
            "rounds": conv_rounds,
            "runs": runs,
            "acc_gap": acc_gap,
            "bytes_up_dense": conv_dense_bytes,
            "bytes_up_randk": conv_randk_bytes,
            "reduction_x": reduction_x,
        },
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "CODEC_FRONTIER_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _server_pipeline_microbench():
    """``server_pipeline_post_barrier``: barrier vs stream server collect.

    Measures what the distributed server does AFTER the last StartTrain
    reply lands (the post-barrier gap the streaming pipeline exists to
    shrink) plus the per-reply collect-side work, on real wire payloads
    through the real ``PrimaryServer`` machinery — no gRPC, the replies are
    pre-encoded ``int8_flat`` records:

    - ``barrier``: per-leaf template decode per reply (collect side), then
      leaf-by-leaf stacking of every client tree + the jitted
      ``_aggregate`` (host->device transfer inside the dispatch) after the
      barrier — the reference-shaped path.
    - ``stream``: decode-into-row + per-row device_put + in-place device
      buffer write per reply (collect side, overlapped with network wait in
      real rounds), then ONE fused ``_finalize_stream`` after the barrier.

    Also reports peak host delta memory (decoded per-leaf trees for every
    client vs one flat ``[clients, P]`` buffer) and checks the two paths'
    aggregated params are bit-identical. Run via
    ``python bench.py --server-pipeline-microbench``; prints one JSON line
    and writes ``artifacts/SERVER_PIPELINE_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.transport import sparse
    from fedtpu.transport.federation import PrimaryServer, _model_template

    model_names = os.environ.get(
        "FEDTPU_SPB_MODELS", "densenet_cifar,smallcnn"
    ).split(",")
    clients = int(os.environ.get("FEDTPU_SPB_CLIENTS", "64"))
    reps = int(os.environ.get("FEDTPU_SPB_REPS", "3"))

    def timed(fn):
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    models = {}
    for name in model_names:
        name = name.strip()
        cfg = RoundConfig(
            model=name,
            num_classes=10,
            data=DataConfig(dataset="cifar10"),
            fed=FedConfig(
                num_clients=clients,
                delta_layout="flat",
                server_pipeline="stream",
            ),
        )
        primary = PrimaryServer(cfg, [])
        lay = primary._flat_layout
        params_t, stats_t = _model_template(primary.model, cfg)
        template = {"params": params_t, "batch_stats": stats_t}
        rng = np.random.default_rng(0)
        delta = jax.tree.map(
            lambda s: rng.normal(size=s.shape).astype(np.float32) * 1e-2,
            template,
        )
        payload, _ = sparse.encode_int8_flat(
            delta, extra={"num_examples": np.float32(6.0)}
        )
        weights = jnp.ones((clients,), jnp.float32)
        global_tree = {
            "params": primary.params, "batch_stats": primary.batch_stats
        }

        # ---- collect-side work, per reply --------------------------------
        decode_tree_s = timed(lambda: sparse.decode(payload, template))
        tree = sparse.decode(payload, template)[0]
        trees = [tree] * clients

        host_row = np.zeros((lay.padded,), np.float32)
        dev_buf = [jnp.zeros((clients, lay.padded), jnp.float32)]

        def stream_reply(i=0):
            sparse.decode_into_row(payload, lay.sizes, host_row)
            dev_buf[0] = primary._set_row(
                dev_buf[0], jax.device_put(host_row), i
            )
            jax.block_until_ready(dev_buf[0])

        stream_reply()  # compile _set_row before timing
        decode_row_s = timed(stream_reply)
        for i in range(clients):
            stream_reply(i)

        # ---- post-barrier gap: last reply -> new global ------------------
        def barrier_post():
            stacked = jax.tree.map(
                lambda *ls: jnp.stack(ls), *trees
            )
            out, _ = primary._aggregate(
                global_tree, stacked, weights,
                primary._server_opt_state, jnp.asarray(0, jnp.int32),
            )
            jax.block_until_ready(out["params"])
            return out

        def stream_post():
            out, _ = primary._finalize_stream(
                global_tree, dev_buf[0], weights,
                primary._server_opt_state,
            )
            jax.block_until_ready(out["params"])
            return out

        out_b = barrier_post()  # compile both before timing
        out_s = stream_post()
        bit_identical = all(
            bool(np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(
                jax.tree.leaves(out_b["params"]),
                jax.tree.leaves(out_s["params"]),
            )
        )
        barrier_post_s = timed(barrier_post)
        stream_post_s = timed(stream_post)

        tree_bytes = sum(
            np.asarray(l).nbytes for l in jax.tree.leaves(tree)
        )
        models[name] = {
            "num_leaves": lay.num_leaves,
            "num_params": lay.total,
            "padded_row": lay.padded,
            "barrier": {
                "decode_ms_per_reply": round(decode_tree_s * 1e3, 3),
                "post_barrier_s": round(barrier_post_s, 4),
                "host_delta_bytes": tree_bytes * clients,
            },
            "stream": {
                "decode_h2d_ms_per_reply": round(decode_row_s * 1e3, 3),
                "post_barrier_s": round(stream_post_s, 4),
                "host_delta_bytes": int(clients * lay.padded * 4),
            },
            "post_barrier_speedup": round(barrier_post_s / stream_post_s, 2),
            "mean_bit_identical": bit_identical,
        }

    headline = model_names[0].strip()
    result = {
        "metric": "server_pipeline_post_barrier",
        "unit": "x (barrier / stream post-barrier gap, last-reply -> new-global)",
        # Acceptance headline: the speedup on the first (many-leaf) model.
        "value": models[headline]["post_barrier_speedup"],
        "headline_model": headline,
        "num_clients": clients,
        "models": models,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "SERVER_PIPELINE_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _telemetry_microbench():
    """``telemetry_overhead``: what FedConfig.telemetry costs per round.

    Two measurements, reported side by side because only one of them can
    actually resolve the effect:

    - **Attributable cost** (the headline ``value``): the engine's basic
      mode adds EXACTLY one no-op span call and one registry counter
      increment per round; trace mode swaps in a real span. That exact
      per-round instrument sequence is timed directly (tight loop,
      20k iterations) and divided by the off-mode round wall. This is the
      physical overhead, and it is sub-ppm on seconds-scale rounds.
    - **A/B wall times**: the SAME engine instance (one compile, one
      jitted program — the jits never close over the telemetry object,
      which is exactly why it is swappable) drives full FedAvg rounds on
      densenet_cifar (CPU) under off / basic / trace, with the mode order
      rotated every rep so machine drift cannot masquerade as overhead;
      medians reported as ``round_ms`` / ``ab_delta_pct`` next to
      ``noise_floor_pct`` (the off-mode trials' own spread). Differencing
      ~seconds walls with ~1% run-to-run jitter cannot resolve a ~1 us
      effect — two fixed-order runs measured 1.3-1.5% "overhead" that
      rotation reassigned to noise (trace cheaper than basic, which is a
      strict superset) — so the A/B block is the audit trail showing the
      delta sits inside the noise floor, not the estimator.

    A second leg runs a real 2-client/2-round gRPC federation at
    ``telemetry=trace`` with the streaming server pipeline and validates
    the exported Chrome trace: decode/h2d/aggregate spans must carry
    non-negative durations, resolve to a ``round`` root via their
    parent_id chain, and sit inside that round span's [ts, ts+dur] window
    — i.e. the Perfetto view nests the phases under their round. The
    trace itself lands at artifacts/TELEMETRY_TRACE.json.

    Run via ``python bench.py --telemetry-microbench``; prints one JSON
    line and writes ``artifacts/TELEMETRY_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.obs import Telemetry

    model_name = os.environ.get("FEDTPU_TB_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_TB_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_TB_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_TB_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_TB_BATCH", "8"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="off"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)

    def run_block():
        for _ in range(rounds):
            m = fed.step()
        # Fetching a program output is the honest sync point (OPERATIONS
        # rule 4); identical in every mode, so it cancels in the deltas.
        np.asarray(m.loss)

    run_block()  # compile + warmup
    modes = ("off", "basic", "trace")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        # Rotate the mode order each rep: with a FIXED order, any slow
        # machine-wide drift within a rep lands on the same modes every
        # time and reads as fake overhead (measured: ~1.5% phantom basic
        # overhead from ordering alone on 5.8 s densenet rounds, against a
        # ~1 us true per-round cost). Rotation cancels the positional bias.
        for mode in modes[rep % 3:] + modes[: rep % 3]:
            fed.telemetry = Telemetry(mode)
            t0 = time.perf_counter()
            run_block()
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = {
        mode: (med[mode] - med["off"]) / med["off"] * 100.0
        for mode in ("basic", "trace")
    }
    noise_floor_pct = (
        (max(trials["off"]) - min(trials["off"])) / med["off"] * 100.0
    )

    # Attributable cost: time the EXACT per-round instrument sequence the
    # engine adds in each mode (see Federation.step), then scale by the
    # off-mode round wall. This resolves what the A/B differencing cannot.
    n = 20000

    def timed_ops(tel):
        t0 = time.perf_counter()
        for _ in range(n):
            with tel.span("round", round=0):
                pass
            tel.counter("fedtpu_rounds_completed_total", "rounds").inc()
        return (time.perf_counter() - t0) / n * 1e6  # us per round

    per_round_us = {
        "basic": timed_ops(Telemetry("basic")),
        "trace": timed_ops(Telemetry("trace")),
    }
    attributable_pct = {
        mode: us / (med["off"] * 1e6) * 100.0
        for mode, us in per_round_us.items()
    }

    # Raw instrument costs, for the arithmetic's audit trail.
    tel = Telemetry("trace")
    t0 = time.perf_counter()
    for _ in range(n):
        with tel.span("x"):
            pass
    span_ns = (time.perf_counter() - t0) / n * 1e9
    c = tel.counter("c")
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc()
    counter_ns = (time.perf_counter() - t0) / n * 1e9
    h = tel.histogram("h")
    t0 = time.perf_counter()
    for _ in range(n):
        h.observe(0.01)
    hist_ns = (time.perf_counter() - t0) / n * 1e9

    trace_check = _telemetry_trace_leg()

    result = {
        "metric": "telemetry_overhead",
        "unit": "% of round wall time attributable to telemetry=basic "
                "instruments",
        # Headline: the per-round basic-mode instrument cost over the
        # off-mode round wall — the resolvable, physical overhead. The A/B
        # medians + noise floor below show the wall-clock deltas sit
        # inside run-to-run jitter (see docstring).
        "value": round(attributable_pct["basic"], 6),
        "attributable_pct": {
            k: round(v, 6) for k, v in attributable_pct.items()
        },
        "per_round_instrument_us": {
            k: round(v, 3) for k, v in per_round_us.items()
        },
        "ab_delta_pct": {k: round(v, 3) for k, v in ab_delta_pct.items()},
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "instrument_ns": {
            "span_trace_mode": round(span_ns, 1),
            "counter_inc": round(counter_ns, 1),
            "histogram_observe": round(hist_ns, 1),
        },
        "trace_check": trace_check,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "TELEMETRY_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _telemetry_trace_leg():
    """The microbench's trace-validation leg (see _telemetry_microbench)."""
    import socket

    from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig
    from fedtpu.obs import write_chrome_trace
    from fedtpu.transport.federation import PrimaryServer, serve_client

    def free_port():
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    cfg = RoundConfig(
        model="mlp",
        num_classes=10,
        opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
        data=DataConfig(
            dataset="synthetic", batch_size=8, eval_batch_size=8,
            num_examples=256,
        ),
        fed=FedConfig(
            num_clients=2, num_rounds=2, telemetry="trace",
            server_pipeline="stream",
        ),
        steps_per_round=2,
    )
    servers = []
    try:
        addrs = []
        for i in range(2):
            addr = f"localhost:{free_port()}"
            server, _ = serve_client(addr, cfg, seed=i)
            addrs.append(addr)
            servers.append(server)
        primary = PrimaryServer(cfg, addrs)
        for _ in range(2):
            primary.round()
        events = primary.telemetry.trace_events()
        os.makedirs(ARTIFACTS_DIR, exist_ok=True)
        trace_path = os.path.join(ARTIFACTS_DIR, "TELEMETRY_TRACE.json")
        write_chrome_trace(events, trace_path)

        by_id = {e["args"]["span_id"]: e for e in events}

        def root(e):
            while "parent_id" in e["args"]:
                e = by_id[e["args"]["parent_id"]]
            return e

        nested = True
        phase_counts = {}
        for name in ("decode", "h2d", "aggregate"):
            phase_events = [e for e in events if e["name"] == name]
            phase_counts[name] = len(phase_events)
            for e in phase_events:
                r = root(e)
                inside = (
                    r["name"] == "round"
                    and r["ts"] - 1e-3 <= e["ts"]
                    and e["ts"] + e["dur"] <= r["ts"] + r["dur"] + 1e-3
                )
                nested = nested and inside
        return {
            "trace_path": "artifacts/TELEMETRY_TRACE.json",
            "num_events": len(events),
            "rounds": sum(1 for e in events if e["name"] == "round"),
            "phase_span_counts": phase_counts,
            "nonnegative_durations": all(e["dur"] >= 0 for e in events),
            "phases_nest_under_round": nested
            and all(phase_counts[n] > 0 for n in phase_counts),
        }
    finally:
        for s in servers:
            s.stop(0)


def _obs_plane_microbench():
    """``obs_plane_overhead``: what the federation-wide observability plane
    costs per round — trace-context metadata injection/extraction on every
    RPC (fedtpu.obs.propagate) plus the round loop's live status feed
    (StatusBoard updates behind /statusz).

    Same two-measurement methodology as ``--telemetry-microbench`` (PR 3),
    because the effect sizes are again microseconds against seconds-scale
    rounds:

    - **Attributable cost** (the headline ``value``): the EXACT per-round
      obs-plane sequence — one context encode + one metadata extract per
      client RPC, and the round loop's four status-board updates — timed
      directly in a tight loop and scaled by the bare round wall of a
      densenet_cifar CPU round with ``FEDTPU_OB_CLIENTS`` clients.
      Acceptance gate: <= 1% (``gate_pct`` / ``passes_gate``).
    - **A/B walls (audit)**: the same compiled engine driven with and
      without the explicit per-round obs-plane sequence bolted on, mode
      order rotated every rep, medians next to the bare trials' own
      spread (``noise_floor_pct``) — demonstrating the delta sits inside
      run-to-run jitter, exactly like PR 3's phantom-overhead analysis.

    Run via ``python bench.py --obs-plane-microbench``; prints one JSON
    line and writes ``artifacts/OBS_PLANE_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.obs import StatusBoard
    from fedtpu.obs import propagate

    model_name = os.environ.get("FEDTPU_OB_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_OB_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_OB_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_OB_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_OB_BATCH", "8"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="off"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)

    # The per-RPC and per-round sequences under test, shaped exactly like
    # the production path: a realistic context (ids in the range a long run
    # reaches), the real wire key, a real status board.
    ctx = propagate.TraceContext(
        trace_id="a3f1c09d5e7b2468", span_id=123456, role="primary",
        round=10_000,
    )
    wire_md = [("fedtpu-trace-bin", propagate.encode_context(ctx))]
    board = StatusBoard(role="primary", phase="init", round=0)

    def obs_round_sequence(r: int) -> None:
        board.update(round=r, phase="collect")
        for _ in range(clients):
            propagate.from_metadata(
                [("fedtpu-trace-bin", propagate.encode_context(ctx))]
            )
        board.update(phase="aggregate")
        board.update(phase="broadcast")
        board.update(phase="idle")

    def run_block(with_obs: bool):
        for r in range(rounds):
            if with_obs:
                obs_round_sequence(r)
            m = fed.step()
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)

    run_block(False)  # compile + warmup
    modes = ("bare", "obs")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        # Rotate mode order per rep — fixed ordering turns machine drift
        # into phantom overhead (see _telemetry_microbench).
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            t0 = time.perf_counter()
            run_block(mode == "obs")
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["obs"] - med["bare"]) / med["bare"] * 100.0
    noise_floor_pct = (
        (max(trials["bare"]) - min(trials["bare"])) / med["bare"] * 100.0
    )

    # Attributable cost: direct timing of the exact instrument sequences.
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        propagate.encode_context(ctx)
    inject_us = (time.perf_counter() - t0) / n * 1e6
    t0 = time.perf_counter()
    for _ in range(n):
        propagate.from_metadata(wire_md)
    extract_us = (time.perf_counter() - t0) / n * 1e6
    t0 = time.perf_counter()
    for i in range(n):
        board.update(round=i, phase="collect")
        board.update(phase="aggregate")
        board.update(phase="broadcast")
        board.update(phase="idle")
    status_us = (time.perf_counter() - t0) / n * 1e6
    per_round_us = clients * (inject_us + extract_us) + status_us
    attributable_pct = per_round_us / (med["bare"] * 1e6) * 100.0

    result = {
        "metric": "obs_plane_overhead",
        "unit": "% of round wall time attributable to trace propagation + "
                "status feed",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": bool(attributable_pct <= 1.0),
        "per_rpc_us": {
            "inject": round(inject_us, 3),
            "extract": round(extract_us, 3),
        },
        "per_round_status_us": round(status_us, 3),
        "per_round_obs_us": round(per_round_us, 3),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "OBS_PLANE_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _chaos_overhead_microbench():
    """``chaos_overhead``: what an ARMED-but-quiet fault-injection schedule
    costs per round — the per-RPC ``FaultSchedule.decide`` consult the
    chaos interceptors add to every outbound call even when no rule fires
    (rules with ``p=0`` or non-matching RPCs). This is the no-op path the
    acceptance gate cares about: a chaos layer you can leave compiled into
    the binary must be free when idle.

    Same two-measurement methodology as ``--obs-plane-microbench``:

    - **Attributable cost** (the headline ``value``): the exact per-RPC
      consult — one armed schedule with a never-firing rule and a
      non-matching rule, decided once per client RPC (StartTrain +
      SendModel per client per round) — timed directly in a tight loop and
      scaled by the bare round wall of a densenet_cifar CPU round.
      Acceptance gate: <= 1% (``gate_pct`` / ``passes_gate``).
    - **A/B walls (audit)**: the same compiled engine driven with and
      without the per-round consult sequence bolted on, mode order rotated
      per rep, medians next to the bare trials' spread
      (``noise_floor_pct``).

    Run via ``python bench.py --chaos-overhead-microbench``; prints one
    JSON line and writes ``artifacts/CHAOS_OVERHEAD_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.ft.chaos import parse_spec

    model_name = os.environ.get("FEDTPU_CH_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_CH_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_CH_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_CH_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_CH_BATCH", "8"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="off"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)

    # Armed but quiet: one rule that can match but never fires (p=0) and
    # one keyed to an RPC the consult below never asks about — the
    # worst-case no-op consult (both rules walked per call).
    schedule = parse_spec("error@StartTrain:p=0.0,seed=7;delay@FetchModel:p=1.0")

    def chaos_round_sequence(r: int) -> None:
        schedule.set_round(r)
        for i in range(clients):
            schedule.decide("StartTrain", f"localhost:5005{i}")
            schedule.decide("SendModel", f"localhost:5005{i}")

    def run_block(with_chaos: bool):
        for r in range(rounds):
            if with_chaos:
                chaos_round_sequence(r)
            m = fed.step()
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)

    run_block(False)  # compile + warmup
    modes = ("bare", "chaos")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            t0 = time.perf_counter()
            run_block(mode == "chaos")
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["chaos"] - med["bare"]) / med["bare"] * 100.0
    noise_floor_pct = (
        (max(trials["bare"]) - min(trials["bare"])) / med["bare"] * 100.0
    )

    # Attributable cost: direct timing of the exact per-RPC consult.
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        schedule.decide("StartTrain", "localhost:50051")
    decide_us = (time.perf_counter() - t0) / n * 1e6
    per_round_us = clients * 2 * decide_us  # StartTrain + SendModel each
    attributable_pct = per_round_us / (med["bare"] * 1e6) * 100.0

    result = {
        "metric": "chaos_overhead",
        "unit": "% of round wall time attributable to the armed no-op "
                "fault-injection consult",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": bool(attributable_pct <= 1.0),
        "per_rpc_us": {"decide": round(decide_us, 3)},
        "per_round_chaos_us": round(per_round_us, 3),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "CHAOS_OVERHEAD_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _fencing_overhead_microbench():
    """``fencing_overhead``: what coordinator-epoch fencing costs per round
    — the epoch a coordinator injects into every outbound RPC plus the
    receiver-side fence validation (decode the epoch back out, compare it
    against the max seen under a lock, adopt or reject; mirrors
    ``ClientAgent._fence_check``). Fencing is the split-brain eliminator
    (docs/FAULT_TOLERANCE.md §Fencing); it runs on EVERY StartTrain /
    SendModel / replica push / liveness ping, so it must be free on the
    steady-state path.

    Same two-measurement methodology as ``--chaos-overhead-microbench``:

    - **Attributable cost** (the headline ``value``): the exact per-RPC
      inject+validate — encode an epoch-bearing request, decode it,
      locked compare-and-adopt — timed directly in a tight loop and
      scaled by the per-round RPC multiplicity (StartTrain + SendModel
      per client, plus the backup ping and the replica push) over the
      bare round wall of a densenet_cifar CPU round. Deliberately an
      over-count: the whole encode/decode is charged to fencing, not
      just the marginal two varint fields. Acceptance gate: <= 1%
      (``gate_pct`` / ``passes_gate``).
    - **A/B walls (audit)**: the same compiled engine driven with and
      without the per-round inject+validate sequence bolted on, mode
      order rotated per rep, medians next to the bare trials' spread
      (``noise_floor_pct``).

    Run via ``python bench.py --fencing-overhead-microbench``; prints one
    JSON line and writes ``artifacts/FENCING_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import threading

    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.transport import proto

    model_name = os.environ.get("FEDTPU_FE_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_FE_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_FE_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_FE_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_FE_BATCH", "8"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="off"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)

    # Receiver-side fence state, mirroring ClientAgent._fence_check: max
    # epoch seen, updated/compared under a lock on every validation.
    fence_lock = threading.Lock()
    epoch_seen = [41]

    def fence_rpc(epoch: int) -> bool:
        # Sender side: inject the epoch into the request bytes; receiver
        # side: decode it back out and run the locked fence compare.
        wire = proto.TrainRequest(
            rank=1, world=clients, round=7, epoch=epoch
        ).encode()
        req = proto.TrainRequest.decode(wire)
        with fence_lock:
            if req.epoch >= epoch_seen[0]:
                epoch_seen[0] = req.epoch
                return True
        return False

    # StartTrain + SendModel per client, plus the backup liveness ping and
    # the replica push — every fenced RPC a synchronous round issues.
    rpcs_per_round = clients * 2 + 2

    def fencing_round_sequence(r: int) -> None:
        for _ in range(rpcs_per_round):
            fence_rpc(42)

    def run_block(with_fencing: bool):
        for r in range(rounds):
            if with_fencing:
                fencing_round_sequence(r)
            m = fed.step()
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)

    run_block(False)  # compile + warmup
    modes = ("bare", "fenced")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            t0 = time.perf_counter()
            run_block(mode == "fenced")
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["fenced"] - med["bare"]) / med["bare"] * 100.0
    noise_floor_pct = (
        (max(trials["bare"]) - min(trials["bare"])) / med["bare"] * 100.0
    )

    # Attributable cost: direct timing of the exact per-RPC op.
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        fence_rpc(42)
    inject_validate_us = (time.perf_counter() - t0) / n * 1e6
    per_round_us = rpcs_per_round * inject_validate_us
    attributable_pct = per_round_us / (med["bare"] * 1e6) * 100.0

    result = {
        "metric": "fencing_overhead",
        "unit": "% of round wall time attributable to the per-RPC "
                "coordinator-epoch inject + fence validation",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": bool(attributable_pct <= 1.0),
        "per_rpc_us": {"inject_validate": round(inject_validate_us, 3)},
        "rpcs_per_round": rpcs_per_round,
        "per_round_fencing_us": round(per_round_us, 3),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "FENCING_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _checkpoint_overhead_microbench():
    """``checkpoint_overhead``: what per-round durable checkpointing costs
    the ROUND LOOP under the background writer
    (:class:`fedtpu.checkpoint.BackgroundCheckpointer`). The loop-side
    work is only the device->host state snapshot + queue handoff; the
    encode + fsync'd atomic write + manifest + verify + prune run on the
    writer thread, overlapped with the next round's compute. Acceptance
    gate of the durability PR: the loop-side cost must be <= 1% of a
    densenet_cifar CPU round at checkpoint-every-round cadence.

    Same two-measurement methodology as ``--chaos-overhead-microbench``:

    - **Attributable cost** (the headline ``value``): the exact
      ``save()`` call the round loop makes, timed directly with the
      writer idle before each call (flush between timed saves, flush time
      excluded) and scaled by the bare round wall. The synchronous path's
      full inline save (``sync_full``) and the writer-side write wall
      (``writer_write``, from ``fedtpu_checkpoint_write_seconds``) ride
      along, so the artifact shows exactly what the background split
      buys.
    - **A/B walls (audit)**: the same compiled engine driven with and
      without a per-round background save (final flush inside the timed
      block — an upper bound on steady-state), mode order rotated per
      rep, medians next to the bare trials' spread (``noise_floor_pct``).

    Run via ``python bench.py --checkpoint-overhead-microbench``; prints
    one JSON line and writes ``artifacts/CHECKPOINT_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import shutil
    import tempfile

    import numpy as np

    from fedtpu.checkpoint import BackgroundCheckpointer, Checkpointer
    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.obs import MetricsRegistry

    model_name = os.environ.get("FEDTPU_CK_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_CK_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_CK_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_CK_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_CK_BATCH", "8"))
    timed_saves = int(os.environ.get("FEDTPU_CK_SAVES", "10"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="off"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)
    workdir = tempfile.mkdtemp(prefix="fedtpu_ckpt_mb_")
    reg = MetricsRegistry()
    inner = Checkpointer(
        os.path.join(workdir, "async"), keep=3, backend="wire", metrics=reg,
    )
    bg = BackgroundCheckpointer(inner)
    sync_ckpt = Checkpointer(
        os.path.join(workdir, "sync"), keep=3, backend="wire",
    )

    def run_block(with_ckpt: bool, base: int = 0):
        for r in range(rounds):
            m = fed.step()
            if with_ckpt:
                bg.save(base + r, fed.state)
        if with_ckpt:
            bg.flush()
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)

    run_block(False)  # compile + warmup
    run_block(True, base=10_000)  # warm the writer path too
    modes = ("bare", "ckpt")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            t0 = time.perf_counter()
            run_block(mode == "ckpt", base=20_000 + rep * rounds)
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["ckpt"] - med["bare"]) / med["bare"] * 100.0
    noise_floor_pct = (
        (max(trials["bare"]) - min(trials["bare"])) / med["bare"] * 100.0
    )

    # Attributable cost: the exact loop-side call, writer idle each time.
    save_walls = []
    for i in range(timed_saves):
        bg.flush()
        t0 = time.perf_counter()
        bg.save(30_000 + i, fed.state)
        save_walls.append(time.perf_counter() - t0)
    bg.flush()
    async_call_ms = sorted(save_walls)[len(save_walls) // 2] * 1e3
    # The synchronous contrast: one full inline save (encode + fsync'd
    # write + verify + prune) on the loop.
    sync_walls = []
    for i in range(timed_saves):
        t0 = time.perf_counter()
        sync_ckpt.save(i, fed.state)
        sync_walls.append(time.perf_counter() - t0)
    sync_full_ms = sorted(sync_walls)[len(sync_walls) // 2] * 1e3
    hist = reg.histogram("fedtpu_checkpoint_write_seconds", "")
    writer_write_ms = (hist.sum / max(hist.count, 1)) * 1e3
    state_bytes = (inner.last_save or {}).get("bytes", 0)
    attributable_pct = (async_call_ms / 1e3) / med["bare"] * 100.0

    bg.close()
    shutil.rmtree(workdir, ignore_errors=True)
    result = {
        "metric": "checkpoint_overhead",
        "unit": "% of round wall time attributable to the round-loop side "
                "of one background checkpoint save per round",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": bool(attributable_pct <= 1.0),
        "per_save_ms": {
            "async_call": round(async_call_ms, 3),
            "sync_full": round(sync_full_ms, 3),
            "writer_write": round(writer_write_ms, 3),
        },
        "checkpoint_bytes": int(state_bytes),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "timed_saves": timed_saves,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "CHECKPOINT_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _screening_overhead_microbench():
    """``screening_overhead``: what the fused Byzantine screening stage
    (:func:`fedtpu.ops.flat.screen_rows` — per-row L2 norm, cosine to the
    median direction, median/MAD z-score, all one jitted program) costs per
    round. The acceptance gate of the Byzantine PR: screening must ride
    the default fast path at <= 1% of round wall time — it runs on the
    SAME device-resident ``[clients, P]`` buffer the stream finalize reads,
    so the only new work is the one fused stats pass measured here.

    Same two-measurement methodology as ``--chaos-overhead-microbench``:

    - **Attributable cost** (the headline ``value``): the fused screening
      pass over a ``[clients, P]`` buffer of the headline model's real
      padded row width, timed directly (device-synced per call) and scaled
      by the bare round wall. Gate: <= 1% (``gate_pct``/``passes_gate``).
    - **A/B walls (audit)**: the same engine config compiled with
      screening off vs armed (thresholds set loose so no row is ever
      rejected — the verdict math runs, the trajectory is unchanged),
      mode order rotated per rep, medians next to the bare trials' spread
      (``noise_floor_pct``).

    Run via ``python bench.py --screening-overhead-microbench``; prints one
    JSON line and writes ``artifacts/SCREENING_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig, ScreenConfig
    from fedtpu.core.engine import Federation
    from fedtpu.ops import flat as flat_ops

    model_name = os.environ.get("FEDTPU_SC_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_SC_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_SC_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_SC_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_SC_BATCH", "8"))

    def make_cfg(screen):
        return RoundConfig(
            model=model_name,
            num_classes=10,
            data=DataConfig(
                dataset="cifar10", batch_size=batch, partition="iid",
                num_examples=clients * batch * 4,
            ),
            fed=FedConfig(
                num_clients=clients, telemetry="off", screen=screen,
            ),
            steps_per_round=1,
        )

    # Armed-but-lenient: every check runs, nothing is ever rejected, so
    # the A/B trajectories stay comparable.
    armed = ScreenConfig(norm_max=1e30, zmax=1e6, cos_min=-1.0)
    bare_fed = Federation(make_cfg(ScreenConfig()), seed=0)
    screen_fed = Federation(make_cfg(armed), seed=0)

    def run_block(fed):
        for _ in range(rounds):
            m = fed.step()
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)

    run_block(bare_fed)  # compile + warmup
    run_block(screen_fed)
    modes = ("bare", "screen")
    feds = {"bare": bare_fed, "screen": screen_fed}
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            t0 = time.perf_counter()
            run_block(feds[mode])
            trials[mode].append((time.perf_counter() - t0) / rounds)
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["screen"] - med["bare"]) / med["bare"] * 100.0
    noise_floor_pct = (
        (max(trials["bare"]) - min(trials["bare"])) / med["bare"] * 100.0
    )

    # Attributable cost: the exact fused screening pass over the model's
    # real padded row width, timed directly with a device sync per call.
    layout = flat_ops.make_layout(bare_fed.state.params)
    rng = np.random.default_rng(0)
    rows = jnp.asarray(
        rng.normal(size=(clients, layout.padded)).astype(np.float32)
    )
    live = jnp.ones((clients,), jnp.float32)
    screen_fn = jax.jit(
        lambda r, a: flat_ops.screen_rows(
            r, a, armed.norm_max, armed.zmax, armed.cos_min
        )
    )
    jax.block_until_ready(screen_fn(rows, live))  # compile
    n = 50
    t0 = time.perf_counter()
    for _ in range(n):
        keep, _ = screen_fn(rows, live)
    jax.block_until_ready(keep)
    screen_us = (time.perf_counter() - t0) / n * 1e6
    attributable_pct = screen_us / (med["bare"] * 1e6) * 100.0

    result = {
        "metric": "screening_overhead",
        "unit": "% of round wall time attributable to the fused "
                "screening pass",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": bool(attributable_pct <= 1.0),
        "per_round_screen_us": round(screen_us, 3),
        "padded_row": int(layout.padded),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "SCREENING_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


ARTIFACTS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "artifacts")


def _cohort_scale():
    """``cohort_scale``: clients-per-round vs round wall-clock for the
    massive-cohort simulation engine (fedtpu.sim), on one host.

    For a fixed simulated POPULATION, sweeps the per-round COHORT size
    through the fused ``lax.scan`` engine and records, per point, the
    round wall time and the device-side per-seat state footprint. Two
    claims are made auditable:

    - **scale**: the largest cohort actually runs (default sweep tops out
      at 10k simulated clients in one round on this host);
    - **O(cohort) device memory**: per-seat state bytes grow with the
      cohort and are INDEPENDENT of the population — the same cohort is
      re-measured at half the population and must report identical bytes
      (``memory_model.o_cohort``). The population's only footprint is
      host-side numpy tables (reported as ``host_table_bytes``).

    Env knobs (shrunk by tests/test_bench.py): FEDTPU_CS_MODEL,
    FEDTPU_CS_POPULATION, FEDTPU_CS_COHORTS, FEDTPU_CS_ROUNDS,
    FEDTPU_CS_BATCH, FEDTPU_CS_STEPS, FEDTPU_CS_SCENARIO.

    Run via ``python bench.py --cohort-scale``; prints one JSON line and
    writes ``artifacts/COHORT_SCALE.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import (
        DataConfig, FedConfig, OptimizerConfig, RoundConfig, SimConfig,
    )
    from fedtpu.sim import SimFederation

    model_name = os.environ.get("FEDTPU_CS_MODEL", "mlp_tiny")
    population = int(os.environ.get("FEDTPU_CS_POPULATION", "10000"))
    cohorts = [
        int(c)
        for c in os.environ.get(
            "FEDTPU_CS_COHORTS", "64,256,1024,4096,10000"
        ).split(",")
    ]
    rounds = int(os.environ.get("FEDTPU_CS_ROUNDS", "2"))
    batch = int(os.environ.get("FEDTPU_CS_BATCH", "8"))
    steps = int(os.environ.get("FEDTPU_CS_STEPS", "1"))
    scenario = os.environ.get(
        "FEDTPU_CS_SCENARIO", "dirichlet:alpha=0.3+quantity_skew:power=1.2"
    )
    num_examples = int(
        os.environ.get("FEDTPU_CS_EXAMPLES", str(max(2 * population, 1000)))
    )

    def make_cfg(cohort: int) -> RoundConfig:
        return RoundConfig(
            model=model_name,
            num_classes=10,
            opt=OptimizerConfig(learning_rate=0.05, weight_decay=0.0),
            data=DataConfig(
                dataset="synthetic", batch_size=batch, partition="iid",
                num_examples=num_examples, device_layout="gather",
            ),
            fed=FedConfig(
                num_clients=cohort,
                sim=SimConfig(population=population, scenario=scenario),
            ),
            steps_per_round=steps,
        )

    def seat_state_bytes(fed, cohort: int) -> int:
        """Device bytes of per-seat STATE — the exact footprint the
        O(cohort) claim is about: the fields FederatedState stacks along
        the clients axis (momentum, compressor residuals, PRNG keys, loss
        observations). Global fields (params, batch stats, server-opt
        moments) are excluded by construction, not by shape heuristics —
        a param leaf's first dim can coincide with the cohort. The
        assignment rows are reported separately: they are
        O(cohort * shard_len) where shard_len is the partition's padded
        max shard, which varies with the partition draw."""
        per_seat = (
            fed.state.opt_state,
            fed.state.comp_state,
            fed.state.client_rng,
            fed.state.last_client_loss,
        )
        total = 0
        for leaf in jax.tree_util.tree_leaves(per_seat):
            assert leaf.shape[0] == cohort, leaf.shape
            total += leaf.size * leaf.dtype.itemsize
        return int(total)

    def measure(cohort: int, pop: int) -> dict:
        import dataclasses

        cfg = make_cfg(cohort)
        if pop != population:
            cfg = dataclasses.replace(
                cfg,
                fed=dataclasses.replace(
                    cfg.fed,
                    sim=dataclasses.replace(cfg.fed.sim, population=pop),
                ),
            )
        fed = SimFederation(cfg, seed=0)
        m = fed.run_on_device(1)  # compile + warmup
        np.asarray(m.loss)  # honest sync point (OPERATIONS rule 4)
        t0 = time.perf_counter()
        m = fed.run_on_device(rounds)
        np.asarray(m.loss)
        dt = (time.perf_counter() - t0) / rounds
        pop_tables = fed.population
        host_bytes = int(
            pop_tables.idx.nbytes + pop_tables.mask.nbytes
            + pop_tables.last_seen_loss.nbytes
            + pop_tables.last_sampled_round.nbytes
            + pop_tables.times_sampled.nbytes
        )
        clients = int(fed.alive.sum())
        return {
            "cohort": cohort,
            "population": pop,
            "clients_per_round": clients,
            "round_s": round(dt, 4),
            "clients_per_sec": round(clients / max(dt, 1e-9), 2),
            "seat_state_bytes": seat_state_bytes(fed, cohort),
            "assignment_bytes": int(
                fed.client_idx.nbytes + fed.client_mask.nbytes
            ),
            "host_table_bytes": host_bytes,
            "heterogeneity_index": round(fed._hetero, 4),
        }

    curve = [measure(c, population) for c in cohorts]
    # O(cohort) proof: the SAME cohort at half the population must hold
    # byte-identical seat state (population only grows host tables).
    probe_cohort = cohorts[0]
    half = measure(probe_cohort, max(probe_cohort, population // 2))
    at_full = next(p for p in curve if p["cohort"] == probe_cohort)
    result = {
        "metric": "cohort_scale",
        "unit": "simulated clients per round (device memory O(cohort))",
        "value": max(p["clients_per_round"] for p in curve),
        "population": population,
        "scenario": scenario,
        "model": model_name,
        "batch": batch,
        "steps_per_round": steps,
        "rounds_per_point": rounds,
        "curve": curve,
        "memory_model": {
            "cohort": probe_cohort,
            "seat_state_bytes_full_population": at_full["seat_state_bytes"],
            "seat_state_bytes_half_population": half["seat_state_bytes"],
            "o_cohort": at_full["seat_state_bytes"]
            == half["seat_state_bytes"],
        },
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "COHORT_SCALE.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _live_artifact_pointer():
    """Most recent builder-captured live measurement, if any — attached to
    DIAGNOSTIC (value 0.0) outputs only, so a wedged-tunnel bench moment
    still records where this round's measured number lives. Never used as
    the reported value: the driver's number must be the driver's run."""
    art = ARTIFACTS_DIR
    best = None
    try:
        names = sorted(os.listdir(art))
    except OSError:
        return None
    for name in names:
        if not (name.startswith("BENCH_LIVE_") and name.endswith(".json")):
            continue
        # Per-file guard: a capture killed mid-write (the wedge scenario this
        # pointer exists for) can leave one truncated artifact, and nothing
        # stops a writer emitting null/odd-typed fields — skip such files,
        # never lose the pointer to the valid ones.
        try:
            with open(os.path.join(art, name)) as f:
                data = json.load(f)
            if not (isinstance(data, dict) and data.get("value", 0) > 0):
                continue
            stamp = str(data.get("captured_at") or "")
            if best is None or stamp >= best[2]:
                best = (name, data, stamp)
        except (OSError, ValueError, TypeError):
            continue
    if best is None:
        return None
    name, data, _ = best
    return {
        "live_artifact": f"artifacts/{name}",
        "live_value": data.get("value"),
        "live_unit": data.get("unit"),
        "live_captured_at": data.get("captured_at"),
        "live_device_kind": data.get("device_kind"),
    }


def _salvage_json(text: str):
    """Last line of ``text`` that parses as a JSON object, or None. Guards
    against truncated lines from a killed child being shipped as the
    artifact."""
    for line in reversed((text or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                json.loads(line)
            except ValueError:
                continue
            return line
    return None


def _mfu_profile():
    """``--mfu-profile``: the MFU/roofline batch sweep as one command.

    Unifies the hand-run ``tools/bench_profile_tpu.py`` flow (the
    ``artifacts/MFU_PROFILE_r04*.json`` series was produced by invoking
    that script over the tunnel by hand) behind the bench entrypoint, so
    the artifact is reproducible from ``python bench.py --mfu-profile``
    with the same knobs: ``FEDTPU_PROFILE_TAG`` names the artifact
    (default ``r04``), ``FEDTPU_SMOKE=1`` shrinks shapes for off-chip
    smoke runs, ``FEDTPU_PLATFORM`` pins the backend. The sweep itself —
    fused multi-round dispatch timing, XLA cost analysis, roofline
    placement via ``fedtpu.obs.profile.device_peaks``/``roofline``, one
    traced dispatch — lives in tools/bench_profile_tpu.py; this wrapper
    imports and runs it, returning the artifact dict (schema contract
    pinned by tests/test_bench.py).
    """
    import importlib

    tools_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools"
    )
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    import bench_profile_tpu

    # The sweep constants (FEDTPU_SMOKE shrink) are bound at module import;
    # reload so env knobs set after a first in-process import still apply.
    bench_profile_tpu = importlib.reload(bench_profile_tpu)
    return bench_profile_tpu.run()


def _mfu_microbench():
    """``--mfu-microbench``: is continuous MFU accounting ≤1% of a round?

    The performance observatory stamps every round with step-time /
    achieved-FLOPs / MFU (``Federation.enable_mfu_accounting`` →
    ``RoundProfiler.observe_round`` + ``record_fields``). The acceptance
    gate is that this accounting costs at most 1% of a round. Same
    estimator discipline as ``--telemetry-microbench``:

    - **Attributable cost** (headline ``value``): the EXACT per-round
      sequence the engine adds — one ``observe_round`` (3 gauge sets +
      arithmetic) and one ``record_fields`` — timed in a tight loop and
      divided by the bare round wall. The one-time cost-model build
      (jaxpr trace, optionally an AOT compile) is reported separately as
      ``cost_model_build_s``; it is setup, not per-round cost.
    - **A/B walls**: the same engine instance drives full rounds with
      ``fed.profiler`` toggled off/on, order rotated per rep, medians +
      the off-mode noise floor as the audit trail that the wall-clock
      delta sits inside jitter.

    Env knobs: FEDTPU_MF_MODEL / _CLIENTS / _ROUNDS / _REPS / _BATCH.
    Prints one JSON line, writes artifacts/MFU_ACCOUNTING_MICROBENCH.json.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig

    # A peak-FLOPs override so the CPU backend exercises the FULL per-round
    # sequence (achieved-FLOPs + MFU gauges, not the None early-outs).
    os.environ.setdefault("FEDTPU_PEAK_FLOPS", "1e12")
    from fedtpu.core.engine import Federation

    model_name = os.environ.get("FEDTPU_MF_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_MF_CLIENTS", "2"))
    rounds = int(os.environ.get("FEDTPU_MF_ROUNDS", "3"))
    reps = int(os.environ.get("FEDTPU_MF_REPS", "5"))
    batch = int(os.environ.get("FEDTPU_MF_BATCH", "8"))

    cfg = RoundConfig(
        model=model_name,
        num_classes=10,
        data=DataConfig(
            dataset="cifar10", batch_size=batch, partition="iid",
            num_examples=clients * batch * 4,
        ),
        fed=FedConfig(num_clients=clients, telemetry="basic"),
        steps_per_round=1,
    )
    fed = Federation(cfg, seed=0)

    def run_block():
        for _ in range(rounds):
            m = fed.step()
        np.asarray(m.loss)  # honest sync: fetch a program output

    run_block()  # compile + warmup
    t0 = time.perf_counter()
    fed.enable_mfu_accounting(xla_check=False)
    cost_model_build_s = time.perf_counter() - t0
    profiler = fed.profiler

    modes = ("off", "mfu")
    trials = {mode: [] for mode in modes}
    for rep in range(reps):
        # Rotate mode order per rep so machine-wide drift cannot read as
        # overhead (see _telemetry_microbench for the measured rationale).
        for mode in modes if rep % 2 == 0 else modes[::-1]:
            fed.profiler = profiler if mode == "mfu" else None
            t0 = time.perf_counter()
            run_block()
            trials[mode].append((time.perf_counter() - t0) / rounds)
    fed.profiler = profiler
    med = {mode: sorted(ts)[len(ts) // 2] for mode, ts in trials.items()}
    ab_delta_pct = (med["mfu"] - med["off"]) / med["off"] * 100.0
    noise_floor_pct = (
        (max(trials["off"]) - min(trials["off"])) / med["off"] * 100.0
    )

    # Attributable cost: the exact per-round accounting sequence the engine
    # adds (Federation.step observe_round + the run loop's record_fields),
    # scaled by the bare round wall.
    n = 20000
    wall = med["off"]
    t0 = time.perf_counter()
    for _ in range(n):
        profiler.observe_round(wall)
        profiler.record_fields()
    per_round_us = (time.perf_counter() - t0) / n * 1e6
    attributable_pct = per_round_us / (med["off"] * 1e6) * 100.0

    sample = profiler.observe_round(med["off"])
    result = {
        "metric": "mfu_accounting_overhead",
        "unit": "% of round wall time attributable to per-round MFU "
                "accounting",
        "value": round(attributable_pct, 6),
        "gate_pct": 1.0,
        "passes_gate": attributable_pct <= 1.0,
        "per_round_accounting_us": round(per_round_us, 3),
        "cost_model_build_s": round(cost_model_build_s, 3),
        "flops_per_round": profiler.cost.flops if profiler.cost else None,
        "flops_source": profiler.cost.source if profiler.cost else None,
        "sample_mfu": sample.get("mfu"),
        "ab_delta_pct": round(ab_delta_pct, 3),
        "noise_floor_pct": round(noise_floor_pct, 3),
        "round_ms": {mode: round(t * 1e3, 3) for mode, t in med.items()},
        "model": model_name,
        "num_clients": clients,
        "rounds_per_trial": rounds,
        "reps": reps,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "MFU_ACCOUNTING_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _mixed_precision_microbench():
    """``--mixed-precision-microbench``: the fast-path levers, A/B'd off-chip.

    Three modes of the SAME round program — ``f32`` (parity),
    ``bf16_mixed`` (``FedConfig.compute_dtype='bfloat16_mixed'``) and
    ``bf16_megabatch`` (bf16 plus ``megabatch_clients``) — measured two
    ways:

    - **analytic** (the headline ``value``): per-round FLOPs and
      bytes-accessed from XLA cost analysis of the AOT-compiled fused round
      program at the PROFILE shape (densenet_cifar, batch 128, 6 steps —
      the config behind ``artifacts/MFU_PROFILE_r04*.json``; client count
      reduced for CPU compile tractability, stamped in the artifact), plus
      roofline placement against the headline chip's peaks
      (``fedtpu.obs.profile.device_peaks``). ``value`` is the
      f32→bf16+megabatch bytes_per_round drop — the ISSUE-13 acceptance
      gate is ≥1.8x.
    - **walls**: host wall-clock A/B at a seconds-scale config, mode order
      rotated per rep, medians + the f32-mode noise floor. CPU walls are
      an honesty check that the modes RUN, not a TPU speedup predictor —
      CPUs emulate bf16, so the measured on-chip numbers live in
      ``artifacts/BENCH_LIVE_r04_bf16.json`` and the queued
      ``tools/tpu_watch.py`` leg.

    Env knobs (shrunk by tests/test_bench.py): FEDTPU_MP_MODEL / _CLIENTS /
    _MEGABATCH / _COST_BATCH / _COST_STEPS / _BATCH / _ROUNDS / _REPS /
    _PLACEMENT_DEVICE. Run via ``python bench.py
    --mixed-precision-microbench``; prints one JSON line and writes
    ``artifacts/MIXED_PRECISION_MICROBENCH.json``.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from fedtpu.config import DataConfig, FedConfig, RoundConfig
    from fedtpu.core.engine import Federation
    from fedtpu.obs.profile import device_peaks, engine_cost_model, roofline

    model_name = os.environ.get("FEDTPU_MP_MODEL", "densenet_cifar")
    clients = int(os.environ.get("FEDTPU_MP_CLIENTS", "8"))
    mega = int(os.environ.get("FEDTPU_MP_MEGABATCH", "0")) or clients
    cost_batch = int(os.environ.get("FEDTPU_MP_COST_BATCH", "128"))
    cost_steps = int(os.environ.get("FEDTPU_MP_COST_STEPS", "6"))
    batch = int(os.environ.get("FEDTPU_MP_BATCH", "8"))
    rounds = int(os.environ.get("FEDTPU_MP_ROUNDS", "2"))
    reps = int(os.environ.get("FEDTPU_MP_REPS", "3"))
    # Roofline placement chip: the headline bench fleet (v5e; the committed
    # MFU_PROFILE_r04 ridge point 240 flops/byte comes from its peaks).
    placement = os.environ.get("FEDTPU_MP_PLACEMENT_DEVICE", "v5e")

    modes = (
        ("f32", "float32", 0),
        ("bf16_mixed", "bfloat16_mixed", 0),
        ("bf16_megabatch", "bfloat16_mixed", mega),
    )

    def make_cfg(compute_dtype, megabatch, batch_size, steps):
        return RoundConfig(
            model=model_name,
            num_classes=10,
            data=DataConfig(
                dataset="cifar10", batch_size=batch_size, partition="iid",
                num_examples=clients * steps * batch_size,
            ),
            fed=FedConfig(
                num_clients=clients, telemetry="off",
                compute_dtype=compute_dtype, megabatch_clients=megabatch,
            ),
            steps_per_round=steps,
        )

    peak_f, peak_b = device_peaks(placement)
    analytic = {}
    for name, cd, mb in modes:
        fed = Federation(make_cfg(cd, mb, cost_batch, cost_steps), seed=0)
        # bytes_per_round is the backend-independent jaxpr aval model
        # (obs.profile.analytic_bytes): the CPU backend's cost_analysis
        # bytes describe bf16 EMULATION (f32 upconverts), inverting the
        # dtype lever this artifact exists to predict. The CPU-XLA figure
        # rides along as the audit trail.
        cost = engine_cost_model(fed, xla_check=True)
        analytic[name] = {
            "flops_per_round": cost.flops,
            "bytes_per_round": cost.analytic_bytes,
            "xla_bytes_cpu": cost.xla_bytes,
            "flops_source": cost.source,
            **roofline(cost.flops, cost.analytic_bytes, peak_f, peak_b),
        }
        del fed

    b_f32 = analytic["f32"]["bytes_per_round"]
    b_fast = analytic["bf16_megabatch"]["bytes_per_round"]
    bytes_drop = round(b_f32 / b_fast, 3) if b_f32 and b_fast else None
    b_bf16 = analytic["bf16_mixed"]["bytes_per_round"]

    feds = {
        name: Federation(make_cfg(cd, mb, batch, 1), seed=0)
        for name, cd, mb in modes
    }

    def run_block(fed):
        m = fed.run_on_device(rounds)
        np.asarray(m.loss)  # honest sync: fetch a program output

    for fed in feds.values():
        run_block(fed)  # compile + warmup
    order = tuple(feds)
    trials = {name: [] for name in order}
    for rep in range(reps):
        # Rotate mode order per rep so machine-wide drift cannot read as a
        # mode delta (see _telemetry_microbench for the measured rationale).
        for name in order if rep % 2 == 0 else order[::-1]:
            t0 = time.perf_counter()
            run_block(feds[name])
            trials[name].append((time.perf_counter() - t0) / rounds)
    med = {name: sorted(ts)[len(ts) // 2] for name, ts in trials.items()}
    noise_floor_pct = (
        (max(trials["f32"]) - min(trials["f32"])) / med["f32"] * 100.0
    )

    result = {
        "metric": "mixed_precision_bytes_drop",
        "unit": "x reduction in analytic bytes_per_round, f32 -> "
                "bf16_mixed+megabatch",
        "value": bytes_drop,
        "gate_x": 1.8,
        "passes_gate": bool(bytes_drop and bytes_drop >= 1.8),
        "analytic": analytic,
        "bytes_drop_bf16_only": (
            round(b_f32 / b_bf16, 3) if b_f32 and b_bf16 else None
        ),
        "flops_ratio_fast_vs_f32": (
            round(
                analytic["bf16_megabatch"]["flops_per_round"]
                / analytic["f32"]["flops_per_round"], 3,
            )
            if analytic["f32"]["flops_per_round"]
            and analytic["bf16_megabatch"]["flops_per_round"] else None
        ),
        "analytic_config": {
            "model": model_name, "num_clients": clients,
            "batch": cost_batch, "steps_per_round": cost_steps,
            "megabatch_clients": mega, "placement_device": placement,
            "peak_flops": peak_f, "peak_hbm_bytes_per_s": peak_b,
        },
        "walls": {
            "round_ms": {n: round(t * 1e3, 3) for n, t in med.items()},
            "noise_floor_pct": round(noise_floor_pct, 3),
            "config": {"batch": batch, "rounds_per_trial": rounds,
                       "reps": reps},
            "note": "CPU walls prove the modes run; bf16 is emulated on "
                    "CPU, so TPU speedups come from the live artifacts",
        },
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "MIXED_PRECISION_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _predicted_roofline_pointer():
    """Predicted roofline delta of the fast-path defaults, read from the
    committed mixed-precision microbench artifact — attached to DIAGNOSTIC
    (value 0.0) outputs next to the ``live_*`` fallback, so an
    unreachable-backend stretch shows the expected trajectory (analytic
    bytes_per_round from fedtpu.obs.profile) instead of a flat zero.
    Prediction, never measurement: the keys are namespaced ``predicted_*``
    and the value stays 0.0."""
    path = os.path.join(ARTIFACTS_DIR, "MIXED_PRECISION_MICROBENCH.json")
    try:
        with open(path) as f:
            data = json.load(f)
        analytic = data.get("analytic") or {}
        f32 = analytic.get("f32") or {}
        fast = analytic.get("bf16_megabatch") or {}
        if not (f32.get("bytes_per_round") and fast.get("bytes_per_round")):
            return None
        return {
            "predicted_artifact": "artifacts/MIXED_PRECISION_MICROBENCH.json",
            "predicted_bytes_per_round_f32": f32["bytes_per_round"],
            "predicted_bytes_per_round_fast": fast["bytes_per_round"],
            "predicted_bytes_drop": data.get("value"),
            "predicted_arith_intensity_fast": fast.get(
                "arith_intensity_flops_per_byte"
            ),
            "predicted_roofline_bound_fast": fast.get("roofline_bound"),
        }
    except (OSError, ValueError, TypeError, KeyError):
        return None


def _backend_reachable():
    """(ok, detail): can a fresh process enumerate devices in bounded time?"""
    probe = (
        "import jax; ds = jax.devices(); "
        "print(len(ds), ds[0].device_kind, jax.default_backend())"
    )
    last = None
    for attempt in range(PROBE_ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", probe],
                capture_output=True,
                text=True,
                timeout=PROBE_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            last = f"probe timed out ({PROBE_TIMEOUT_S}s)"
            continue
        if proc.returncode == 0:
            return True, proc.stdout.strip()
        # Fast failure (broken install, plugin init error): report the real
        # cause, not a fictitious timeout.
        last = f"probe rc={proc.returncode}: {proc.stderr.strip()[-800:]}"
    return False, f"{PROBE_ATTEMPTS} attempts; last: {last}"


def _fanin_microbench():
    """``fanin_microbench``: does the hierarchical root's per-round work
    scale with AGGREGATORS or with CLIENTS?

    Drives up to 10k simulated clients/round through a real 2-tier
    topology: leaf :class:`fedtpu.transport.aggregator.AggregatorServer`
    processes serve SubmitPartial over REAL localhost gRPC, each backed by
    a SimFederation-style cohort (``fedtpu.sim`` Population + uniform
    cohort sampler draws which virtual clients participate; only the local
    TRAINING is simulated — every reply payload runs the genuine FSP1
    encode -> stream decode -> partial-reduce -> SubmitPartial path). The
    root side mirrors tier-mode ``_round_body``: one SubmitPartial pull
    per aggregator, ``sparse.decode_into_row`` into the ``[A, P]`` buffer,
    ``flat_ops.combine_partial_rows`` finalize.

    Single-core honesty: this box serialises the leaves (no parallelism to
    measure), so the artifact reports BOTH walls —

    - ``serial_wall_s``: everything end-to-end as measured here;
    - ``critical_path_s``: root decode+combine + the SLOWEST single
      leaf's measured duration — the round wall of the deployed topology,
      where leaves run on their own hosts;

    and records ``host_cores`` so a reader can tell which wall binds.
    Two sweeps, two gates (mirrored by tests/test_bench.py):

    - scale-out (fixed cohort, growing aggregators): critical-path
      growth exponent vs total clients < 1 -> round wall SUBLINEAR in
      clients;
    - fan-in (fixed aggregators, growing cohorts): root decode+combine
      flat (<2x) across 4x client growth -> root work O(aggregators),
      not O(clients).

    Run via ``python bench.py --fanin-microbench``; prints one JSON line
    and writes artifacts/FANIN_MICROBENCH.json atomically.
    """
    import gc
    import math
    import socket

    import numpy as np

    from fedtpu.config import FedConfig, RoundConfig
    from fedtpu.ops import flat as flat_ops
    from fedtpu.sim.population import Population
    from fedtpu.sim.samplers import UniformSampler
    from fedtpu.transport import proto, sparse
    from fedtpu.transport.aggregator import serve_aggregator
    from fedtpu.transport.service import TrainerStub, create_channel

    # Synthetic flat surface: ~32k f32 coordinates (the small-model zoo's
    # scale), padded by the layout to the 128 lane.
    dim = int(os.environ.get("FEDTPU_FB_DIM", "32768"))
    template = {
        "params": {"w": np.zeros((dim // 128, 128), np.float32)},
        "batch_stats": {},
    }
    layout = flat_ops.make_layout(template)
    # Sweep 1 (scale-out): cohort size fixed, aggregator count grows —
    # 8 x 1250 = the 10k-clients/round headline. Sweep 2 (fan-in): 4
    # aggregators, cohort grows 4x.
    cohort_fixed = int(os.environ.get("FEDTPU_FB_COHORT", "1250"))
    agg_counts = [
        int(a) for a in
        os.environ.get("FEDTPU_FB_AGGS", "2,4,8").split(",")
    ]
    fixed_aggs = int(os.environ.get("FEDTPU_FB_FIXED_AGGS", "4"))
    growing_cohorts = [
        int(c) for c in
        os.environ.get(
            "FEDTPU_FB_COHORTS",
            f"{cohort_fixed // 4},{cohort_fixed // 2},{cohort_fixed}",
        ).split(",")
    ]
    rounds = int(os.environ.get("FEDTPU_FB_ROUNDS", "4"))
    # Distinct payload templates per leaf: decode cost is content-
    # independent, so cycling K real encoded payloads per cohort keeps the
    # (client-side, unmeasured) encode cost off the bench's clock while
    # every decode is the genuine path.
    distinct = int(os.environ.get("FEDTPU_FB_DISTINCT_PAYLOADS", "8"))

    cfg = RoundConfig(
        fed=FedConfig(
            num_clients=2, delta_layout="flat", telemetry="off",
        ),
    )

    def free_port() -> int:
        s = socket.socket()
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def make_cohort_source(leaf_idx: int, cohort: int, population: int):
        """SimFederation-backed downstream: the Population + sampler pick
        the round's virtual cohort; each member's reply is a real FSP1
        flat payload carrying its example count."""
        shard = np.zeros((population, 1), np.int32)
        pop = Population(shard, np.ones_like(shard, bool), seed=leaf_idx)
        sampler = UniformSampler(seed=leaf_idx)
        rng = np.random.default_rng(1000 + leaf_idx)
        payloads = []
        for i in range(distinct):
            delta = {
                "params": {
                    "w": rng.standard_normal(
                        (dim // 128, 128)
                    ).astype(np.float32)
                },
                "batch_stats": {},
            }
            data, _ = sparse.encode_topk_flat(
                delta, 1.0,
                extra={"num_examples": np.float32(32 + i)},
            )
            payloads.append(data)

        def source(round_idx: int, rank_base: int, world: int):
            ids, alive = sampler.sample(pop, round_idx, cohort)
            return [
                payloads[int(cid) % distinct]
                for cid, ok in zip(ids, alive) if ok
            ]

        return source

    def run_topology(num_aggs: int, cohort: int) -> dict:
        """One 2-tier configuration: real-gRPC leaves, root-side pull +
        decode + combine loop; returns post-warmup per-round medians."""
        servers, aggs, stubs = [], [], []
        for j in range(num_aggs):
            addr = f"localhost:{free_port()}"
            srv, agg = serve_aggregator(
                addr, cfg,
                cohort_source=make_cohort_source(
                    j, cohort, population=4 * cohort
                ),
                template=template,
            )
            servers.append(srv)
            aggs.append(agg)
            stubs.append(TrainerStub(create_channel(addr)))
        world = num_aggs * cohort
        rows = np.zeros((num_aggs, layout.padded), np.float32)
        serial, critical, root_work, leaf_max = [], [], [], []
        clients_seen = 0
        try:
            for r in range(rounds):
                t0 = time.monotonic()
                leaf_walls, records = [], []
                weight_sums = np.zeros((num_aggs,), np.float32)
                clients_seen = 0
                # Collect phase: pull every leaf's partial first, so the
                # root-phase timing below never overlaps leaf serving.
                for j, stub in enumerate(stubs):
                    t_leaf = time.monotonic()
                    reply = stub.SubmitPartial(
                        proto.SubmitPartialRequest(
                            rank_base=j * cohort, world=world,
                            round=r, epoch=1,
                        ),
                        timeout=600,
                    )
                    leaf_walls.append(time.monotonic() - t_leaf)
                    clients_seen += reply.clients
                    records.append(reply.record)
                # Root phase, isolated: everything above shares this one
                # core with the in-process leaves, and their per-round
                # garbage ([cohort, P] buffers, decoded payloads) would
                # otherwise bill its GC pauses to the root's clock — an
                # artifact of the single-host harness, not of the deployed
                # topology (leaves collect on their own hosts).
                gc.collect()
                t_root = time.monotonic()
                for j, record in enumerate(records):
                    extra = sparse.decode_into_row(
                        record, layout.sizes, rows[j]
                    )
                    weight_sums[j] = float(extra["weight_sum"])
                mean_row = flat_ops.combine_partial_rows(
                    jnp.asarray(rows), jnp.asarray(weight_sums)
                )
                jax.block_until_ready(mean_row)
                t_end = time.monotonic()
                root_s = t_end - t_root
                serial.append(t_end - t0)
                root_work.append(root_s)
                leaf_max.append(max(leaf_walls))
                critical.append(root_s + max(leaf_walls))
        finally:
            for a in aggs:
                a.stop()
            for s in servers:
                s.stop(0)
        # Drop round 0 (combine jit warm-up) when more than one round ran;
        # medians, not means — a single-core box shares the clock with the
        # in-process leaves, so per-round tails are scheduler noise.
        sl = slice(1, None) if rounds > 1 else slice(None)
        return {
            "aggregators": num_aggs,
            "cohort": cohort,
            "clients": clients_seen,
            "serial_wall_s": round(float(np.median(serial[sl])), 6),
            "critical_path_s": round(float(np.median(critical[sl])), 6),
            "root_decode_combine_s": round(
                float(np.median(root_work[sl])), 6
            ),
            "leaf_max_s": round(float(np.median(leaf_max[sl])), 6),
        }

    import jax
    import jax.numpy as jnp

    scale_out = [run_topology(a, cohort_fixed) for a in agg_counts]
    fan_in = [run_topology(fixed_aggs, c) for c in growing_cohorts]

    # Gate 1: critical-path growth exponent vs clients < 1 (sublinear).
    lo, hi = scale_out[0], scale_out[-1]
    exponent = (
        math.log(hi["critical_path_s"] / lo["critical_path_s"])
        / math.log(hi["clients"] / lo["clients"])
        if hi["clients"] > lo["clients"] and lo["critical_path_s"] > 0
        else 0.0
    )
    # Gate 2: root decode+combine flat across the cohort growth.
    flo, fhi = fan_in[0], fan_in[-1]
    root_ratio = (
        fhi["root_decode_combine_s"] / flo["root_decode_combine_s"]
        if flo["root_decode_combine_s"] > 0 else 1.0
    )
    client_ratio = (
        fhi["clients"] / flo["clients"] if flo["clients"] else 1.0
    )
    result = {
        "metric": "fanin_microbench",
        "unit": "seconds (post-warmup per-round medians; see sweeps)",
        # Headline: the scale-out sweep's critical-path growth exponent —
        # < 1.0 means round wall-clock is sublinear in total clients.
        "value": round(exponent, 4),
        "max_clients_per_round": max(r["clients"] for r in scale_out),
        "flat_coords": int(layout.total),
        "host_cores": os.cpu_count(),
        "rounds_per_config": rounds,
        "sweeps": {
            "scale_out_fixed_cohort": scale_out,
            "fan_in_fixed_aggregators": fan_in,
        },
        "gates": {
            "critical_path_exponent_vs_clients": round(exponent, 4),
            "critical_path_sublinear": bool(exponent < 1.0),
            "root_work_ratio_across_cohort_growth": round(root_ratio, 4),
            "root_client_growth_ratio": round(client_ratio, 4),
            # Root work must stay far from tracking the 4x client growth.
            "root_work_o_aggregators": bool(root_ratio < 2.0),
        },
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    os.makedirs(ARTIFACTS_DIR, exist_ok=True)
    path = os.path.join(ARTIFACTS_DIR, "FANIN_MICROBENCH.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(result, f, indent=2)
    os.replace(tmp, path)
    return result


def _print_diag(error: str) -> None:
    """Emit the value-0.0 diagnostic line (with the live-artifact pointer)."""
    diag = {
        "metric": METRIC,
        "value": 0.0,
        "unit": UNIT,
        "vs_baseline": 0.0,
        "error": error,
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
    }
    diag.update(_live_artifact_pointer() or {})
    diag.update(_predicted_roofline_pointer() or {})
    print(json.dumps(diag))


def main():
    if "--compression-microbench" in sys.argv:
        print(json.dumps(_compression_microbench()))
        return
    if "--codec-frontier-microbench" in sys.argv:
        print(json.dumps(_codec_frontier_microbench()))
        return
    if "--server-pipeline-microbench" in sys.argv:
        print(json.dumps(_server_pipeline_microbench()))
        return
    if "--telemetry-microbench" in sys.argv:
        print(json.dumps(_telemetry_microbench()))
        return
    if "--obs-plane-microbench" in sys.argv:
        print(json.dumps(_obs_plane_microbench()))
        return
    if "--chaos-overhead-microbench" in sys.argv:
        print(json.dumps(_chaos_overhead_microbench()))
        return
    if "--screening-overhead-microbench" in sys.argv:
        print(json.dumps(_screening_overhead_microbench()))
        return
    if "--fencing-overhead-microbench" in sys.argv:
        print(json.dumps(_fencing_overhead_microbench()))
        return
    if "--checkpoint-overhead-microbench" in sys.argv:
        print(json.dumps(_checkpoint_overhead_microbench()))
        return
    if "--cohort-scale" in sys.argv:
        print(json.dumps(_cohort_scale()))
        return
    if "--mfu-profile" in sys.argv:
        print(json.dumps(_mfu_profile()))
        return
    if "--mfu-microbench" in sys.argv:
        print(json.dumps(_mfu_microbench()))
        return
    if "--mixed-precision-microbench" in sys.argv:
        print(json.dumps(_mixed_precision_microbench()))
        return
    if "--fanin-microbench" in sys.argv:
        print(json.dumps(_fanin_microbench()))
        return
    if "--inner" in sys.argv:
        print(json.dumps(_measure()))
        return

    ok, detail = _backend_reachable()
    if not ok:
        _print_diag(f"backend unreachable: {detail}")
        return

    last_err = "unknown"
    for attempt in range(ATTEMPTS):
        if attempt:
            time.sleep(BACKOFF_S * attempt)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--inner"],
                capture_output=True,
                text=True,
                timeout=ATTEMPT_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired as exc:
            # The child may have printed its measurement BEFORE wedging in
            # backend/interpreter teardown — salvage it from captured output.
            out = exc.stdout or b""
            line = _salvage_json(out.decode() if isinstance(out, bytes) else out)
            if line:
                print(line)
                return
            last_err = f"attempt {attempt + 1}: timeout after {ATTEMPT_TIMEOUT_S}s"
            continue
        # Accept a printed measurement even on nonzero exit: a backend that
        # segfaults during interpreter teardown (after the JSON was emitted)
        # must not cost two more 20-minute attempts.
        line = _salvage_json(proc.stdout)
        if line:
            print(line)
            return
        last_err = (
            f"attempt {attempt + 1}: rc={proc.returncode}, no JSON: "
            + proc.stderr.strip()[-1500:]
        )
    _print_diag(last_err)


if __name__ == "__main__":
    main()
