"""Checkpoint / resume.

The reference's ``.pth`` files quadruple as RPC payloads, FedAvg inputs,
replication state, and resume points (``src/main.py:87-96,160-165``,
``src/server.py:34,174-179``; SURVEY §5). fedtpu separates concerns: the
transport payload is :mod:`fedtpu.transport.wire`; *checkpoints* are this
module — round-granularity snapshots of the full
:class:`fedtpu.core.round.FederatedState` (global model + per-client
momentum + RNG + compressor residuals), so resume reproduces the exact
training trajectory, not just the weights.

Two backends behind one API:
- ``orbax`` (directory-per-step, async-capable, the standard JAX tool) when
  available;
- the framed wire codec (single file, CRC-checked) as fallback — also the
  format used for cross-host replication blobs.
"""

from fedtpu.checkpoint.checkpoint import (
    Checkpointer,
    atomic_write_bytes,
    latest_round,
    restore,
    save,
    verify_generation,
)
from fedtpu.checkpoint.writer import BackgroundCheckpointer

__all__ = [
    "BackgroundCheckpointer",
    "Checkpointer",
    "atomic_write_bytes",
    "latest_round",
    "restore",
    "save",
    "verify_generation",
]
