"""Checkpoint backends: orbax directory checkpoints or single-file wire blobs.

Layout (wire backend):  ``<dir>/round_<N>.fckpt``  — one framed, CRC-checked
file per round (see :mod:`fedtpu.transport.wire`) plus a digest-bearing
manifest ``round_<N>.fckpt.manifest.json`` recording the byte count and
crc32 the write CLAIMED to make durable. Layout (orbax backend):
``<dir>/<N>/...`` per orbax's StandardCheckpointer. ``latest_round`` scans
either layout; ``Checkpointer`` keeps at most ``keep`` snapshots, mirroring
the reference's behavior of only ever retaining the latest
``optimizedModel.pth`` (``src/server.py:174-179``) while fixing its
inability to resume mid-run (the TODO at ``src/server.py:64``).

Durability contract (the disaster-recovery spine, docs/OPERATIONS.md):

- **Crash-consistent writes.** Every wire-backend generation is written to
  a temp file, fsync'd, atomically renamed into place, and the DIRECTORY
  fsync'd (rename atomicity alone does not make the rename durable — a
  power cut can resurrect the old directory entry). The manifest follows
  the same protocol, written only after its data file is durable, so a
  manifest never vouches for bytes that were not yet on disk.
- **Verify-on-read with multi-generation fallback.** ``restore`` checks
  the manifest digest before decoding (and the wire CRC during decode);
  :meth:`Checkpointer.restore_latest` treats a corrupt newest generation
  (bit rot, torn write, truncation) as a FALLBACK event — logged, counted
  into ``fedtpu_checkpoint_fallback_total``, flight-recorded — and
  restores the previous generation instead of raising through ``--resume``
  (the pre-hardening behavior: one flipped byte in the newest file made
  the whole directory unusable). Template mismatches (an intact file whose
  pytree does not match the caller's state) still raise: that is a config
  problem, and silently restoring an OLDER generation would mask it.
- **Non-fatal saves.** :meth:`Checkpointer.save` treats ``OSError``
  (ENOSPC, EIO, a vanished mount) as a counted, flight-recorded warning —
  ``fedtpu_checkpoint_save_failures_total`` — and returns ``None``:
  training continues on the surviving generations rather than dying
  because the checkpoint disk filled up.
- **Prune only after a verified save.** Old generations are removed only
  once the new one has been read back and digest-verified; a save that
  cannot be verified leaves the previous generations — the recovery
  lifeline — untouched.

Seeded disk faults (``fedtpu.ft.chaos`` kinds ``ckpt_fail`` |
``ckpt_torn`` | ``ckpt_rot`` on the pseudo-RPC ``Disk``) are consulted by
:meth:`Checkpointer.save` when a schedule is armed, so the fallback and
non-fatal paths above are chaos-testable against real files
(``tools/chaos_soak.py --disaster``).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import time
import zlib
from typing import Any, List, Optional

import jax
import numpy as np

from fedtpu.transport import wire

Pytree = Any

log = logging.getLogger("fedtpu.checkpoint")

_WIRE_RE = re.compile(r"^round_(\d+)\.fckpt$")
_MANIFEST_SUFFIX = ".manifest.json"
_MANIFEST_FORMAT = "fckpt-manifest/1"


def _wire_path(directory: str, round_idx: int) -> str:
    return os.path.join(directory, f"round_{round_idx}.fckpt")


def _manifest_path(directory: str, round_idx: int) -> str:
    return _wire_path(directory, round_idx) + _MANIFEST_SUFFIX


def _fsync_dir(directory: str) -> None:
    """Make a rename in ``directory`` durable (POSIX: the rename mutates
    the directory inode, which has its own dirty state)."""
    try:
        fd = os.open(directory or ".", os.O_RDONLY)
    except OSError:
        return  # platforms that refuse O_RDONLY on dirs: best effort
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """fsync'd atomic file replacement: temp write -> flush -> fsync(file)
    -> rename -> fsync(directory). A crash at ANY point leaves either the
    old file or the new one — never a torn mix — and a completed return
    means the bytes survive power loss."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _write_manifest(directory: str, round_idx: int, payload: bytes) -> None:
    manifest = {
        "format": _MANIFEST_FORMAT,
        "round": int(round_idx),
        "bytes": len(payload),
        "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
    }
    atomic_write_bytes(
        _manifest_path(directory, round_idx),
        json.dumps(manifest).encode(),
    )


def verify_generation(directory: str, round_idx: int) -> bool:
    """True iff the wire generation's on-disk bytes match its manifest
    digest (or the pre-manifest legacy layout, where only the wire CRC can
    vouch — checked at decode time instead). Raises nothing: any read
    error reads as unverified."""
    path = _wire_path(directory, round_idx)
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except OSError:
        return False
    mpath = _manifest_path(directory, round_idx)
    if not os.path.exists(mpath):
        # Legacy generation (pre-manifest): defer to the wire CRC.
        return True
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
        return (
            int(manifest["bytes"]) == len(data)
            and int(manifest["crc32"]) == (zlib.crc32(data) & 0xFFFFFFFF)
        )
    except (OSError, ValueError, KeyError):
        return False


def save(directory: str, round_idx: int, state: Pytree, backend: str = "auto") -> str:
    """Write one snapshot; returns its path. ``backend``: auto | orbax | wire.

    The device->host transfer happens HERE for both backends (one
    ``np.asarray`` map over the tree), so every caller — the synchronous
    round loop and the background writer alike — blocks the device for
    exactly the snapshot copy and nothing downstream ever holds device
    buffers."""
    os.makedirs(directory, exist_ok=True)
    host = jax.tree.map(np.asarray, state)
    if backend == "auto":
        backend = "orbax" if _orbax() is not None else "wire"
    if backend == "orbax":
        ocp = _orbax()
        path = os.path.join(os.path.abspath(directory), str(round_idx))
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, host, force=True)
        ckptr.wait_until_finished()
        return path
    if backend == "wire":
        path = _wire_path(directory, round_idx)
        payload = wire.encode(host, compress=True)
        atomic_write_bytes(path, payload)
        # Manifest last: it must never vouch for bytes that are not yet
        # durable. Verify-on-read treats a data file without a manifest as
        # legacy (wire-CRC-only), so a crash between the two writes
        # degrades gracefully.
        _write_manifest(directory, round_idx, payload)
        return path
    raise ValueError(f"unknown checkpoint backend '{backend}'")


def restore(
    directory: str, round_idx: int, like: Pytree, backend: str = "auto"
) -> Pytree:
    """Load the snapshot for ``round_idx`` into the structure of ``like``.

    Wire generations are digest-verified against their manifest before the
    decode (bit rot and torn writes fail HERE, as :class:`wire.WireError`,
    not as a confusing msgpack error deep in flax)."""
    wire_p = _wire_path(directory, round_idx)
    orbax_p = os.path.join(os.path.abspath(directory), str(round_idx))
    if backend == "auto":
        backend = "wire" if os.path.exists(wire_p) else "orbax"
    if backend == "orbax":
        ocp = _orbax()
        if ocp is None:
            raise FileNotFoundError(orbax_p)
        ckptr = ocp.StandardCheckpointer()
        host_like = jax.tree.map(np.asarray, like)
        restored = ckptr.restore(orbax_p, host_like)
        return jax.tree.map(lambda l, r: np.asarray(r, l.dtype), host_like, restored)
    if not verify_generation(directory, round_idx):
        raise wire.WireError(
            f"checkpoint generation {round_idx} in {directory} fails its "
            "manifest digest (torn write or bit rot)"
        )
    with open(wire_p, "rb") as fh:
        data = fh.read()
    try:
        return wire.decode(data, like)
    except wire.WireError:
        raise
    except ValueError:
        legacy = _legacy_decode(data, like)
        if legacy is not None:
            return legacy
        raise


# State fields added after the first release of the wire format, OLDEST
# FIRST. Checkpoints written before a field existed lack its key, and flax's
# from_bytes raises on any key mismatch — so a failed decode retries with
# progressively more of these (newest first) dropped from the template and
# refilled from ``like`` (i.e. their freshly-initialised values, which is
# exactly right for a state the old run never had). The suffix order handles
# mid-generation blobs that have some but not all of the newer fields.
_NEW_STATE_FIELDS = ("server_opt_state", "last_client_loss")


def _legacy_decode(data: bytes, like: Pytree) -> Optional[Pytree]:
    if not hasattr(like, "_asdict"):
        return None
    full = dict(like._asdict())
    present = [k for k in _NEW_STATE_FIELDS if k in full]
    for n_drop in range(1, len(present) + 1):
        d = dict(full)
        dropped = {k: d.pop(k) for k in present[-n_drop:]}
        try:
            tree = wire.decode(data, d)
        except ValueError:
            continue
        return type(like)(**tree, **dropped)
    return None


def _scan_rounds(directory: str) -> List[int]:
    """Round indices present in ``directory`` under either layout."""
    if not os.path.isdir(directory):
        return []
    rounds: List[int] = []
    for name in os.listdir(directory):
        m = _WIRE_RE.match(name)
        if m:
            rounds.append(int(m.group(1)))
        elif name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            rounds.append(int(name))
    return sorted(set(rounds))


def latest_round(directory: str) -> Optional[int]:
    """Highest round index present in ``directory`` (either layout), or None."""
    rounds = _scan_rounds(directory)
    return rounds[-1] if rounds else None


class Checkpointer:
    """Round-granularity checkpoint manager with retention.

    >>> ckpt = Checkpointer("ckpt/", keep=3)
    >>> ckpt.save(round_idx, state)
    >>> state = ckpt.restore_latest(like=state)

    ``metrics`` (a :class:`fedtpu.obs.MetricsRegistry`) and ``flight`` (a
    :class:`fedtpu.obs.FlightRecorder`) hook the durability counters and
    events; ``chaos`` (a :class:`fedtpu.ft.chaos.FaultSchedule`) arms the
    seeded disk faults on the pseudo-RPC ``Disk``. ``strict=True`` restores
    the old raise-on-save-failure behavior for callers that prefer it.
    """

    def __init__(self, directory: str, keep: int = 3, backend: str = "auto",
                 metrics=None, flight=None, chaos=None, strict: bool = False):
        self.directory = directory
        self.keep = keep
        self.backend = backend
        self.strict = strict
        self._metrics = metrics
        self._flight = flight
        self._chaos = chaos
        # Last successful save, for /statusz-style introspection:
        # {round, bytes, wall_s}.
        self.last_save: Optional[dict] = None

    # ------------------------------------------------------------- metrics
    def _count(self, name: str, help_: str, amount: float = 1.0) -> None:
        if self._metrics is not None:
            self._metrics.counter(name, help_).inc(amount)

    def _observe(self, name: str, help_: str, value: float) -> None:
        if self._metrics is not None:
            self._metrics.histogram(name, help_).observe(value)

    # ---------------------------------------------------------------- save
    def save(self, round_idx: int, state: Pytree) -> Optional[str]:
        """Write + verify one generation, then prune. NON-FATAL: an OSError
        (ENOSPC, EIO, vanished mount) or a verify-after-write failure is
        logged, counted into ``fedtpu_checkpoint_save_failures_total`` and
        flight-recorded, and ``None`` returned — the round loop keeps
        training on the surviving generations. Old generations are pruned
        ONLY after the new one verifies (the prune-after-verified-save
        ordering: a bad write must never cost the recovery lifeline)."""
        rule = None
        if self._chaos is not None:
            rule = self._chaos.decide("Disk")
        t0 = time.monotonic()
        try:
            if rule is not None and rule.kind == "ckpt_fail":
                raise OSError(28, "chaos: injected ENOSPC")  # errno.ENOSPC
            path = save(self.directory, round_idx, state, backend=self.backend)
            if self.backend != "orbax" and not verify_generation(
                self.directory, round_idx
            ):
                raise OSError(
                    f"checkpoint generation {round_idx} failed "
                    "verify-after-write"
                )
        except OSError as exc:
            log.warning(
                "checkpoint save of round %d failed (%s); training "
                "continues on the surviving generations", round_idx, exc,
            )
            self._count(
                "fedtpu_checkpoint_save_failures_total",
                "checkpoint saves that failed (ENOSPC/EIO/verify) — "
                "non-fatal, training continues",
            )
            if self._flight is not None:
                self._flight.record(
                    "checkpoint", event="save_failed", round=round_idx,
                    error=str(exc),
                )
            if self.strict:
                raise
            return None
        wall = time.monotonic() - t0
        nbytes = 0
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            pass
        self._prune()
        self.last_save = {
            "round": int(round_idx), "bytes": int(nbytes),
            "wall_s": round(wall, 6),
        }
        self._count(
            "fedtpu_checkpoint_saves_total",
            "checkpoint generations written, verified, and made durable",
        )
        self._observe(
            "fedtpu_checkpoint_write_seconds",
            "wall seconds per checkpoint save (encode + fsync'd write + "
            "verify)",
            wall,
        )
        if self._metrics is not None:
            self._metrics.gauge(
                "fedtpu_checkpoint_bytes",
                "on-disk bytes of the most recent checkpoint generation",
            ).set(nbytes)
        # Post-verification silent corruption (ckpt_torn | ckpt_rot): the
        # fault models a disk that ACKNOWLEDGED the write and lost or
        # flipped bits afterwards — invisible to the writer, caught only by
        # restore-time verification. Applied after the metrics above:
        # the save legitimately looked successful to this process.
        if rule is not None and rule.kind in ("ckpt_torn", "ckpt_rot"):
            _corrupt_generation(self.directory, round_idx, rule.kind)
        return path

    def restore(self, round_idx: int, like: Pytree) -> Pytree:
        return restore(self.directory, round_idx, like, backend=self.backend)

    def restore_latest(self, like: Pytree) -> Optional[tuple]:
        """(round_idx, state) of the newest VERIFIED snapshot, or None for
        an empty directory — the ``--resume`` path (reference:
        ``src/main.py:87-96``).

        A corrupt generation (manifest digest mismatch, wire CRC failure,
        truncation, unreadable file) falls back to the previous one:
        logged, counted into ``fedtpu_checkpoint_fallback_total``,
        flight-recorded. Template mismatches (intact bytes that do not
        match ``like``'s structure) raise — a config problem the operator
        must see, not a disk fault to skip past. Raises
        :class:`wire.WireError` when generations exist but ALL fail
        verification, so a resume never silently restarts from scratch.
        Requires ``keep >= 2`` (or unbounded retention, ``keep <= 0``):
        fallback needs a previous generation to exist."""
        if 0 < self.keep < 2:
            raise ValueError(
                f"resuming requires keep >= 2 (got keep={self.keep}): "
                "generation fallback needs a previous snapshot to fall "
                "back to"
            )
        rounds = _scan_rounds(self.directory)
        if not rounds:
            return None
        for r in reversed(rounds):
            try:
                return r, self.restore(r, like)
            except (wire.WireError, OSError) as exc:
                log.error(
                    "checkpoint generation %d is corrupt (%s); falling "
                    "back to the previous generation", r, exc,
                )
                self._count(
                    "fedtpu_checkpoint_fallback_total",
                    "restore-time fallbacks past a corrupt checkpoint "
                    "generation (torn write / bit rot)",
                )
                if self._flight is not None:
                    self._flight.record(
                        "checkpoint", event="fallback", round=r,
                        error=str(exc),
                    )
        raise wire.WireError(
            f"all {len(rounds)} checkpoint generations in "
            f"{self.directory} failed verification"
        )

    def _prune(self) -> None:
        rounds = _scan_rounds(self.directory)
        for r in rounds[: -self.keep] if self.keep > 0 else []:
            wp = _wire_path(self.directory, r)
            dp = os.path.join(self.directory, str(r))
            if os.path.exists(wp):
                os.remove(wp)
            mp = _manifest_path(self.directory, r)
            if os.path.exists(mp):
                os.remove(mp)
            if os.path.isdir(dp):
                shutil.rmtree(dp, ignore_errors=True)

    def status(self) -> dict:
        """Introspection block (CLI /statusz): directory + last save."""
        return {
            "directory": self.directory,
            "keep": self.keep,
            "last_save": self.last_save,
        }

    # Lifecycle no-ops, so callers hold one surface whether saves are
    # synchronous or routed through the BackgroundCheckpointer wrapper.
    def flush(self, timeout: Optional[float] = None) -> bool:
        return True

    def close(self, timeout: Optional[float] = None) -> None:
        return None


def _corrupt_generation(directory: str, round_idx: int, kind: str) -> None:
    """Apply a seeded SILENT disk fault to a written generation: the
    manifest keeps claiming the intended bytes, so only restore-time
    verification can notice — exactly the failure mode the fallback path
    exists for. ``ckpt_torn`` truncates the file to half (an acknowledged
    write the filesystem lost the tail of); ``ckpt_rot`` flips one byte in
    the middle (media bit rot)."""
    path = _wire_path(directory, round_idx)
    try:
        size = os.path.getsize(path)
        if size < 2:
            return
        if kind == "ckpt_torn":
            with open(path, "r+b") as fh:
                fh.truncate(size // 2)
        else:
            with open(path, "r+b") as fh:
                fh.seek(size // 2)
                byte = fh.read(1)
                fh.seek(size // 2)
                fh.write(bytes((byte[0] ^ 0xFF,)))
    except OSError:
        pass


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None
