"""Checkpoint backends: orbax directory checkpoints or single-file wire blobs.

Layout (wire backend):  ``<dir>/round_<N>.fckpt``  — one framed, CRC-checked
file per round (see :mod:`fedtpu.transport.wire`). Layout (orbax backend):
``<dir>/<N>/...`` per orbax's StandardCheckpointer. ``latest_round`` scans
either layout; ``Checkpointer`` keeps at most ``keep`` snapshots, mirroring
the reference's behavior of only ever retaining the latest
``optimizedModel.pth`` (``src/server.py:174-179``) while fixing its inability
to resume mid-run (the TODO at ``src/server.py:64``).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, List, Optional

import jax
import numpy as np

from fedtpu.transport import wire

Pytree = Any

_WIRE_RE = re.compile(r"^round_(\d+)\.fckpt$")


def _wire_path(directory: str, round_idx: int) -> str:
    return os.path.join(directory, f"round_{round_idx}.fckpt")


def save(directory: str, round_idx: int, state: Pytree, backend: str = "auto") -> str:
    """Write one snapshot; returns its path. ``backend``: auto | orbax | wire."""
    os.makedirs(directory, exist_ok=True)
    if backend == "auto":
        backend = "orbax" if _orbax() is not None else "wire"
    if backend == "orbax":
        ocp = _orbax()
        path = os.path.join(os.path.abspath(directory), str(round_idx))
        ckptr = ocp.StandardCheckpointer()
        host = jax.tree.map(np.asarray, state)
        ckptr.save(path, host, force=True)
        ckptr.wait_until_finished()
        return path
    if backend == "wire":
        path = _wire_path(directory, round_idx)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(wire.encode(state, compress=True))
        os.replace(tmp, path)  # atomic: no torn checkpoints on crash
        return path
    raise ValueError(f"unknown checkpoint backend '{backend}'")


def restore(
    directory: str, round_idx: int, like: Pytree, backend: str = "auto"
) -> Pytree:
    """Load the snapshot for ``round_idx`` into the structure of ``like``."""
    wire_p = _wire_path(directory, round_idx)
    orbax_p = os.path.join(os.path.abspath(directory), str(round_idx))
    if backend == "auto":
        backend = "wire" if os.path.exists(wire_p) else "orbax"
    if backend == "orbax":
        ocp = _orbax()
        if ocp is None:
            raise FileNotFoundError(orbax_p)
        ckptr = ocp.StandardCheckpointer()
        host_like = jax.tree.map(np.asarray, like)
        restored = ckptr.restore(orbax_p, host_like)
        return jax.tree.map(lambda l, r: np.asarray(r, l.dtype), host_like, restored)
    with open(wire_p, "rb") as fh:
        data = fh.read()
    try:
        return wire.decode(data, like)
    except ValueError:
        legacy = _legacy_decode(data, like)
        if legacy is not None:
            return legacy
        raise


# State fields added after the first release of the wire format, OLDEST
# FIRST. Checkpoints written before a field existed lack its key, and flax's
# from_bytes raises on any key mismatch — so a failed decode retries with
# progressively more of these (newest first) dropped from the template and
# refilled from ``like`` (i.e. their freshly-initialised values, which is
# exactly right for a state the old run never had). The suffix order handles
# mid-generation blobs that have some but not all of the newer fields.
_NEW_STATE_FIELDS = ("server_opt_state", "last_client_loss")


def _legacy_decode(data: bytes, like: Pytree) -> Optional[Pytree]:
    if not hasattr(like, "_asdict"):
        return None
    full = dict(like._asdict())
    present = [k for k in _NEW_STATE_FIELDS if k in full]
    for n_drop in range(1, len(present) + 1):
        d = dict(full)
        dropped = {k: d.pop(k) for k in present[-n_drop:]}
        try:
            tree = wire.decode(data, d)
        except ValueError:
            continue
        return type(like)(**tree, **dropped)
    return None


def _scan_rounds(directory: str) -> List[int]:
    """Round indices present in ``directory`` under either layout."""
    if not os.path.isdir(directory):
        return []
    rounds: List[int] = []
    for name in os.listdir(directory):
        m = _WIRE_RE.match(name)
        if m:
            rounds.append(int(m.group(1)))
        elif name.isdigit() and os.path.isdir(os.path.join(directory, name)):
            rounds.append(int(name))
    return sorted(set(rounds))


def latest_round(directory: str) -> Optional[int]:
    """Highest round index present in ``directory`` (either layout), or None."""
    rounds = _scan_rounds(directory)
    return rounds[-1] if rounds else None


class Checkpointer:
    """Round-granularity checkpoint manager with retention.

    >>> ckpt = Checkpointer("ckpt/", keep=3)
    >>> ckpt.save(round_idx, state)
    >>> state = ckpt.restore_latest(like=state)
    """

    def __init__(self, directory: str, keep: int = 3, backend: str = "auto"):
        self.directory = directory
        self.keep = keep
        self.backend = backend

    def save(self, round_idx: int, state: Pytree) -> str:
        path = save(self.directory, round_idx, state, backend=self.backend)
        self._prune()
        return path

    def restore(self, round_idx: int, like: Pytree) -> Pytree:
        return restore(self.directory, round_idx, like, backend=self.backend)

    def restore_latest(self, like: Pytree) -> Optional[tuple]:
        """(round_idx, state) of the newest snapshot, or None if empty —
        the ``--resume`` path (reference: ``src/main.py:87-96``)."""
        r = latest_round(self.directory)
        if r is None:
            return None
        return r, self.restore(r, like)

    def _prune(self) -> None:
        rounds = _scan_rounds(self.directory)
        for r in rounds[: -self.keep] if self.keep > 0 else []:
            wp = _wire_path(self.directory, r)
            dp = os.path.join(self.directory, str(r))
            if os.path.exists(wp):
                os.remove(wp)
            if os.path.isdir(dp):
                shutil.rmtree(dp, ignore_errors=True)


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None
