"""Background checkpoint writer: saves off the round loop.

The synchronous path (``Checkpointer.save`` on the round loop) blocks the
loop for encode + fsync'd write + verify — milliseconds for an MLP, but a
full serialize-and-fsync of a real model is round-scale work the loop
should not wait for. :class:`BackgroundCheckpointer` splits the save at
the only point that MUST happen on the loop: the device->host snapshot
(``np.asarray`` over the state tree — the copy that pins the values the
checkpoint claims to capture). Everything after — wire encode, atomic
write, manifest, verify, prune — runs on one daemon writer thread under a
``checkpoint`` span.

Ordering guarantees:

- Saves are applied strictly in submission order (single writer thread,
  FIFO queue), so generation N on disk never predates generation N-1.
- The inner :class:`~fedtpu.checkpoint.checkpoint.Checkpointer` prunes
  only after each generation verifies, and its save errors are non-fatal
  (counted, flight-recorded) — a full disk degrades durability, never
  liveness, and never kills the writer thread.
- The queue is bounded (``queue_depth``): if the writer falls behind, the
  round loop blocks on the NEXT save instead of accumulating unbounded
  host snapshots — backpressure, not a leak.

``flush()`` drains pending saves (call before reading the directory back
in-process); ``close()`` drains and stops the thread — the CLIs call it
from their exit path so the final generation is durable before the
process exits.
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Optional

import jax
import numpy as np

from fedtpu.checkpoint.checkpoint import Checkpointer

Pytree = Any

log = logging.getLogger("fedtpu.checkpoint")

_STOP = object()


class BackgroundCheckpointer:
    """Same ``save(round_idx, state)`` surface as :class:`Checkpointer`,
    with the write moved to a background thread. ``telemetry`` (a
    :class:`fedtpu.obs.Telemetry`, optional) wraps each write in a
    ``checkpoint`` span so traces show the writer's wall time next to the
    round loop it no longer blocks."""

    def __init__(self, inner: Checkpointer, telemetry=None,
                 queue_depth: int = 2):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        self.inner = inner
        self._telemetry = telemetry
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        # Pending-save accounting (NOT the queue size: the item currently
        # being written has left the queue but is not yet durable).
        self._lock = threading.Lock()
        self._pending = 0
        self._drained = threading.Condition(self._lock)
        self._thread = threading.Thread(
            target=self._run, name="fedtpu-ckpt-writer", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- surface
    @property
    def directory(self) -> str:
        return self.inner.directory

    @property
    def last_save(self) -> Optional[dict]:
        return self.inner.last_save

    def save(self, round_idx: int, state: Pytree) -> None:
        """Snapshot-to-host NOW (the only device-blocking step — both save
        paths block the device identically, see ``checkpoint.save``), then
        hand the host tree to the writer. Blocks only when the bounded
        queue is full (writer behind by ``queue_depth`` generations).

        The snapshot is a FORCED copy, never a view: on CPU,
        ``np.asarray`` of a jax array can alias the device buffer, and the
        engines' round steps DONATE their state (``donate_argnums``) — a
        zero-copy view would silently observe the NEXT round's bytes by
        the time the writer serializes it. The copy is the price of the
        crash-consistency claim and is exactly what the microbench's
        ``async_call`` headline times."""
        host = jax.tree.map(lambda l: np.array(l, copy=True), state)
        with self._lock:
            self._pending += 1
        self._q.put((int(round_idx), host))

    def restore(self, round_idx: int, like: Pytree) -> Pytree:
        self.flush()
        return self.inner.restore(round_idx, like)

    def restore_latest(self, like: Pytree):
        self.flush()
        return self.inner.restore_latest(like)

    def status(self) -> dict:
        s = self.inner.status()
        s["async"] = True
        with self._lock:
            s["pending"] = self._pending
        return s

    # ------------------------------------------------------------ lifecycle
    def flush(self, timeout: Optional[float] = None) -> bool:
        """Wait until every submitted save has been written (or failed
        non-fatally). True on drained, False on timeout."""
        with self._drained:
            return self._drained.wait_for(
                lambda: self._pending == 0, timeout
            )

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """Drain and stop the writer. Idempotent."""
        if not self._thread.is_alive():
            return
        self._q.put(_STOP)
        self._thread.join(timeout)
        if self._thread.is_alive():
            log.warning("checkpoint writer did not drain within %ss", timeout)

    # -------------------------------------------------------------- worker
    def _span(self, round_idx: int):
        if self._telemetry is not None:
            return self._telemetry.span("checkpoint", round=round_idx)
        from contextlib import nullcontext

        return nullcontext()

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                return
            round_idx, host = item
            try:
                with self._span(round_idx):
                    # Inner save is non-fatal by design; anything else
                    # escaping here must not kill the writer thread.
                    self.inner.save(round_idx, host)
            except Exception:
                log.exception(
                    "background checkpoint save of round %d raised",
                    round_idx,
                )
            finally:
                with self._drained:
                    self._pending -= 1
                    if self._pending == 0:
                        self._drained.notify_all()
