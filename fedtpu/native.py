"""ctypes bindings for the native host codec (``native/codec.cpp``).

Loads ``native/libfedtpu_native.so`` if present (``make -C native`` builds
it; :func:`ensure_built` does so programmatically). Every entry point has a
numpy fallback, so the package works without a toolchain — the native path
just makes the DCN-edge sparsification O(n) single-pass instead of
numpy-temporary-heavy.

No pybind11 in this environment, hence plain-C ABI + ctypes (allowed per the
environment constraints).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libfedtpu_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_f32p = ctypes.POINTER(ctypes.c_float)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i8p = ctypes.POINTER(ctypes.c_int8)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.fedtpu_kth_magnitude.restype = ctypes.c_float
    lib.fedtpu_kth_magnitude.argtypes = [_f32p, ctypes.c_int64, ctypes.c_int64]
    lib.fedtpu_pack_sparse.restype = ctypes.c_int64
    lib.fedtpu_pack_sparse.argtypes = [
        _f32p, ctypes.c_int64, ctypes.c_float, _i32p, _f32p, ctypes.c_int64,
    ]
    lib.fedtpu_unpack_sparse.restype = None
    lib.fedtpu_unpack_sparse.argtypes = [_i32p, _f32p, ctypes.c_int64, _f32p]
    lib.fedtpu_quant_int8.restype = None
    lib.fedtpu_quant_int8.argtypes = [_f32p, ctypes.c_int64, ctypes.c_float, _i8p]
    lib.fedtpu_dequant_int8.restype = None
    lib.fedtpu_dequant_int8.argtypes = [_i8p, ctypes.c_int64, ctypes.c_float, _f32p]
    lib.fedtpu_pack_sparse_with_residual.restype = ctypes.c_int64
    lib.fedtpu_pack_sparse_with_residual.argtypes = [
        _f32p, ctypes.c_int64, ctypes.c_float, _i32p, _f32p, ctypes.c_int64, _f32p,
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None if unbuilt/unloadable (numpy fallback)."""
    global _lib, _load_attempted
    if _lib is None and not _load_attempted:
        _load_attempted = True
        if os.path.exists(_LIB_PATH):
            try:
                _lib = _bind(ctypes.CDLL(_LIB_PATH))
            except OSError:
                _lib = None
    return _lib


def ensure_built() -> bool:
    """Build the native library if missing; True if it is now loadable."""
    global _load_attempted
    if load() is not None:
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except Exception:
        return False
    _load_attempted = False
    return load() is not None


def available() -> bool:
    return load() is not None


def _as_f32(x: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(x, np.float32)


# ------------------------------------------------------------------ kernels
def kth_magnitude(x: np.ndarray, k: int) -> float:
    """k-th largest |x| (k>=1) — the top-k keep threshold."""
    x = _as_f32(x).ravel()
    if x.size == 0:
        return 0.0
    k = min(max(int(k), 1), x.size)
    lib = load()
    if lib is not None:
        return float(
            lib.fedtpu_kth_magnitude(x.ctypes.data_as(_f32p), x.size, k)
        )
    return float(np.partition(np.abs(x), x.size - k)[x.size - k])


def pack_sparse(x: np.ndarray, thresh: float) -> Tuple[np.ndarray, np.ndarray]:
    """(idx int32, vals f32) of entries with |x| >= thresh."""
    x = _as_f32(x).ravel()
    lib = load()
    if lib is not None:
        idx = np.empty(x.size, np.int32)
        vals = np.empty(x.size, np.float32)
        m = lib.fedtpu_pack_sparse(
            x.ctypes.data_as(_f32p), x.size, ctypes.c_float(thresh),
            idx.ctypes.data_as(_i32p), vals.ctypes.data_as(_f32p), x.size,
        )
        return idx[:m].copy(), vals[:m].copy()
    keep = np.abs(x) >= thresh
    return np.flatnonzero(keep).astype(np.int32), x[keep]


def unpack_sparse(idx: np.ndarray, vals: np.ndarray, n: int) -> np.ndarray:
    out = np.zeros(n, np.float32)
    idx = np.ascontiguousarray(idx, np.int32)
    vals = _as_f32(vals)
    lib = load()
    if lib is not None:
        lib.fedtpu_unpack_sparse(
            idx.ctypes.data_as(_i32p), vals.ctypes.data_as(_f32p),
            idx.size, out.ctypes.data_as(_f32p),
        )
        return out
    out[idx] = vals
    return out


def pack_sparse_with_residual(
    x: np.ndarray, thresh: float
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(idx, vals, residual): kept entries + the dropped mass (error
    feedback), one fused pass natively."""
    x = _as_f32(x).ravel()
    lib = load()
    if lib is not None:
        idx = np.empty(x.size, np.int32)
        vals = np.empty(x.size, np.float32)
        residual = np.empty(x.size, np.float32)
        m = lib.fedtpu_pack_sparse_with_residual(
            x.ctypes.data_as(_f32p), x.size, ctypes.c_float(thresh),
            idx.ctypes.data_as(_i32p), vals.ctypes.data_as(_f32p), x.size,
            residual.ctypes.data_as(_f32p),
        )
        return idx[:m].copy(), vals[:m].copy(), residual
    keep = np.abs(x) >= thresh
    residual = np.where(keep, 0.0, x).astype(np.float32)
    return np.flatnonzero(keep).astype(np.int32), x[keep], residual


def quant_int8(x: np.ndarray) -> Tuple[np.ndarray, float]:
    """(codes int8, scale). scale = max|x| / 127."""
    x = _as_f32(x).ravel()
    scale = float(np.abs(x).max() / 127.0) if x.size else 0.0
    lib = load()
    if lib is not None:
        out = np.empty(x.size, np.int8)
        lib.fedtpu_quant_int8(
            x.ctypes.data_as(_f32p), x.size, ctypes.c_float(scale),
            out.ctypes.data_as(_i8p),
        )
        return out, scale
    if scale <= 0:
        return np.zeros(x.size, np.int8), 0.0
    return np.clip(np.rint(x / scale), -127, 127).astype(np.int8), scale


def dequant_int8(codes: np.ndarray, scale: float, n: int) -> np.ndarray:
    codes = np.ascontiguousarray(codes, np.int8)
    lib = load()
    if lib is not None:
        out = np.empty(n, np.float32)
        lib.fedtpu_dequant_int8(
            codes.ctypes.data_as(_i8p), n, ctypes.c_float(scale),
            out.ctypes.data_as(_f32p),
        )
        return out
    return scale * codes.astype(np.float32)
