"""Distributed (multi-process) federation over the gRPC edge.

This is the process topology the reference implements (``src/server.py`` /
``src/client.py``): a primary server dialing out to client agents that each
host a ``Trainer`` gRPC server, with a backup server for failover. fedtpu
keeps the topology — it is the cross-pod/DCN deployment shape — but every
host-side sin is replaced:

- model payloads are raw wire bytes, not base64 pickle files on disk
  (:mod:`fedtpu.transport.wire` vs ``src/client.py:19-29``);
- aggregation is one jitted weighted mean on device, not a host loop over
  checkpoint files (vs ``src/server.py:155-179``), and it never averages in
  stale state from dead clients (reference bug, ``src/server.py:157``);
- client local training is the same jitted ``local_update`` the simulated
  engine uses (:mod:`fedtpu.core.client`), so single-process simulation and
  multi-process deployment run identical math;
- failure detection/failover is the event-driven machinery of
  :mod:`fedtpu.ft`, not signal handlers.

For intra-pod scale the simulated engine (:class:`fedtpu.core.Federation`)
is strictly faster — this module exists for the reference's deployment model:
genuinely separate processes/hosts federating over a network edge.
"""

from __future__ import annotations

import json
import logging
import math
import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from fedtpu import models as model_zoo
from fedtpu.config import (
    RoundConfig,
    resolve_server_pipeline,
    screening_enabled,
    validate_retry_policy,
    validate_screen_config,
    validate_tier_config,
)
from fedtpu.core.client import make_eval_fn, make_local_update
from fedtpu.core import optim
from fedtpu.data import load, dataset_info
from fedtpu.data import partition
from fedtpu.ft import (
    ClientRegistry,
    FailoverStateMachine,
    HeartbeatMonitor,
    MembershipTable,
    PrimaryPinger,
    WatchdogRunner,
)
from fedtpu.obs import (
    FlightRecorder,
    StatusBoard,
    Telemetry,
    process_rss_bytes,
)
from fedtpu.obs import propagate
from fedtpu.obs.registry import Counter
from fedtpu.transport import proto, sparse, wire
from fedtpu.transport.codec_policy import AdaptiveCodecPolicy
from fedtpu.transport.retry import call_with_retry, is_stale_coordinator
from fedtpu.transport.service import (
    TrainerServicer,
    TrainerStub,
    create_channel,
    create_server,
    probe,
    trace_context_of,
)

log = logging.getLogger("fedtpu.federation")


def _model_template(model, cfg: RoundConfig):
    """(params, batch_stats) zero-templates for wire decode."""
    shape = dataset_info(cfg.data.dataset)[0]
    variables = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1,) + shape, jnp.float32), train=False),
        jax.random.PRNGKey(0),
    )
    zeros = jax.tree.map(lambda s: np.zeros(s.shape, s.dtype), variables)
    return zeros["params"], zeros.get("batch_stats", {})


def _payload_template(model, cfg: RoundConfig):
    params, stats = _model_template(model, cfg)
    return {
        "params": params,
        "batch_stats": stats,
        "num_examples": np.zeros((), np.float32),
    }


# FSP1 record kind -> codec name, for the per-codec wire accounting
# (fedtpu_rpc_bytes_*_total{codec=...} and the /statusz byte table). Dense
# FTP1 payloads carry no kind and count as "none".
_CODEC_OF_KIND = {
    "topk": "topk",
    "topk_flat": "topk",
    "int8": "int8",
    "int8_flat": "int8",
    "rotq_flat": "rotq",
    "randk_flat": "randk",
    "partial_flat": "partial",
}


def _sum_codec_bytes(pairs) -> Dict[str, int]:
    """Fold (codec_name, nbytes) pairs into a {codec: total_bytes} dict."""
    out: Dict[str, int] = {}
    for codec_name, nb in pairs:
        out[codec_name] = out.get(codec_name, 0) + int(nb)
    return out


# --------------------------------------------------------------------- client
class LocalTrainer:
    """Client-side training engine: the jitted single-client local update.

    Mirrors the reference client's semantics (``src/main.py:128-165``): on
    StartTrain the *weights* are whatever the last SendModel delivered, while
    the optimizer state persists locally across rounds (the reference keeps
    its torch optimizer alive in the module global, ``src/main.py:99``).
    """

    # Per-round local-state snapshots retained for coordinator-replay
    # rollback (see _train_round_impl): bounded ring, newest rounds win.
    SNAPSHOT_KEEP = 4

    def __init__(self, cfg: RoundConfig, seed: int = 0,
                 state_dir: Optional[str] = None):
        self.cfg = cfg
        self.telemetry = Telemetry(cfg.fed.telemetry, role="client")
        n_classes = dataset_info(cfg.data.dataset)[1]
        if cfg.num_classes != n_classes:
            raise ValueError(
                f"cfg.num_classes={cfg.num_classes} but dataset "
                f"'{cfg.data.dataset}' has {n_classes} classes"
            )
        self.model = model_zoo.create(cfg.model, num_classes=cfg.num_classes)
        self.images, self.labels = load(
            cfg.data.dataset, "train", seed=cfg.data.seed, num=cfg.data.num_examples
        )
        self.eval_images, self.eval_labels = load(
            cfg.data.dataset, "test", seed=cfg.data.seed, num=cfg.data.num_examples
        )
        sample = jnp.zeros((1,) + tuple(self.images.shape[1:]), jnp.float32)
        variables = self.model.init(jax.random.PRNGKey(seed), sample, train=False)
        self.params = variables["params"]
        self.batch_stats = variables.get("batch_stats", {})
        self.opt_state = optim.init(self.params, cfg.opt)
        self.rng = jax.random.PRNGKey(seed + 1)
        self.round_idx = 0
        self._local_update = jax.jit(make_local_update(self.model.apply, cfg))
        self._evaluate = make_eval_fn(self.model.apply, cfg)
        # Sparse-delta mode needs the client's round-start model to equal the
        # server's global; until the first SendModel lands we fall back to
        # dense full-weight payloads.
        self.synced = False
        # Edge error feedback: mass dropped by top-k is carried locally into
        # the next round's delta (the host-side analogue of
        # fedtpu.ops.compression residuals).
        self.edge_residual = None
        # Byzantine self: an armed FaultSchedule whose ATTACK_KINDS rules
        # make THIS client adversarial (fedtpu.ft.chaos.decide_attack —
        # keyed on `identity`, the client's serving address). None = honest.
        self.chaos = None
        self.identity = "self"
        # Dense f32 wire size of one full model payload — the denominator
        # of the compression-ratio gauge (codec bytes / dense bytes).
        self._dense_bytes = sum(
            int(np.prod(l.shape)) * np.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(
                {"params": self.params, "batch_stats": self.batch_stats}
            )
        )
        # Cold-start client durability (docs/OPERATIONS.md §Disaster
        # recovery): the server resyncs a restarted client's WEIGHTS, but
        # the local round counter, optimizer moments, PRNG stream, and the
        # edge error-feedback residual live only in this process — losing
        # them silently diverges the client (a fresh residual re-injects
        # mass top-k already shipped; a reset round counter replays old
        # batch draws). With ``state_dir`` set, that local state persists
        # per round through the hardened generational Checkpointer (fsync
        # + manifest + fallback) and restores on construction.
        self._snapshots: Dict[int, dict] = {}
        self._state_ckpt = None
        if state_dir:
            from fedtpu.checkpoint import Checkpointer

            self._state_ckpt = Checkpointer(
                state_dir, keep=3, backend="wire",
                metrics=self.telemetry.registry if self.telemetry.enabled
                else None,
            )
            self._restore_client_state()

    def _shard(self, rank: int, world: int):
        """This client's rows of the deterministic ``world``-way partition.
        All clients compute the same global partition from the shared data
        seed, so shards are disjoint without any coordination — the
        distributed analogue of the engine's partitioner dispatch
        (``fedtpu/core/engine.py``)."""
        cfg = self.cfg
        if cfg.data.partition == "round_robin":
            idx, mask = partition.round_robin(
                len(self.images), world, cfg.data.batch_size
            )
        elif cfg.data.partition == "iid":
            idx, mask = partition.iid(len(self.images), world, seed=cfg.data.seed)
        elif cfg.data.partition == "dirichlet":
            idx, mask = partition.dirichlet(
                self.labels, world, alpha=cfg.data.dirichlet_alpha, seed=cfg.data.seed
            )
        else:
            raise ValueError(f"unknown partition {cfg.data.partition}")
        return idx[rank : rank + 1], mask[rank : rank + 1]

    # ------------------------------------------------- local-state durability
    def _residual_template(self) -> dict:
        return {
            "params": jax.tree.map(
                lambda l: np.zeros(l.shape, l.dtype), self.params
            ),
            "batch_stats": jax.tree.map(
                lambda l: np.zeros(l.shape, l.dtype), self.batch_stats
            ),
        }

    def _client_state(self) -> dict:
        """The client-local state one wire blob must capture for a cold
        restart to RESUME rather than diverge: the local round counter,
        PRNG key, optimizer moments, and the error-feedback residual
        (``has_residual`` distinguishes "no residual yet" from a zero
        residual)."""
        residual = self.edge_residual
        return {
            "round_idx": np.asarray(self.round_idx, np.int64),
            "rng": np.asarray(self.rng),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "has_residual": np.asarray(
                0 if residual is None else 1, np.int8
            ),
            "residual": (
                jax.tree.map(np.asarray, residual)
                if residual is not None else self._residual_template()
            ),
        }

    def _install_client_state(self, tree: dict) -> None:
        self.round_idx = int(tree["round_idx"])
        self.rng = jnp.asarray(tree["rng"])
        self.opt_state = jax.tree.map(jnp.asarray, tree["opt_state"])
        self.edge_residual = (
            jax.tree.map(np.asarray, tree["residual"])
            if int(tree["has_residual"]) else None
        )

    def _restore_client_state(self) -> None:
        try:
            latest = self._state_ckpt.restore_latest(self._client_state())
        except (ValueError, OSError) as exc:
            log.warning(
                "client state in %s unusable (%s); starting fresh",
                self._state_ckpt.directory, exc,
            )
            return
        if latest is None:
            return
        r, tree = latest
        self._install_client_state(tree)
        # Seed the rollback ring with the restored cut, so a coordinator
        # replaying exactly this round (the common recovery alignment)
        # needs no further unwinding.
        self._snapshot_round(self.round_idx)
        log.info(
            "client state restored: resuming at local round %d "
            "(residual=%s)", self.round_idx,
            "yes" if self.edge_residual is not None else "no",
        )

    def _persist_client_state(self) -> None:
        if self._state_ckpt is not None:
            # Non-fatal by construction (hardened Checkpointer): a full
            # state disk degrades the client's restartability, never its
            # participation in the current round.
            self._state_ckpt.save(self.round_idx, self._client_state())

    def _snapshot_round(self, round_idx: int) -> None:
        """Host snapshot of the round-START local state, for replay
        rollback. Ring-bounded: older than SNAPSHOT_KEEP rounds falls off
        (a deeper replay than the checkpoint keep-window cannot happen —
        the coordinator's own fallback is bounded by its retention)."""
        self._snapshots[round_idx] = {
            "params": jax.tree.map(np.asarray, self.params),
            "batch_stats": jax.tree.map(np.asarray, self.batch_stats),
            "rng": np.asarray(self.rng),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "residual": (
                jax.tree.map(np.asarray, self.edge_residual)
                if self.edge_residual is not None else None
            ),
        }
        for r in sorted(self._snapshots):
            if len(self._snapshots) <= self.SNAPSHOT_KEEP:
                break
            del self._snapshots[r]

    def _rollback(self, target_round: int) -> bool:
        snap = self._snapshots.get(target_round)
        if snap is None:
            # Deeper than the in-memory ring (e.g. this client ALSO cold-
            # restarted and only seeded its newest cut): the on-disk state
            # generations under state_dir may still hold the target round.
            # They carry no params — the coordinator's pre-round broadcast
            # re-bases the weights in every recovery flow.
            if self._state_ckpt is None:
                return False
            try:
                tree = self._state_ckpt.restore(
                    target_round, self._client_state()
                )
            except (ValueError, OSError):
                return False
            self._install_client_state(tree)
            for r in [r for r in self._snapshots if r > target_round]:
                del self._snapshots[r]
            return True
        self.round_idx = target_round
        self.params = jax.tree.map(jnp.asarray, snap["params"])
        self.batch_stats = jax.tree.map(jnp.asarray, snap["batch_stats"])
        self.rng = jnp.asarray(snap["rng"])
        self.opt_state = jax.tree.map(jnp.asarray, snap["opt_state"])
        self.edge_residual = (
            jax.tree.map(np.asarray, snap["residual"])
            if snap["residual"] is not None else None
        )
        # Everything after the restored cut is now an alternate history.
        for r in [r for r in self._snapshots if r > target_round]:
            del self._snapshots[r]
        return True

    def train_round(self, rank: int, world: int,
                    trace_ctx: Optional[propagate.TraceContext] = None,
                    coord_round: int = -1,
                    codec_override: Optional[str] = None) -> bytes:
        """One local epoch on this client's shard; returns the wire payload
        (trained weights + stats + example count). ``trace_ctx`` — the
        coordinator's propagated trace context, when the StartTrain carried
        one: the span below then records the federation ``trace_id`` plus
        ``remote_parent``/``remote_role`` so ``tools/trace_merge.py`` can
        nest this client's work under the coordinator's round span, and the
        tracer adopts the federation trace id. ``coord_round`` — the
        coordinator's lineage round from the TrainRequest (-1 from older
        peers): a value BEHIND this client's local counter means the
        coordinator recovered from a checkpoint older than the rounds this
        client already trained, and the local state rolls back to match
        (see _train_round_impl). ``codec_override`` — the coordinator's
        per-round codec choice from ``TrainRequest.codec`` (the adaptive
        policy); None keeps the static configured codec."""
        tel = self.telemetry
        propagate.adopt(tel.tracer, trace_ctx)
        with tel.span("client_train", rank=rank, round=self.round_idx,
                      **propagate.span_args(trace_ctx)):
            payload = self._train_round_impl(
                rank, world, coord_round, codec_override
            )
        self._persist_client_state()
        tel.counter(
            "fedtpu_client_tx_bytes_total",
            "StartTrain reply payload bytes shipped by this client",
        ).inc(len(payload))
        tel.gauge(
            "fedtpu_client_compression_ratio",
            "last reply's wire bytes / dense model payload bytes",
        ).set(len(payload) / max(self._dense_bytes, 1))
        return payload

    def _train_round_impl(self, rank: int, world: int,
                          coord_round: int = -1,
                          codec_override: Optional[str] = None) -> bytes:
        cfg = self.cfg
        # Coordinator-replay rollback (disaster recovery): a StartTrain
        # whose lineage round is BEHIND our local counter means the
        # coordinator cold-restarted from a checkpoint generation older
        # than the rounds we already trained (its fallback past corrupt
        # generations rewound the lineage). Training "forward" from our
        # newer local state would silently fork the trajectory — instead
        # rewind to the round-start snapshot of the replayed round, so the
        # re-run reproduces the original round bit-for-bit. A coordinator
        # AHEAD of us (participation sampling, stragglers) is ordinary
        # drift and keeps the existing semantics.
        if 0 <= coord_round < self.round_idx:
            local_was = self.round_idx
            if self._rollback(coord_round):
                log.warning(
                    "coordinator replays round %d (local counter was %d): "
                    "rolled local state back to the matching snapshot",
                    coord_round, local_was,
                )
            else:
                log.warning(
                    "coordinator replays round %d but no local snapshot "
                    "survives (local counter %d); training forward — "
                    "trajectories may diverge", coord_round, self.round_idx,
                )
        self._snapshot_round(self.round_idx)
        # Model-level attack consult (fedtpu.ft.chaos ATTACK_KINDS): one
        # decision per training round, keyed on this client's identity and
        # local round. label_flip poisons THIS round's training labels;
        # delta kinds poison only the SUBMITTED payload below — the
        # attacker's own local state stays its honest trajectory, exactly
        # like a real adversary running an unmodified trainer with a
        # poisoned send hook.
        atk_round = self.round_idx
        atk = (
            self.chaos.decide_attack(self.identity, atk_round)
            if self.chaos is not None else None
        )
        own, own_mask = self._shard(rank, world)
        num_examples = float(own_mask.sum())
        # One epoch = the shard's batch count; local_epochs multiplies it
        # (same fold as the simulated engine, fedtpu/core/engine.py).
        steps = max(1, int(own_mask[0].sum()) // cfg.data.batch_size) * max(
            1, cfg.fed.local_epochs
        )
        x, y, step_mask = partition.make_client_batches(
            self.images,
            self.labels,
            own,
            own_mask,
            cfg.data.batch_size,
            steps,
            seed=cfg.data.seed + self.round_idx,
        )
        if atk is not None and atk.kind == "label_flip":
            y = (np.asarray(y) + atk.label_offset) % cfg.num_classes
        self.rng, step_rng = jax.random.split(self.rng)
        start_params, start_stats = self.params, self.batch_stats
        out = self._local_update(
            start_params,
            start_stats,
            self.opt_state,
            jnp.asarray(x[0]),
            jnp.asarray(y[0]),
            jnp.asarray(step_mask[0]),
            step_rng,
            jnp.asarray(self.round_idx, jnp.int32),
        )
        self.params = out.params
        self.batch_stats = out.batch_stats
        self.opt_state = out.opt_state
        self.round_idx += 1
        send_params, send_stats = out.params, out.batch_stats
        if atk is not None and atk.kind in ("sign_flip", "scale", "noise"):
            honest = jax.tree.map(
                lambda a, b: np.asarray(a) - np.asarray(b),
                {"params": out.params, "batch_stats": out.batch_stats},
                {"params": start_params, "batch_stats": start_stats},
            )
            hostile = self.chaos.apply_attack_delta(
                atk, honest, self.identity, atk_round
            )
            sent = jax.tree.map(
                lambda s, d: (np.asarray(s) + d).astype(np.asarray(s).dtype),
                {"params": start_params, "batch_stats": start_stats},
                hostile,
            )
            send_params, send_stats = sent["params"], sent["batch_stats"]

        # Per-round codec: the coordinator's adaptive choice when the
        # StartTrain carried one (TrainRequest.codec), else the static
        # configured codec — a legacy coordinator never sends the field and
        # nothing changes.
        codec = codec_override or cfg.fed.compression
        if codec in ("topk", "int8", "rotq", "randk") and self.synced:
            # Ship the sparse/quantized *delta* — the wire actually shrinks,
            # unlike the reference's gzip-over-dense (src/server.py:104-107).
            delta = jax.tree.map(
                lambda a, b: np.asarray(a) - np.asarray(b),
                {"params": send_params, "batch_stats": send_stats},
                {"params": start_params, "batch_stats": start_stats},
            )
            extra = {"num_examples": np.float32(num_examples)}
            ef = cfg.fed.error_feedback
            # delta_layout='flat' ships ONE contiguous record (index/value
            # or int8 block + offsets table) instead of a per-leaf map —
            # the wire twin of the engine's flat pipeline. The server's
            # template-based sparse.decode dispatches on the record kind,
            # so mixed fleets decode either form. The seeded sketch codecs
            # (rotq / randk) are inherently flat records — there is no
            # per-leaf variant.
            if cfg.fed.delta_layout == "flat":
                enc_topk, enc_int8 = sparse.encode_topk_flat, sparse.encode_int8_flat
            else:
                enc_topk, enc_int8 = sparse.encode_topk, sparse.encode_int8
            # Seeded codecs: the record seed is a pure function of (round,
            # rank) so a replayed round re-encodes byte-identically (the
            # coordinator-replay recovery path, and the bit-identical-replay
            # pins in tests/test_properties.py) while distinct clients draw
            # decorrelated rotations/index sets. atk_round is the
            # round-START counter captured above.
            sketch_seed = (atk_round << 16) | (rank & 0xFFFF)
            if codec == "topk":
                encode = lambda d, r: enc_topk(
                    d, cfg.fed.topk_fraction, residuals=r, extra=extra,
                    collect_residual=ef)
            elif codec == "int8":
                encode = lambda d, r: enc_int8(
                    d, residuals=r, extra=extra, collect_residual=ef)
            elif codec == "rotq":
                encode = lambda d, r: sparse.encode_rotq_flat(
                    d, bits=cfg.fed.rotq_bits, residuals=r, extra=extra,
                    collect_residual=ef, seed=sketch_seed)
            else:  # randk
                encode = lambda d, r: sparse.encode_randk_flat(
                    d, cfg.fed.topk_fraction, residuals=r, extra=extra,
                    collect_residual=ef, seed=sketch_seed)
            payload, residual = encode(delta, self.edge_residual if ef else None)
            if ef:
                # The residual is a dense model-space tree, so it carries
                # UNCHANGED across adaptive lossy->lossy codec switches —
                # no rescale needed (the rescale-or-reset rule,
                # docs/OPERATIONS.md §Adaptive codec).
                self.edge_residual = residual
            return payload

        payload = {
            "params": send_params,
            "batch_stats": send_stats,
            "num_examples": np.float32(num_examples),
        }
        if (
            self.edge_residual is not None
            and self.synced
            and cfg.fed.error_feedback
        ):
            # The other half of the rescale-or-reset rule: switching to the
            # dense codec FLUSHES the accumulated error-feedback residual
            # into this round's full-weight payload (weights + residual ==
            # what the lossy stream would eventually have delivered), then
            # resets it — dropped mass is never silently lost across a
            # switch to 'none'.
            res = self.edge_residual
            payload["params"] = jax.tree.map(
                lambda w, r: (np.asarray(w) + np.asarray(r)).astype(
                    np.asarray(w).dtype
                ),
                payload["params"], res["params"],
            )
            payload["batch_stats"] = jax.tree.map(
                lambda w, r: (np.asarray(w) + np.asarray(r)).astype(
                    np.asarray(w).dtype
                ),
                payload["batch_stats"], res["batch_stats"],
            )
            self.edge_residual = None
        return wire.encode(payload, compress=codec != "none")

    def set_global(self, data: bytes,
                   trace_ctx: Optional[propagate.TraceContext] = None) -> None:
        propagate.adopt(self.telemetry.tracer, trace_ctx)
        with self.telemetry.span("install_global",
                                 **propagate.span_args(trace_ctx)):
            params, stats = _model_template(self.model, self.cfg)
            tree = wire.decode(data, {"params": params, "batch_stats": stats})
            self.params = jax.tree.map(jnp.asarray, tree["params"])
            self.batch_stats = jax.tree.map(jnp.asarray, tree["batch_stats"])
            self.synced = True
        self.telemetry.counter(
            "fedtpu_client_rx_bytes_total",
            "global-model broadcast bytes received by this client",
        ).inc(len(data))

    def evaluate(self) -> Tuple[float, float]:
        bs = self.cfg.data.eval_batch_size
        nb = max(1, len(self.eval_images) // bs)
        xs = self.eval_images[: nb * bs].reshape(
            (nb, bs) + self.eval_images.shape[1:]
        )
        ys = self.eval_labels[: nb * bs].reshape((nb, bs))
        loss, acc = self._evaluate(
            self.params, self.batch_stats, jnp.asarray(xs), jnp.asarray(ys)
        )
        return float(loss), float(acc)


class ClientAgent(TrainerServicer):
    """The gRPC servicer a federated client hosts (parity:
    ``src/client.py:15-35``). StartTrain trains and returns weights; SendModel
    installs the global model and evaluates it; HeartBeat answers liveness."""

    def __init__(self, cfg: RoundConfig, seed: int = 0,
                 state_dir: Optional[str] = None):
        self.trainer = LocalTrainer(cfg, seed=seed, state_dir=state_dir)
        self.last_eval: Optional[Tuple[float, float]] = None
        # Coordinator fencing (docs/FAULT_TOLERANCE.md §Fencing): the max
        # coordinator epoch this client has ever seen. A coordinator-
        # originated RPC carrying a LOWER epoch comes from a superseded
        # primary (a healed partition's stale side) and is rejected with a
        # typed STALE_COORDINATOR status — accepting it would fork the
        # lineage. -1 until any epoch-carrying peer speaks (pre-fencing
        # coordinators never advertise one and are never rejected).
        self._max_epoch = -1
        self._epoch_lock = threading.Lock()

    def _fence_check(self, epoch: int, rpc: str, context) -> None:
        """Track the max coordinator epoch; abort a stale sender. Aborting
        raises, so callers just invoke this first."""
        if epoch < 0:
            return  # pre-fencing peer: no epoch advertised
        with self._epoch_lock:
            if epoch >= self._max_epoch:
                self._max_epoch = epoch
                return
            newest = self._max_epoch
        log.warning(
            "%s from stale coordinator epoch %d rejected (newest seen %d)",
            rpc, epoch, newest,
        )
        self.trainer.telemetry.counter(
            "fedtpu_ft_stale_rejected_total",
            "coordinator RPCs rejected for a stale fencing epoch, by rpc",
            labels={"rpc": rpc},
        ).inc()
        context.abort(
            grpc.StatusCode.FAILED_PRECONDITION,
            f"STALE_COORDINATOR: epoch {epoch} < {newest}",
        )

    def StartTrain(self, request: proto.TrainRequest, context) -> proto.TrainReply:
        self._fence_check(request.epoch, "StartTrain", context)
        payload = self.trainer.train_round(
            request.rank, request.world,
            trace_ctx=trace_context_of(context),
            coord_round=request.round,
            # Adaptive-codec choice (field 5): 0/unknown ids fall back to
            # the static configured codec, so an unrecognized id from a
            # newer coordinator degrades safely instead of crashing.
            codec_override=proto.CODEC_NAMES.get(request.codec),
        )
        return proto.TrainReply(message=payload)

    def SendModel(self, request: proto.SendModelRequest, context) -> proto.SendModelReply:
        self._fence_check(request.epoch, "SendModel", context)
        self.trainer.set_global(
            request.model, trace_ctx=trace_context_of(context)
        )
        self.last_eval = self.trainer.evaluate()
        log.info("global model installed: eval %s", self.last_eval)
        return proto.SendModelReply(reply=f"{self.last_eval[1]:.4f}".encode())

    def HeartBeat(self, request: proto.Request, context) -> proto.HeartBeatResponse:
        return proto.HeartBeatResponse(status=1)

    def status_snapshot(self) -> dict:
        """``/statusz`` feed for a client agent process."""
        t = self.trainer
        return {
            "role": t.telemetry.role or "client",
            "pid": os.getpid(),
            "round": t.round_idx,
            "synced": t.synced,
            "last_eval": (
                {"loss": self.last_eval[0], "acc": self.last_eval[1]}
                if self.last_eval else None
            ),
        }


def serve_client(
    address: str, cfg: RoundConfig, seed: int = 0, compress: bool = False,
    chaos=None, state_dir: Optional[str] = None,
):
    """Build + start a client agent server on ``address`` (parity:
    ``serve``, ``src/client.py:38-52``). Returns (server, agent).
    ``chaos`` (a :class:`fedtpu.ft.chaos.FaultSchedule`) arms fault
    injection on this agent's INBOUND RPCs — the client-side half of a
    chaos drill. ``state_dir`` persists the client's local training state
    per round so a restarted agent resumes instead of silently diverging
    (``--state-dir`` on the client CLI; docs/OPERATIONS.md)."""
    agent = ClientAgent(cfg, seed=seed, state_dir=state_dir)
    # The bind address doubles as the client's trace/flight identity.
    agent.trainer.telemetry.role = f"client:{address}"
    agent.trainer.identity = address
    if chaos is not None:
        chaos.attach(metrics=agent.trainer.telemetry.registry
                     if agent.trainer.telemetry.enabled else None)
        # ATTACK_KINDS rules in the schedule make this client Byzantine:
        # the trainer consults them per round (decide_attack) and poisons
        # its submissions/labels accordingly.
        agent.trainer.chaos = chaos
    server = create_server(address, agent, compress=compress, chaos=chaos)
    server.start()
    return server, agent


# -------------------------------------------------------------------- primary
class PrimaryServer:
    """The FedAvg orchestrator (parity: ``run()``, ``src/server.py:113-153``).

    Per round: fan out StartTrain(rank, world) to active clients, aggregate
    the returned weights with one jitted weighted mean, replicate to the
    backup, broadcast to clients. RpcErrors mark clients dead; the heartbeat
    monitor revives + resyncs them.
    """

    def __init__(
        self,
        cfg: RoundConfig,
        clients: List[str],
        backup_address: Optional[str] = None,
        compress: bool = False,
        seed: int = 0,
        initial_model: Optional[bytes] = None,
        rpc_timeout: Optional[float] = None,
        round_deadline_s: Optional[float] = None,
        flight: Optional[FlightRecorder] = None,
        chaos=None,
    ):
        """``round_deadline_s``: straggler mitigation — wait at most this
        long for StartTrain replies each round, then aggregate whatever
        arrived. Stragglers stay ALIVE (they still get the broadcast and
        rejoin next round), unlike RpcError failures; the reference's
        barrier blocks on its slowest client unconditionally
        (``src/server.py:132-135``). None = reference behavior.

        ``rpc_timeout``: legacy blanket deadline — when given it overrides
        the per-RPC data-plane deadlines of ``cfg.fed.retry`` (the typed
        :class:`fedtpu.config.RetryPolicy` that replaced the old scattered
        constants). ``chaos``: a :class:`fedtpu.ft.chaos.FaultSchedule` —
        every outbound channel then carries the fault-injection
        interceptor (deterministic, seeded; see docs/FAULT_TOLERANCE.md).
        """
        self.cfg = cfg
        self.compress = compress
        self.round_deadline_s = round_deadline_s
        rp = validate_retry_policy(cfg.fed.retry)
        self.retry_policy = rp
        # Per-RPC deadlines from the policy; an explicit rpc_timeout= keeps
        # the old blanket-override surface for the data-plane RPCs.
        self._deadlines = {
            "StartTrain": rpc_timeout if rpc_timeout is not None
            else rp.start_train_timeout_s,
            "SendModel": rpc_timeout if rpc_timeout is not None
            else rp.send_model_timeout_s,
            "FetchModel": rpc_timeout if rpc_timeout is not None
            else rp.fetch_model_timeout_s,
            "HeartBeat": rp.probe_timeout_s,
            "CheckIfPrimaryUp": rp.backup_ping_timeout_s,
        }
        # Legacy attribute: the data-plane deadline some callers/tests read.
        self.rpc_timeout = self._deadlines["SendModel"]
        if not 0.0 <= cfg.fed.round_quorum <= 1.0:
            raise ValueError(
                f"round_quorum must be in [0, 1], got {cfg.fed.round_quorum}"
            )
        self.chaos = chaos
        # The resolved timing surface, logged once so operators can read a
        # run's effective deadlines off the startup log instead of chasing
        # constants through the source (docs/OPERATIONS.md).
        log.info(
            "transport timings: start_train=%.1fs send_model=%.1fs "
            "fetch_model=%.1fs probe=%.1fs backup_ping=%.1fs "
            "heartbeat_period=%.1fs retries=%d backoff=%.2fs*%.1f<=%.1fs "
            "round_quorum=%.2f chaos=%s",
            self._deadlines["StartTrain"], self._deadlines["SendModel"],
            self._deadlines["FetchModel"], self._deadlines["HeartBeat"],
            self._deadlines["CheckIfPrimaryUp"],
            cfg.fed.ft_heartbeat_period_s, rp.max_attempts, rp.backoff_s,
            rp.backoff_multiplier, rp.backoff_max_s, cfg.fed.round_quorum,
            chaos.describe() if chaos is not None else "off",
        )
        self.telemetry = Telemetry(cfg.fed.telemetry, role="primary")
        # Flight recorder: bounded black box of recent spans, round marks,
        # and warning+ events — dumpable at any moment (obs/flight.py). The
        # CLI passes one with the process hooks armed; library users get a
        # buffer they can dump by hand / read over /flightz.
        self.flight = flight if flight is not None else FlightRecorder(
            role="primary"
        )
        if self.telemetry.tracer is not None:
            self.telemetry.tracer.sink = self.flight.record_span
        # Live status feed for /statusz (obs/http.py): the round loop
        # updates round/phase as it moves; status_snapshot() adds the
        # registry-backed liveness/failure context.
        self.status = StatusBoard(role="primary", phase="init", round=0)
        # XLA compile observability (obs/profile.py): the CLI installs a
        # CompileWatcher and hands it over so /statusz can surface compile
        # counts + steady-state recompile warnings.
        self.compile_watcher = None
        self.model = model_zoo.create(cfg.model, num_classes=cfg.num_classes)
        shape = dataset_info(cfg.data.dataset)[0]
        variables = self.model.init(
            jax.random.PRNGKey(seed), jnp.zeros((1,) + shape, jnp.float32), train=False
        )
        self.params = variables["params"]
        self.batch_stats = variables.get("batch_stats", {})
        from fedtpu.core import server_opt as server_opt_lib

        if cfg.fed.aggregator not in ("mean", "median", "trimmed_mean", "krum"):
            raise ValueError(
                f"unknown aggregator {cfg.fed.aggregator!r}; "
                "have mean | median | trimmed_mean | krum"
            )
        # Robust aggregators silently ignore example-count weights; say it
        # once at startup and stamp every round record (satellite of the
        # Byzantine PR — the silence read as a bug to operators).
        self._weights_ignored = False
        if cfg.fed.weighted:
            from fedtpu.core.round import warn_weighted_robust

            self._weights_ignored = warn_weighted_robust(cfg.fed.aggregator)
        if cfg.fed.aggregator != "mean":
            if cfg.fed.compression != "none":
                raise ValueError(
                    f"aggregator={cfg.fed.aggregator!r} cannot compose with "
                    "delta compression: sparse deltas zero out coordinate-"
                    "wise robust statistics. Use compression='none'."
                )
            if not 0.0 <= cfg.fed.trim_fraction < 0.5:
                raise ValueError(
                    f"trim_fraction must be in [0, 0.5), got "
                    f"{cfg.fed.trim_fraction}"
                )
        if cfg.fed.dp_clip_norm > 0:
            # Same soundness guards as the simulated engine
            # (fedtpu.core.round.make_round_step / init_state).
            if cfg.fed.compression != "none":
                raise ValueError(
                    "DP clipping cannot compose with delta compression. "
                    "Use compression='none'."
                )
            if cfg.fed.weighted:
                raise ValueError(
                    "DP requires uniform weighting (FedConfig(weighted=False))."
                )
            if cfg.fed.aggregator != "mean":
                raise ValueError(
                    "DP noise accounting assumes aggregator='mean'."
                )
            if jax.tree_util.tree_leaves(self.batch_stats):
                raise ValueError(
                    "DP requires a BatchNorm-free model: batch statistics "
                    "are released unclipped. Pick a model without "
                    "batch_stats (e.g. mlp)."
                )
        if cfg.fed.compression not in ("none", "topk", "int8", "rotq", "randk"):
            raise ValueError(
                f"unknown compression {cfg.fed.compression!r}; "
                "have none | topk | int8 | rotq | randk"
            )
        if cfg.fed.codec_policy not in ("static", "adaptive"):
            raise ValueError(
                f"unknown codec_policy {cfg.fed.codec_policy!r}; "
                "have static | adaptive"
            )
        # Adaptive codec selection (docs/OPERATIONS.md §Adaptive codec): the
        # round loop ships a per-client codec choice in TrainRequest.codec,
        # learned from observed bytes x RTT. Lossy codecs may be chosen any
        # round, so the combination must satisfy the same constraints a
        # static lossy codec would.
        self._codec_policy: Optional[AdaptiveCodecPolicy] = None
        if cfg.fed.codec_policy == "adaptive":
            if cfg.fed.delta_layout != "flat":
                raise ValueError(
                    "codec_policy='adaptive' requires delta_layout='flat': "
                    "the sketch codecs it selects among (rotq/randk) only "
                    "exist as flat records"
                )
            if cfg.fed.aggregator != "mean" or cfg.fed.dp_clip_norm > 0:
                raise ValueError(
                    "codec_policy='adaptive' can select lossy codecs, so it "
                    "needs aggregator='mean' and no DP clipping (the same "
                    "constraints as a static lossy codec)"
                )
            self._codec_policy = AdaptiveCodecPolicy()
        # Cumulative per-codec wire-byte ledger for /statusz (the labeled
        # twins of the unlabeled rpc byte counters; kinds map to codec
        # names via _CODEC_OF_KIND). Guarded by its own lock: collect
        # workers write while /statusz reads.
        self._codec_bytes_up: Dict[str, int] = {}
        self._codec_bytes_lock = threading.Lock()
        self._server_opt = server_opt_lib.make_server_optimizer(cfg.fed)
        self._server_opt_state = server_opt_lib.init(cfg.fed, self.params)
        # Monotonic count of aggregations performed across this model
        # lineage's *entire* life — seeds DP noise and participation
        # subsampling, rides in the replica payload, and is restored by
        # _install so a promoted backup (or recovering primary) never
        # replays earlier rounds' PRNG draws. len(self.history) cannot
        # serve: history restarts at 0 in every new server process.
        self._round_counter = 0
        # --- Coordinator fencing (docs/FAULT_TOLERANCE.md §Fencing) ------
        # role: 1 = configured primary, 2 = acting (promoted backup) — rides
        # on SendModelRequest.role so receivers/flight can attribute the
        # sender without decoding the payload. epoch: minted monotonically
        # on every promotion or post-fence re-base; replicated in the
        # replica payload and persisted in the checkpoint template ladder,
        # so a lineage's epoch survives restarts. _fenced flips when any
        # receiver rejects us with STALE_COORDINATOR — the round loop then
        # voids the in-flight round and re-bases (handle_fence).
        # _epoch_seen: the largest epoch any rejection has told us about,
        # so the re-base mints PAST the winner even if the backup is
        # unreachable during the heal.
        self._role = 1
        self._fenced = False
        self._epoch_seen = -1
        self._fence_lock = threading.Lock()
        # Pacing between re-base attempts while the winning lineage is
        # still unreachable (handle_fence keeps the fence up until the
        # recovering handshake actually lands).
        self._fence_retry_s = 0.5
        self._set_epoch(1)
        # Seeded retry jitter: when chaos is armed, backoff jitter draws
        # from a schedule-seeded stream instead of the global random, so a
        # soak's retry timing replays deterministically under one seed.
        self._retry_rand = (
            random.Random(chaos.seed ^ 0xFE17CE).random
            if chaos is not None else None
        )

        _metrics = self.telemetry.registry if self.telemetry.enabled else None
        if chaos is not None:
            chaos.attach(metrics=_metrics, flight=self.flight)
        # The mutable, versioned roster (fedtpu.ft.membership): `clients`
        # is only the STARTUP roster — members join/leave at runtime
        # through the membership gate (start_gate / admit_client /
        # remove_client), and a replica payload installed below may replace
        # the roster wholesale with the previous primary's current one.
        self.registry = MembershipTable(clients, metrics=_metrics)
        # Every outbound channel (StartTrain/SendModel fan-out, heartbeat
        # probes, backup pings/replication/FetchModel) carries the
        # trace-propagation interceptor; _trace_source yields None below
        # trace mode, so the interceptor is a single no-op call then. The
        # chaos interceptor (when armed) wraps outermost, keyed by peer.
        # Guarded by _member_lock: the gate's admit/evict mutates this dict
        # while collect workers read it.
        self._member_lock = threading.Lock()
        self._stubs: Dict[str, TrainerStub] = {
            c: self._make_stub(c) for c in clients
        }
        self._gate_server = None
        self.backup_stub = (
            TrainerStub(create_channel(
                backup_address, compress=compress,
                trace_source=self._trace_source, chaos=chaos))
            if backup_address
            else None
        )
        self.monitor = HeartbeatMonitor(
            self.registry,
            probe=self._probe_member,
            resync=self._resync,
            period=cfg.fed.ft_heartbeat_period_s,
            metrics=_metrics,
            # Concurrent probes are bounded per tick by the worst-case
            # single probe: per-attempt deadline plus the backoff budget.
            probe_deadline_s=(
                rp.max_attempts
                * (rp.probe_timeout_s + rp.backoff_max_s) + 1.0
            ),
        )
        self.pinger = (
            PrimaryPinger(self._ping_backup, metrics=_metrics)
            if self.backup_stub else None
        )
        self._aggregate = jax.jit(self._aggregate_impl)
        # Streaming collect pipeline (server_pipeline="stream", resolved
        # from the config — "auto" streams for the flat delta layout):
        # replies decode into rows of ONE flat [clients, P] buffer and ship
        # to the device as they arrive, so the post-barrier work is a
        # single fused finalize instead of per-leaf decode/stack/transfer
        # behind the slowest client. See round() and docs/PERF_ANALYSIS.md.
        self.server_pipeline = resolve_server_pipeline(cfg.fed)
        if self.server_pipeline == "stream":
            from fedtpu.ops import flat as flat_ops

            params_t, stats_t = _model_template(self.model, cfg)
            self._flat_layout = flat_ops.make_layout(
                {"params": params_t, "batch_stats": stats_t}
            )
            # Donated row write: XLA aliases input and output, so each
            # arriving row is an in-place update of the device buffer, not
            # a [clients, P] copy.
            self._set_row = jax.jit(
                lambda buf, row, i: jax.lax.dynamic_update_slice(
                    buf, row[None], (i, 0)
                ),
                donate_argnums=0,
            )
            self._finalize_stream = jax.jit(self._finalize_stream_impl)
        # Hierarchical multi-tier aggregation (docs/ARCHITECTURE.md
        # §Multi-tier): tier_fanout > 0 flips this server into the ROOT of
        # a two-tier topology — the roster holds leaf AggregatorServer
        # addresses, each round's fan-out is one SubmitPartial pull per
        # aggregator, the stream buffer holds [aggregators, P] pre-weighted
        # partial SUMS (row-axis sharded across local devices), and the
        # finalize divides ONCE over the summed weights
        # (_finalize_partial_impl — the exact-associativity contract that
        # keeps the 2-tier mean bit-identical to the flat one).
        self.tier_fanout = cfg.fed.tier_fanout
        if self.tier_fanout:
            validate_tier_config(cfg.fed, "PrimaryServer")
            # The pull shares the training-RPC deadline: a SubmitPartial
            # blocks on the leaf's whole cohort collect, i.e. the same
            # critical path StartTrain bounds one tier down.
            self._deadlines["SubmitPartial"] = self._deadlines["StartTrain"]
            self._finalize_partial = jax.jit(self._finalize_partial_impl)
        # Fused update screening (ScreenConfig, docs/FAULT_TOLERANCE.md):
        # one jitted stats pass over the round's [participants, P] rows —
        # the SAME device-resident buffer the stream finalize reads, so the
        # collect path gains zero extra device syncs — whose verdicts (a)
        # drop rejected rows from the combine through the existing
        # exclusion-by-order mask and (b) feed the per-client suspicion
        # EWMA driving quarantine -> eviction on the MembershipTable.
        self._screen_jit = None
        if screening_enabled(cfg.fed.screen):
            from fedtpu.ops import flat as flat_ops

            sc = validate_screen_config(cfg.fed.screen)
            self._screen_cfg = sc
            params_t, stats_t = _model_template(self.model, cfg)
            self._screen_layout = flat_ops.make_layout(
                {"params": params_t, "batch_stats": stats_t}
            )
            self._screen_jit = jax.jit(
                lambda rows, live: flat_ops.screen_rows(
                    rows, live, sc.norm_max, sc.zmax, sc.cos_min
                )
            )
        self.history: List[dict] = []
        self._did_initial_sync = False
        # Straggler StartTrain threads still in flight from earlier rounds,
        # keyed by client (see round()).
        self._inflight: Dict[str, threading.Thread] = {}
        # Broadcast SendModel threads still in flight from earlier rounds —
        # tracked like _inflight so next round's send to the same client
        # cannot race a stale one and install an older model last.
        self._sends: Dict[str, threading.Thread] = {}
        # Install the seed state LAST: a replica payload carries the
        # previous primary's membership roster, and adopting it needs the
        # registry and stub plumbing above to exist.
        if initial_model is not None:
            self._install(initial_model)

    # ----------------------------------------------------------- aggregation
    def _aggregate_impl(
        self, global_tree, stacked_deltas, weights, opt_state, round_idx
    ):
        """global + combined client deltas over the stacked axis — one jitted
        program, same math as the simulated engine's aggregator; dead clients
        never enter the stack so no mask is needed here. ``cfg.fed.aggregator``
        selects the combine (weighted mean, or coordinate-wise median /
        trimmed mean — robust combiners ignore the example-count weights).
        DP (clip per client, seeded noise on the combined delta) mirrors the
        engine's round step. The optional server optimizer (FedOpt family,
        fedtpu.core.server_opt) consumes the combined params-delta; BN stats
        combine the same way, mirroring the simulated round step."""
        from fedtpu.core import server_opt as server_opt_lib
        from fedtpu.core.round import _dp_clip, _dp_noise

        fed = self.cfg.fed
        total = jnp.maximum(jnp.sum(weights), 1e-9)

        def mean(d):
            w = weights.reshape((-1,) + (1,) * (d.ndim - 1)).astype(d.dtype)
            return jnp.sum(d * w, axis=0) / total.astype(d.dtype)

        def robust(d):
            xf = d.astype(jnp.float32)
            if fed.aggregator == "median":
                out = jnp.median(xf, axis=0)
            else:  # trimmed_mean; data-point bounds so the band is never empty
                lo = jnp.quantile(
                    xf, fed.trim_fraction, axis=0, keepdims=True,
                    method="lower",
                )
                hi = jnp.quantile(
                    xf, 1.0 - fed.trim_fraction, axis=0, keepdims=True,
                    method="higher",
                )
                band = (xf >= lo) & (xf <= hi)
                out = jnp.sum(jnp.where(band, xf, 0.0), axis=0) / jnp.maximum(
                    jnp.sum(band, axis=0), 1
                )
            return out.astype(d.dtype)

        if fed.dp_clip_norm > 0:
            stacked_deltas = dict(
                stacked_deltas,
                params=_dp_clip(stacked_deltas["params"], fed.dp_clip_norm),
            )
        if fed.aggregator == "krum":
            from fedtpu.core.round import _krum_over_clients

            # Joint selection over params + stats; the stack holds only
            # successful replies, so every row is "alive".
            deltas = _krum_over_clients(
                stacked_deltas,
                jnp.ones((weights.shape[0],), jnp.float32),
                None,
                fed.trim_fraction,
            )
        else:
            combine = mean if fed.aggregator == "mean" else robust
            deltas = jax.tree.map(combine, stacked_deltas)
        if fed.dp_clip_norm > 0 and fed.dp_noise_multiplier > 0:
            n = jnp.asarray(weights.shape[0], jnp.float32)
            std = fed.dp_clip_norm * fed.dp_noise_multiplier / jnp.maximum(n, 1.0)
            deltas = dict(
                deltas,
                params=_dp_noise(
                    deltas["params"], std, round_idx,
                    seed=self.cfg.data.seed ^ 0x5F5E5F,
                ),
            )
        new_params, new_opt = server_opt_lib.apply(
            self._server_opt, global_tree["params"], deltas["params"], opt_state
        )
        new_stats = jax.tree.map(
            lambda g, d: g + d, global_tree["batch_stats"], deltas["batch_stats"]
        )
        return {"params": new_params, "batch_stats": new_stats}, new_opt

    def _finalize_stream_impl(self, global_tree, rows, weights, opt_state):
        """Post-barrier finalize of the streaming pipeline: ONE fused
        program over the device-resident ``[participants, P]`` row buffer —
        weighted mean, unpack to the delta pytree, server-optimizer step,
        BN-stats add. The mean is :func:`fedtpu.core.round.flat_weighted_mean`,
        whose stacked axis-0 reduce is bit-identical to
        :meth:`_aggregate_impl`'s per-leaf mean (the stream/barrier parity
        the tests pin); everything downstream is the same per-leaf math.
        Robust aggregators and DP never reach here — config validation
        routes them to the barrier path (fedtpu.config.resolve_server_pipeline).
        """
        from fedtpu.core import server_opt as server_opt_lib
        from fedtpu.core.round import flat_weighted_mean
        from fedtpu.ops import flat as flat_ops

        mean_row = flat_weighted_mean(rows, weights)
        deltas = flat_ops.unpack(self._flat_layout, mean_row)
        new_params, new_opt = server_opt_lib.apply(
            self._server_opt, global_tree["params"], deltas["params"], opt_state
        )
        new_stats = jax.tree.map(
            lambda g, d: g + d, global_tree["batch_stats"], deltas["batch_stats"]
        )
        return {"params": new_params, "batch_stats": new_stats}, new_opt

    def _finalize_partial_impl(
        self, global_tree, sum_rows, weight_sums, opt_state
    ):
        """Tier-mode finalize: the stream buffer's rows are the leaf tiers'
        PRE-WEIGHTED sums, so the combine is sum-of-sums divided ONCE by
        the global weight total (:func:`fedtpu.ops.flat.combine_partial_rows`)
        — NOT :func:`fedtpu.core.round.flat_weighted_mean`, which would
        re-multiply each partial by its own weight sum and silently square
        the weighting. The single division is the exact-associativity
        contract: for inputs whose f32 adds are exact, the 2-tier result is
        bit-identical to the flat one-tier weighted mean
        (tests/test_aggregator.py parity pins). Everything downstream
        (unpack, server-optimizer step, BN add) is the flat path's code.
        """
        from fedtpu.core import server_opt as server_opt_lib
        from fedtpu.ops import flat as flat_ops

        mean_row = flat_ops.combine_partial_rows(sum_rows, weight_sums)
        deltas = flat_ops.unpack(self._flat_layout, mean_row)
        new_params, new_opt = server_opt_lib.apply(
            self._server_opt, global_tree["params"], deltas["params"], opt_state
        )
        new_stats = jax.tree.map(
            lambda g, d: g + d, global_tree["batch_stats"], deltas["batch_stats"]
        )
        return {"params": new_params, "batch_stats": new_stats}, new_opt

    # ------------------------------------------------------------- transport
    def model_bytes(self) -> bytes:
        """Client-broadcast payload: the global model only."""
        return wire.encode(
            {"params": self.params, "batch_stats": self.batch_stats},
            compress=self.compress,
        )

    def _set_epoch(self, epoch: int) -> None:
        """Adopt a coordinator epoch and mirror it on the gauge — one path
        for mint (promotion / post-fence re-base) and restore (replica /
        checkpoint), so the observable epoch can never lag the wire one."""
        self._coord_epoch = int(epoch)
        self.telemetry.gauge(
            "fedtpu_ft_coordinator_epoch",
            "this coordinator's fencing epoch (minted on promotion or "
            "post-fence re-base)",
        ).set(float(self._coord_epoch))

    def state_tree(self) -> dict:
        """Full resumable server state as one pytree: the model, the
        monotonic round counter, the coordinator fencing epoch, the
        membership roster (as a JSON uint8 leaf — variable-length, so a
        growing federation still replicates), and (when a server optimizer
        is configured) its moments. This is both the replica payload body
        and the checkpoint state — one format, so failover and resume can
        never drift apart."""
        tree = {
            "params": self.params,
            "batch_stats": self.batch_stats,
            "round_counter": np.asarray(self._round_counter, np.int64),
            "coord_epoch": np.asarray(self._coord_epoch, np.int64),
            "membership": self._membership_bytes(),
        }
        if self._server_opt is not None:
            tree["server_opt"] = self._server_opt_state
        return tree

    def state_template(self, membership: bool = True,
                       epoch: bool = True) -> dict:
        """Decode template matching :meth:`state_tree`'s structure.
        ``membership=False`` yields the pre-elastic-membership layout and
        ``epoch=False`` the pre-fencing one, so replicas/checkpoints
        written by older coordinators still restore (with the startup
        roster / current epoch kept)."""
        from fedtpu.core import server_opt as server_opt_lib

        params, stats = _model_template(self.model, self.cfg)
        tree = {
            "params": params,
            "batch_stats": stats,
            "round_counter": np.zeros((), np.int64),
        }
        if epoch:
            tree["coord_epoch"] = np.zeros((), np.int64)
        if membership:
            tree["membership"] = np.zeros((0,), np.uint8)
        if self._server_opt is not None:
            tree["server_opt"] = server_opt_lib.init(self.cfg.fed, params)
        return tree

    def install_state(self, tree: dict) -> None:
        """Adopt a restored :meth:`state_tree` (from replica or checkpoint).
        When the tree carries a membership roster, the CURRENT roster — not
        the startup list — is adopted with it (failover inherits joins,
        leaves, and alive flags). The fencing epoch adopts by MAX: a
        replica can only raise our epoch, never demote us below one we
        already minted."""
        self._round_counter = int(tree["round_counter"])
        if self._server_opt is not None:
            self._server_opt_state = jax.tree.map(
                jnp.asarray, tree["server_opt"]
            )
        self.params = jax.tree.map(jnp.asarray, tree["params"])
        self.batch_stats = jax.tree.map(jnp.asarray, tree["batch_stats"])
        if "coord_epoch" in tree:
            self._set_epoch(max(self._coord_epoch, int(tree["coord_epoch"])))
        if "membership" in tree:
            self._adopt_membership(tree["membership"])

    def restore_from_checkpoint(self, ckpt) -> Optional[int]:
        """Cold-start recovery protocol, coordinator side
        (docs/OPERATIONS.md §Disaster recovery): restore the full server
        state — model, monotone lineage counter, membership roster
        including suspicion/reputation, FedOpt moments — from the newest
        VERIFIED on-disk generation (``ckpt`` is a
        :class:`fedtpu.checkpoint.Checkpointer` or the background wrapper;
        its ``restore_latest`` falls back past corrupt generations and
        counts ``fedtpu_checkpoint_fallback_total``). Adopting the
        membership leaf re-resolves the roster and rebuilds the stub table
        (``_adopt_membership``), and the initial-sync flag is cleared so
        the first round after recovery pushes the restored global to every
        surviving client through the existing ``sync_clients``/seat-resync
        path — no client re-registers, nothing is lost from the roster.

        Template ladder: current layout -> pre-fencing layout (epoch kept)
        -> pre-elastic-membership layout (startup roster kept) -> legacy
        model-only checkpoints (counter estimated from the generation
        index). Returns the next round index to run (``start_round``), or
        None for an empty directory (fresh start). Raises
        :class:`wire.WireError` when generations exist but none verifies —
        a disaster the operator must see, never a silent restart from
        round 0."""
        try:
            latest = ckpt.restore_latest(self.state_template())
        except wire.WireError:
            raise
        except ValueError:
            try:
                latest = ckpt.restore_latest(self.state_template(epoch=False))
            except wire.WireError:
                raise
            except ValueError:
                try:
                    latest = ckpt.restore_latest(
                        self.state_template(membership=False, epoch=False)
                    )
                except wire.WireError:
                    raise
                except ValueError:
                    latest = None
        if latest is None:
            params, stats = _model_template(self.model, self.cfg)
            legacy = ckpt.restore_latest(
                {"params": params, "batch_stats": stats}
            )
            if legacy is None:
                return None
            r, tree = legacy
            self.params = jax.tree.map(jnp.asarray, tree["params"])
            self.batch_stats = jax.tree.map(jnp.asarray, tree["batch_stats"])
            self._round_counter = r + 1
            self._did_initial_sync = False
            log.info("resumed legacy model-only checkpoint from round %d", r)
            return r + 1
        r, tree = latest
        self.install_state(tree)
        # Survivors hold weights from rounds the restored lineage may not
        # know about; the pre-round broadcast re-bases everyone on the
        # restored global (and the lineage round in their next StartTrain
        # tells them to roll back local state to match).
        self._did_initial_sync = False
        log.info(
            "cold start: restored round %d from %s (lineage continues at "
            "%d; roster size %d, membership v%d)",
            r, getattr(ckpt, "directory", "?"), self._round_counter,
            self.registry.size, self.registry.version,
        )
        self.flight.record(
            "checkpoint", event="restore", round=r,
            members=self.registry.size,
        )
        return r + 1

    def replica_bytes(self) -> bytes:
        """Backup-replication payload: the model plus (when a server
        optimizer is configured) its moments, so a promotion or a recovering
        primary resumes the FedOpt trajectory instead of applying stale/zero
        moments to a model they were never computed against. Also carries
        the monotonic round counter so a promoted backup continues the DP
        noise / participation-subsampling PRNG sequence instead of replaying
        round 0's draws (which would let an observer difference two releases
        and cancel the noise). The frame is stamped kind="replica"."""
        return wire.encode(self.state_tree(), compress=self.compress,
                           kind="replica")

    def _install(self, data: bytes) -> None:
        """Install a replica payload or a plain model payload, dispatched on
        the frame's explicit payload-kind flag (never by trying templates
        and catching exceptions): a corrupted or config-mismatched replica
        raises instead of silently downgrading to "model-only, keep current
        moments"."""
        if wire.payload_kind(data) == "replica":
            try:
                tree = wire.decode(data, self.state_template())
            except wire.WireError:
                raise
            except ValueError:
                # Older coordinator's replica: try the pre-fencing layout
                # (epoch kept), then the pre-membership one (startup roster
                # kept). Any OTHER mismatch fails every template and raises
                # below.
                try:
                    tree = wire.decode(
                        data, self.state_template(epoch=False)
                    )
                except wire.WireError:
                    raise
                except ValueError:
                    try:
                        tree = wire.decode(
                            data,
                            self.state_template(membership=False, epoch=False),
                        )
                    except wire.WireError:
                        raise
                    except ValueError as exc:
                        raise wire.WireError(
                            "replica payload does not match this server's "
                            f"configuration ({exc}); refusing to install a "
                            "partial state"
                        ) from exc
            self.install_state(tree)
        else:
            params, stats = _model_template(self.model, self.cfg)
            try:
                tree = wire.decode(
                    data, {"params": params, "batch_stats": stats}
                )
            except wire.WireError:
                raise
            except ValueError as exc:
                raise wire.WireError(
                    "model payload does not match this server's "
                    f"configuration ({exc})"
                ) from exc
            self.params = jax.tree.map(jnp.asarray, tree["params"])
            self.batch_stats = jax.tree.map(jnp.asarray, tree["batch_stats"])

    def _resync(self, client: str) -> None:
        """Push the current global model to a recovered client (parity:
        ``sendOptimizedModel`` from the recovery loop, ``src/server.py:95-99``).

        Raises (deferring the revive to the next heartbeat tick) while a
        stale broadcast send to this client is still in flight — a resync
        racing it could land first and leave the OLDER payload installed
        last, silently desyncing the client the moment it is revived."""
        stale = self._sends.get(client)
        if stale is not None and stale.is_alive():
            raise RuntimeError(
                f"stale broadcast to {client} still in flight; "
                "deferring resync"
            )
        stub = self._stub(client)
        if stub is None:
            raise RuntimeError(f"{client} evicted; nothing to resync")
        # A transient blip mid-resync retries here instead of bouncing the
        # client back to dead for another full heartbeat cycle.
        try:
            call_with_retry(
                self.retry_policy, "SendModel",
                lambda: stub.SendModel(
                    proto.SendModelRequest(
                        model=self.model_bytes(),
                        epoch=self._coord_epoch, role=self._role,
                    ),
                    timeout=self._deadlines["SendModel"],
                ),
                peer=client, telemetry=self.telemetry,
                rand=self._retry_rand,
            )
        except grpc.RpcError as e:
            if is_stale_coordinator(e):
                self._handle_stale("SendModel", client, e)
            raise

    def sync_clients(self) -> None:
        """Broadcast the current global model to all active clients.

        Runs automatically before the first round (see :meth:`round`):
        clients may hold baselines from a previous server generation, and in
        sparse-delta mode an unsynced baseline would silently corrupt
        aggregation. Transient failures retry under the policy — one blip
        here used to kill the client before round 1 ever ran.
        """
        payload = self.model_bytes()
        for client in self.registry.active_clients():
            stub = self._stub(client)
            if stub is None:
                continue  # evicted since active_clients() snapshot
            try:
                call_with_retry(
                    self.retry_policy, "SendModel",
                    lambda s=stub: s.SendModel(
                        proto.SendModelRequest(
                            model=payload,
                            epoch=self._coord_epoch, role=self._role,
                        ),
                        timeout=self._deadlines["SendModel"],
                    ),
                    peer=client, telemetry=self.telemetry,
                    rand=self._retry_rand,
                )
            except grpc.RpcError as e:
                if is_stale_coordinator(e):
                    # We are the superseded side of a healed partition —
                    # the client is NOT failed; WE must re-base. Leave the
                    # client alive and let the round loop fence us.
                    self._handle_stale("SendModel", client, e)
                    continue
                log.warning("client %s failed during initial sync", client)
                self.telemetry.counter(
                    "fedtpu_rpc_failures_total",
                    "RpcErrors by failing RPC",
                    labels={"rpc": "SendModel"},
                ).inc()
                self.registry.mark_failed(client)
        self._did_initial_sync = True

    def _ping_backup(self, recovering: bool) -> Optional[int]:
        try:
            resp = call_with_retry(
                self.retry_policy, "CheckIfPrimaryUp",
                lambda: self.backup_stub.CheckIfPrimaryUp(
                    proto.PingRequest(
                        req=b"1" if recovering else b"0",
                        epoch=self._coord_epoch,
                    ),
                    timeout=self._deadlines["CheckIfPrimaryUp"],
                ),
                telemetry=self.telemetry,
                rand=self._retry_rand,
            )
        except grpc.RpcError as e:
            if is_stale_coordinator(e):
                # The backup promoted past us while we were partitioned;
                # our liveness probe may no longer reset its watchdog.
                self._handle_stale("CheckIfPrimaryUp", "backup", e)
            return None
        if resp.value == 1:
            # The backup acted as primary while we were down; its model is
            # ahead of ours. Pull it before training another round (the
            # reference silently reverts the backup's progress here). The
            # retry also re-requests a CRC-corrupted replica payload.
            try:
                def fetch():
                    fetched = self.backup_stub.FetchModel(
                        proto.Request(),
                        timeout=self._deadlines["FetchModel"],
                    )
                    if fetched.model:
                        self._install(fetched.model)
                        log.info("recovered newer global model from backup")

                call_with_retry(
                    self.retry_policy, "FetchModel", fetch,
                    telemetry=self.telemetry, rand=self._retry_rand,
                )
            except grpc.RpcError:
                log.warning("backup demoted but FetchModel failed")
            except wire.WireError:
                log.warning(
                    "backup demoted but its model payload stayed corrupt "
                    "after retries; keeping the local model"
                )
        return resp.value

    # --------------------------------------------------------------- fencing
    def _handle_stale(self, rpc: str, peer: str, exc: grpc.RpcError) -> None:
        """A receiver rejected us with STALE_COORDINATOR: another
        coordinator minted a higher epoch while we were partitioned. Record
        the winner's epoch (parsed from the rejection details, so the
        re-base can mint past it even if the backup is unreachable) and
        flip the fence flag — the round loop voids the in-flight round and
        re-bases (:meth:`handle_fence`). Never marks ``peer`` failed: the
        peer is healthy, WE are stale."""
        try:
            details = exc.details() or ""
            self._epoch_seen = max(
                self._epoch_seen, int(details.rsplit("<", 1)[1])
            )
        except Exception:
            pass  # malformed details: re-base still mints past our own epoch
        with self._fence_lock:
            first = not self._fenced
            self._fenced = True
        if not first:
            return
        log.warning(
            "FENCED by %s via %s: our epoch %d is stale (newest seen %d); "
            "voiding the in-flight round and re-basing",
            peer, rpc, self._coord_epoch, self._epoch_seen,
        )
        self.telemetry.counter(
            "fedtpu_ft_fenced_total",
            "times this coordinator was fenced by a STALE_COORDINATOR "
            "rejection (superseded by a higher epoch)",
        ).inc()
        self.flight.record(
            "fence", rpc=rpc, peer=peer, epoch=self._coord_epoch,
            epoch_seen=self._epoch_seen,
        )
        self.flight.dump(reason="fence")

    def handle_fence(self) -> None:
        """Post-fence re-base (docs/FAULT_TOLERANCE.md §Fencing heal
        timeline): demote the acting backup through the recovering
        handshake (``CheckIfPrimaryUp(req=b"1")`` passes the backup's
        stale check by design — the heal must work), adopt its state via
        the existing FetchModel/_install path (install_state raises our
        epoch to the winner's), then mint an epoch PAST everything seen
        and re-broadcast on the next round's initial sync. Our forked
        rounds are already voided — the fenced round never committed.

        The fence only drops once the handshake is DELIVERED: minting past
        the winner without adopting its state would re-fork the lineage —
        the exact split-brain this protocol eliminates. While the winner
        stays unreachable (an asymmetric partition healed client-side
        first, or no backup channel exists at all) the coordinator holds
        the fence — ``health()`` keeps reporting 503 — and retries every
        ``_fence_retry_s``; an acting primary in that position simply
        waits for the demotion the re-basing primary's handshake
        delivers."""
        if not self._fenced:
            return
        log.info("re-basing after fence (epoch %d, seen %d)",
                 self._coord_epoch, self._epoch_seen)
        if self.pinger is None:
            # No channel to the winning lineage: state adoption is
            # impossible from here, so resuming would fork. Hold the fence
            # until demoted (acting primary) or restarted by the operator.
            time.sleep(self._fence_retry_s)
            return
        self.pinger.recovering = True
        if self.pinger.tick() is None:
            # The heal is still partial (we are fenced via clients but the
            # backup link is down). Stay fenced and retry.
            time.sleep(self._fence_retry_s)
            return
        self._set_epoch(max(self._coord_epoch, self._epoch_seen) + 1)
        self._did_initial_sync = False
        with self._fence_lock:
            self._fenced = False
        self.flight.record("fence", event="rebased", epoch=self._coord_epoch)
        log.info("re-based: continuing as epoch %d", self._coord_epoch)

    def health(self) -> Tuple[bool, str]:
        """Honest /healthz verdict: (ok, reason). 503-worthy while fenced
        (stale coordinator pending re-base) or while the latest round
        aborted under quorum — orchestrator probes can then act instead of
        reading an unconditional 200."""
        if self._fenced:
            return False, "fenced: stale coordinator pending re-base"
        if self.history and self.history[-1].get("aborted"):
            return False, "quorum unmet: last round aborted"
        return True, "ok"

    # ------------------------------------------------------------ membership
    def _make_stub(self, address: str) -> TrainerStub:
        return TrainerStub(create_channel(
            address, compress=self.compress,
            trace_source=self._trace_source, chaos=self.chaos,
        ))

    def _stub(self, client: str) -> Optional[TrainerStub]:
        """The member's stub, or None for an (already-evicted) non-member —
        collect/broadcast workers treat None as an ordinary failure."""
        with self._member_lock:
            return self._stubs.get(client)

    def _probe_member(self, client: str) -> bool:
        stub = self._stub(client)
        if stub is None:
            return False  # evicted between dead_clients() and the probe
        return probe(
            stub, timeout=self._deadlines["HeartBeat"],
            policy=self.retry_policy, telemetry=self.telemetry,
        ) is not None

    def admit_client(self, address: str) -> dict:
        """Admit (or re-admit) a member — the Join RPC's implementation.

        The joiner is admitted DEAD and resynced through the same
        model-push path a heartbeat revival uses (:meth:`_resync` →
        ``sync_clients`` semantics): a stale joiner — fresh process, or a
        returning client whose weights predate many rounds — must hold the
        CURRENT global model before its first StartTrain, or in
        sparse-delta mode its first delta would silently corrupt the
        aggregate. If the inline resync fails the member stays dead and
        the heartbeat monitor finishes the revival on a later tick; the
        join itself still succeeded.
        """
        with self._member_lock:
            rejoin = self.registry.is_member(address)
            seat = self.registry.admit(address)
            if address not in self._stubs:
                self._stubs[address] = self._make_stub(address)
        resynced = False
        try:
            self._resync(address)
            self.registry.mark_alive(address)
            resynced = True
        except (grpc.RpcError, RuntimeError) as exc:
            log.warning(
                "join: %s admitted at seat %d but resync failed (%s); "
                "heartbeat monitor will revive it", address, seat, exc,
            )
        self.flight.record(
            "membership", event="join", client=address, seat=seat,
            version=self.registry.version, rejoin=rejoin,
        )
        return {
            "admitted": True,
            "seat": seat,
            "world": self.registry.capacity(),
            "version": self.registry.version,
            "resynced": resynced,
        }

    def remove_client(self, address: str, reason: str = "leave") -> dict:
        """Evict a member (graceful Leave, or operator action): frees its
        seat for later joiners and closes its channel. A late RPC from the
        evicted client is ignored by the tolerant registry."""
        left = self.registry.evict(address, reason=reason)
        with self._member_lock:
            stub = self._stubs.pop(address, None)
        if stub is not None:
            try:
                stub._channel.close()
            except Exception:
                pass  # a late in-flight RPC owns the channel a bit longer
        if left:
            self.flight.record(
                "membership", event="leave", client=address,
                version=self.registry.version, reason=reason,
            )
        return {"left": left, "version": self.registry.version}

    def _update_reputation(
        self, order: List[str], flagged: set, quarantined_now: set
    ) -> None:
        """Close the detection -> eviction loop: fold this round's
        screening verdicts into each participant's suspicion EWMA and run
        the escalation ladder (flagged -> quarantined -> evicted) against
        the live :class:`~fedtpu.ft.membership.MembershipTable`.

        - suspicion >= ``quarantine_at``: quarantine (the member is still
          served and screened — it can redeem itself — but its updates are
          ignored; counted into ``fedtpu_membership_quarantine_total``).
        - a quarantined member whose suspicion decays below ``release_at``
          is released (the false-positive exit).
        - ``evict_after`` consecutive quarantined rounds escalates to
          :meth:`remove_client` with reason ``quarantine`` — the roster
          change replicates to the backup like any other eviction.
        """
        sc = self._screen_cfg
        for c in order:
            s = self.registry.observe_screening(c, c in flagged, ewma=sc.ewma)
            if c in quarantined_now:
                rounds_q = self.registry.tick_quarantine(c)
                if s < sc.release_at:
                    if self.registry.release(c):
                        self.flight.record(
                            "membership", event="release", client=c,
                            suspicion=round(s, 4),
                        )
                elif sc.evict_after and rounds_q >= sc.evict_after:
                    log.warning(
                        "client %s evicted after %d quarantined rounds "
                        "(suspicion %.3f)", c, rounds_q, s,
                    )
                    self.remove_client(c, reason="quarantine")
            elif s >= sc.quarantine_at:
                if self.registry.quarantine(c):
                    self.flight.record(
                        "membership", event="quarantine", client=c,
                        suspicion=round(s, 4),
                    )

    def _membership_bytes(self) -> np.ndarray:
        """The roster snapshot as a uint8 JSON leaf for the replica/
        checkpoint pytree (flax msgpack carries variable-length arrays)."""
        blob = json.dumps(self.registry.snapshot()).encode()
        return np.frombuffer(blob, np.uint8)

    def _adopt_membership(self, leaf) -> None:
        """Adopt a replicated roster (inverse of :meth:`_membership_bytes`)
        and rebuild the stub table to match — a promoted backup then dials
        the CURRENT fleet, not the startup list it was constructed with."""
        blob = np.asarray(leaf, np.uint8).tobytes()
        if not blob:
            return  # template placeholder / membership-less checkpoint
        self.registry.restore(json.loads(blob.decode()))
        members = set(self.registry.clients)
        with self._member_lock:
            for address in members - set(self._stubs):
                self._stubs[address] = self._make_stub(address)
            for address in set(self._stubs) - members:
                self._stubs.pop(address)

    def start_gate(self, address: str):
        """Host the membership gate — a gRPC server answering Join/Leave on
        ``address`` (``--gate`` on the server CLI). The coordinator
        otherwise only DIALS OUT; this is its sole inbound surface, so the
        round loop never competes with admissions for a listener."""
        gate = _MembershipGate(self)
        self._gate_server = create_server(
            address, gate, compress=self.compress, chaos=self.chaos
        )
        self._gate_server.start()
        log.info("membership gate serving on %s", address)
        return self._gate_server

    def stop_gate(self) -> None:
        if self._gate_server is not None:
            self._gate_server.stop(0)
            self._gate_server = None

    # ---------------------------------------------------------- observability
    def _trace_source(self) -> Optional[propagate.TraceContext]:
        """Per-RPC propagation context (runs on the issuing thread, so the
        innermost open span — the collect worker's ``client_rpc`` — becomes
        the remote parent). None below trace mode: the interceptor then
        forwards the call untouched."""
        tracer = self.telemetry.tracer
        if tracer is None:
            return None
        return propagate.TraceContext(
            trace_id=tracer.trace_id,
            span_id=tracer.current_id() or 0,
            role=self.telemetry.role or "primary",
            round=self._round_counter,
        )

    def status_snapshot(self) -> dict:
        """``/statusz`` feed: live round/phase (from the round loop's
        :class:`StatusBoard` updates) + client liveness + FT counters +
        the last round record's phase timings."""
        snap = self.status.snapshot()
        reg = self.registry
        snap.update(
            pid=os.getpid(),
            clients={
                "alive": reg.active_clients(),
                "dead": reg.dead_clients(),
            },
            # The full membership block: epoch/size/capacity + roster —
            # what a churn soak (or an operator watching tools/statusz.py)
            # audits joins and evictions against.
            membership=reg.status(),
            # Leak axes (also exported as gauges): current RSS and the
            # last round's flat collect-buffer footprint.
            mem={
                "rss_bytes": process_rss_bytes(),
                "buffer_bytes": (
                    int(self.history[-1].get("buffer_bytes", 0))
                    if self.history else 0
                ),
                # Tier accounting (docs/ARCHITECTURE.md §Multi-tier):
                # which tier's buffer this is, and the partial rows held
                # toward an in-flight root combine (0 between rounds).
                "tier": "root" if self.tier_fanout else "flat",
                "partial_rows_buffered": (
                    int(
                        self.telemetry.registry.gauge(
                            "fedtpu_partial_rows_buffered", ""
                        ).value
                    )
                    if self.tier_fanout and self.telemetry.enabled else 0
                ),
            },
            stragglers_in_flight=sorted(
                c for c, t in self._inflight.items() if t.is_alive()
            ),
            rounds_completed=sum(
                1 for rec in self.history if not rec.get("aborted")
            ),
            rounds_aborted=sum(
                1 for rec in self.history if rec.get("aborted")
            ),
            # Fencing block (docs/FAULT_TOLERANCE.md §Fencing): which
            # lineage this coordinator is, and whether it has been
            # superseded and is pending re-base.
            fencing={
                "epoch": self._coord_epoch,
                "role": "acting" if self._role == 2 else "primary",
                "fenced": self._fenced,
            },
        )
        tel = self.telemetry
        if tel.enabled:
            snap["heartbeat_misses"] = tel.registry.counter(
                "fedtpu_ft_heartbeat_misses_total",
                "heartbeat probes of dead clients that stayed dead",
            ).value
        if tel.tracer is not None:
            snap["trace_id"] = tel.tracer.trace_id
        if self.history:
            last = self.history[-1]
            snap["last_round"] = {
                k: last[k]
                for k in (
                    "participants", "stragglers", "bytes_up", "bytes_down",
                    "bytes_up_by_codec",
                    "t_collect_s", "t_decode_s", "t_h2d_s", "t_aggregate_s",
                    "t_post_barrier_s", "t_round_s", "pipeline",
                    "client_latency",
                )
                if k in last
            }
        # Per-codec wire-byte table (cumulative across rounds) and, under
        # the adaptive policy, the live per-client cost table (docs/
        # OPERATIONS.md §Adaptive codec).
        with self._codec_bytes_lock:
            if self._codec_bytes_up:
                snap["codec_bytes_up"] = dict(self._codec_bytes_up)
        if self._codec_policy is not None:
            snap["codec_policy"] = self._codec_policy.snapshot()
        if self.compile_watcher is not None:
            snap["compile"] = self.compile_watcher.snapshot()
        return snap

    # ------------------------------------------------------------ round loop
    def round(self) -> dict:
        """One synchronous FedAvg round; returns the round record.

        Wraps :meth:`_round_body` in the top-level ``round`` span and feeds
        the cumulative registry (bytes, phase histograms, straggler counts)
        after the record is built — both no-ops below their telemetry mode.
        """
        tel = self.telemetry
        with tel.span("round", round=self._round_counter) as rspan:
            rec = self._round_body(rspan)
        self.status.update(phase="idle")
        if tel.enabled:
            # Leak axes for the long-haul soaks (docs/OBSERVABILITY.md):
            # flat over a healthy 1k-round churn soak, monotone growth is
            # the failure signature. Sampled once per round — a /proc read
            # is microseconds against a round.
            tel.gauge(
                "fedtpu_process_rss_bytes",
                "current resident set size of this process",
            ).set(process_rss_bytes())
            tel.gauge(
                "fedtpu_buffer_bytes",
                "flat collect-buffer bytes held by the last round "
                "(host rows + device twin; 0 on the barrier path), by "
                "tier: 'flat' = one-tier federation, 'root' = the tiered "
                "root's [aggregators, P] surface, 'leaf' = a sub-"
                "aggregator's [cohort, P] buffer",
                labels={"tier": "root" if self.tier_fanout else "flat"},
            ).set(rec.get("buffer_bytes", 0))
            if self.tier_fanout:
                # The round's partial rows are combined and released.
                tel.gauge(
                    "fedtpu_partial_rows_buffered",
                    "partial-sum rows (one per sub-aggregator) buffered "
                    "toward this round's root combine",
                ).set(0)
        if rec.get("aborted"):
            # Sub-quorum abort: the abort already logged its own flight
            # event and counter inside _round_body; it is NOT a completed
            # round (the counter below would lie to dashboards).
            return rec
        # Cumulative per-codec byte ledger for /statusz — independent of
        # the telemetry mode (the round record is API either way).
        by_codec = rec.get("bytes_up_by_codec", {})
        if by_codec:
            with self._codec_bytes_lock:
                for codec_name, nb in by_codec.items():
                    self._codec_bytes_up[codec_name] = (
                        self._codec_bytes_up.get(codec_name, 0) + nb
                    )
        self.flight.record(
            "round",
            round=self._round_counter - 1,
            participants=rec["participants"],
            stragglers=rec["stragglers"],
            t_collect_s=rec["t_collect_s"],
            t_aggregate_s=rec["t_aggregate_s"],
        )
        if tel.enabled:
            tel.counter(
                "fedtpu_rounds_completed_total",
                "synchronous FedAvg rounds completed by this server",
            ).inc()
            tel.counter(
                "fedtpu_rpc_bytes_up_total",
                "client -> server StartTrain reply bytes (successful)",
            ).inc(rec["bytes_up"])
            tel.counter(
                "fedtpu_rpc_bytes_down_total",
                "server -> client/backup broadcast bytes (successful)",
            ).inc(rec["bytes_down"])
            # Per-codec twins of the unlabeled byte counter above (the
            # unlabeled series stays the authoritative total — dashboards
            # and tests pin it — the labeled series adds the breakdown).
            for codec_name, nb in rec.get("bytes_up_by_codec", {}).items():
                tel.counter(
                    "fedtpu_rpc_bytes_up_total",
                    "client -> server StartTrain reply bytes (successful)",
                    labels={"codec": codec_name},
                ).inc(nb)
            tel.counter(
                "fedtpu_stragglers_total",
                "client-rounds lost to stragglers (deadline, in-flight)",
            ).inc(rec["stragglers"])
            for ph in ("collect", "decode", "h2d", "aggregate"):
                tel.histogram(
                    "fedtpu_round_phase_seconds",
                    "per-round phase wall time by phase label",
                    labels={"phase": ph},
                ).observe(rec[f"t_{ph}_s"])
            if "t_round_s" in rec:
                tel.gauge(
                    "fedtpu_step_time_seconds",
                    "wall time of the last round dispatch, per round",
                ).set(rec["t_round_s"])
        return rec

    def _round_body(self, rspan) -> dict:
        cfg = self.cfg
        tel = self.telemetry
        # Captured ONCE for the whole round: collect workers (including a
        # straggler's late retry after the counter advanced) must all
        # advertise the same lineage round in their TrainRequests — it is
        # the client-side replay-detection signal of disaster recovery.
        lineage_round = self._round_counter
        self.status.update(round=self._round_counter, phase="collect")
        if self.chaos is not None:
            # Advertise the lineage round so rounds= fault windows key on it.
            self.chaos.set_round(self._round_counter)
        if not self._did_initial_sync:
            self.sync_clients()
        # Roster snapshot for this round: cohort selection runs over the
        # LIVE set of the CURRENT membership; a join/leave landing mid-round
        # takes effect next round. Quarantined members stay in the launch —
        # they are SERVED (broadcasts, StartTrain) and keep generating
        # screening evidence so they can redeem themselves — but their
        # updates are dropped before the combine, whatever arrives.
        active = self.registry.active_clients()
        quarantined_now = set(self.registry.quarantined_clients())
        members_now = self.registry.size
        membership_version = self.registry.version
        # The round record's alive mask spans THIS snapshot's roster — a
        # mid-round admit would otherwise tear the record (mask longer
        # than `world`). Alive state itself is read at record time, so a
        # member dying mid-round (retry exhaustion) still shows.
        roster_now = self.registry.clients
        # Random client subsampling (engine parity: _alive_for_round; the
        # reference always uses every live client). Sampled-out clients skip
        # this round's StartTrain but still receive the broadcast.
        frac = cfg.fed.participation_fraction
        if frac < 1.0 and active:
            # Seeded from the lineage-wide round counter (not len(history),
            # which restarts at 0 after failover and would re-correlate the
            # subsampling draws across server generations).
            rng = np.random.default_rng(
                cfg.data.seed * 7919 + self._round_counter
            )
            k = max(1, int(round(frac * len(active))))
            active = sorted(
                rng.choice(np.asarray(active), size=k, replace=False).tolist()
            )
        # Partition width = SEAT capacity (freed seats included): stable
        # under steady churn — a joiner reuses an evicted member's seat, so
        # every other client's shard stays put — and grows only when the
        # roster genuinely outgrows it.
        world = self.registry.capacity()
        tiered = self.tier_fanout > 0
        if tiered:
            # Tier mode: world spans the CLIENT data partition, not the
            # aggregator roster — aggregator seat j relays ranks
            # [j*fanout, (j+1)*fanout) to its cohort, so the tiers tile the
            # dataset without coordination and a flat federation of the
            # same world trains identical shards (the parity pins rely on
            # this).
            world = world * self.tier_fanout
        # Host copies of the global model are only needed for dense replies /
        # sparse templates; build them lazily (in topk steady state the full
        # device->host transfer would otherwise run every round for nothing).
        cache: Dict[str, Any] = {}
        cache_lock = threading.Lock()

        def global_host():
            with cache_lock:
                if "g" not in cache:
                    cache["g"] = {
                        "params": jax.tree.map(np.asarray, self.params),
                        "batch_stats": jax.tree.map(np.asarray, self.batch_stats),
                    }
                return cache["g"]

        def delta_template():
            with cache_lock:
                if "d" not in cache:
                    cache["d"] = {
                        "params": jax.tree.map(
                            lambda s: np.zeros(s.shape, s.dtype), self.params
                        ),
                        "batch_stats": jax.tree.map(
                            lambda s: np.zeros(s.shape, s.dtype), self.batch_stats
                        ),
                    }
                return cache["d"]

        # results[client] = (delta_tree | row_index, num_examples)
        results: Dict[str, tuple] = {}
        # Straggler attribution: per-client StartTrain wall (RPC + decode,
        # retries included) recorded by each collect worker under its own
        # key (GIL-atomic single-key writes, same pattern as `results`).
        # Summarised to p50/p95/p99 + top-k slowest on the round record.
        latencies: Dict[str, float] = {}
        # Wire + phase accounting: thread-safe counters (fedtpu.obs), NOT
        # bare mutable cells — collect workers increment them concurrently,
        # and unsynchronised `x[0] += n` read-modify-writes can drop
        # updates. Always on (the round record is API, whatever the
        # telemetry mode).
        bytes_up = Counter()  # client -> server payload bytes this round
        bytes_down = Counter()  # only successful sends count
        # Per-codec wire accounting (docs/OBSERVABILITY.md §Codec bytes):
        # which codec each surviving reply ACTUALLY used (the decode-side
        # `_codec` record tag; dense payloads count as 'none') and its
        # payload bytes. Single-key writes per collect worker (the
        # `results` pattern); feeds the labeled rpc byte counters, the
        # /statusz per-codec table and the adaptive policy's observations.
        codec_of: Dict[str, tuple] = {}  # client -> (codec_name, bytes)
        # Tier mode: total leaf clients behind this round's partials (each
        # SubmitPartialReply reports its cohort's contributor count) — the
        # round record's participants stay the DIRECT peers (aggregators).
        clients_in = Counter()
        stream = self.server_pipeline == "stream"
        # Per-round phase timing (satellite of the streaming pipeline):
        # decode / H2D are summed across clients; collect and the
        # post-barrier gap are wall-clock marks in this thread. Reported
        # on the round record so the overlap win shows up in ordinary run
        # logs, not just the microbench.
        decode_s = Counter()
        h2d_s = Counter()
        # Streaming collect state: one preallocated host row per launched
        # client (decode target) and ONE device [launch, P] buffer that
        # arriving rows are written into in place (donated
        # dynamic_update_slice), so by the time the last reply lands the
        # whole delta block is already device-resident. All of it is
        # PER-ROUND (like `results`): a straggler from an earlier round
        # still holds references to ITS round's buffers, so its late write
        # can never corrupt this round's rows.
        row_of: Dict[str, int] = {}
        host_rows: List[np.ndarray] = []
        dev_buf: List[Any] = []
        stream_lock = threading.Lock()

        def train_one(rank: int, client: str, stub: TrainerStub) -> None:
            # Runs on a collect worker thread: the client span parents to
            # this round's span EXPLICITLY (thread-local nesting cannot
            # cross threads); decode/h2d spans below nest under it via the
            # worker's own stack.
            # Adaptive codec: ONE choice per client per round, made before
            # the attempt so retries re-request the same codec (a retried
            # reply must match its observation).
            codec_req = (
                self._codec_policy.choose(rank)
                if self._codec_policy is not None else None
            )

            def attempt():
                # One full RPC attempt INCLUDING reply decode: a payload
                # that fails the wire CRC (corrupted in flight) raises
                # WireError here and is re-requested by the retry wrapper
                # — reject-and-retry, never "silently lose the client's
                # round" (the pre-policy behavior: the worker thread died
                # with the exception and the reply just vanished).
                if tiered:
                    # One pulled partial reduce: the aggregator fans
                    # StartTrain out to its cohort, folds the replies to a
                    # pre-weighted sum and answers with ONE FSP1
                    # partial_flat record — the root's per-peer work below
                    # is a single straight-copy decode, whatever the
                    # cohort size (bench.py --fanin-microbench).
                    reply = stub.SubmitPartial(
                        proto.SubmitPartialRequest(
                            rank_base=rank * self.tier_fanout, world=world,
                            round=lineage_round, epoch=self._coord_epoch,
                        ),
                        timeout=self._deadlines["SubmitPartial"],
                    )
                    data = reply.record
                    clients_in.inc(reply.clients)
                else:
                    reply = stub.StartTrain(
                        proto.TrainRequest(
                            rank=rank, world=world, round=lineage_round,
                            epoch=self._coord_epoch,
                            codec=proto.CODEC_IDS.get(codec_req, 0),
                        ),
                        timeout=self._deadlines["StartTrain"],
                    )
                    data = reply.message
                if stream:
                    # Decode straight into this client's row — no
                    # per-leaf template trees, no later leaf-by-leaf
                    # stacking. A retried attempt rewrites the row from
                    # scratch (both decoders write every real coordinate).
                    row = host_rows[0][row_of[client]]
                    t0 = time.monotonic()
                    with tel.span("decode", client=client):
                        if sparse.is_sparse_payload(data):
                            extra = sparse.decode_into_row(
                                data, self._flat_layout.sizes, row
                            )
                        else:
                            # Dense full weights -> delta against the
                            # round's global, written into the row leaf
                            # slices.
                            extra = wire.decode_into_row(
                                data,
                                _payload_template(self.model, cfg),
                                global_host(),
                                row,
                            )
                    t1 = time.monotonic()
                    kind = extra.pop("_codec", None)
                    # Ship the row NOW: the transfer (and the in-place
                    # device-buffer write) overlaps the remaining
                    # clients' network wait instead of queueing behind
                    # the barrier. A deadline straggler landing AFTER
                    # the round closed its buffer (the pop in the
                    # finalize below) skips the device write: its reply
                    # is excluded from this round anyway, and writing
                    # would donate a buffer handle the finalize may
                    # still be reading.
                    with tel.span("h2d", client=client):
                        dev_row = jax.device_put(row)
                        with stream_lock:
                            if dev_buf:
                                dev_buf[0] = self._set_row(
                                    dev_buf[0], dev_row, row_of[client]
                                )
                    t2 = time.monotonic()
                    decode_s.inc(t1 - t0)
                    h2d_s.inc(t2 - t1)
                    # Tier mode: the combine weight is the partial's summed
                    # example weight (the leaf already applied cfg.fed
                    # weighting per client), not a per-client count.
                    out = (
                        row_of[client],
                        float(extra["weight_sum" if tiered
                                    else "num_examples"]),
                    )
                elif sparse.is_sparse_payload(data):
                    t0 = time.monotonic()
                    with tel.span("decode", client=client):
                        deltas, extra = sparse.decode(
                            data, delta_template()
                        )
                    decode_s.inc(time.monotonic() - t0)
                    kind = extra.pop("_codec", None)
                    out = (deltas, float(extra["num_examples"]))
                else:
                    t0 = time.monotonic()
                    with tel.span("decode", client=client):
                        tree = wire.decode(
                            data, _payload_template(self.model, cfg)
                        )
                        # Dense full weights -> delta against the
                        # round's global, so dense and sparse replies
                        # aggregate uniformly.
                        delta = jax.tree.map(
                            lambda a, g: np.asarray(a) - g,
                            {"params": tree["params"],
                             "batch_stats": tree["batch_stats"]},
                            global_host(),
                        )
                    decode_s.inc(time.monotonic() - t0)
                    kind = None  # dense full-weight payload
                    out = (delta, float(tree["num_examples"]))
                # Count only the attempt that survived decode.
                bytes_up.inc(len(data))
                codec_of[client] = (_CODEC_OF_KIND.get(kind, "none"), len(data))
                return out

            rpc_name = "SubmitPartial" if tiered else "StartTrain"
            try:
                t_rpc = time.monotonic()
                with tel.span("submit_partial" if tiered else "client_rpc",
                              parent=rspan.id, client=client):
                    results[client] = call_with_retry(
                        self.retry_policy, rpc_name, attempt,
                        peer=client, telemetry=tel,
                        rand=self._retry_rand,
                    )
                latencies[client] = time.monotonic() - t_rpc
                tel.histogram(
                    "fedtpu_client_rpc_seconds",
                    "per-client StartTrain wall time (RPC + decode, "
                    "retries included; successful rounds only)",
                ).observe(latencies[client])
                if self._codec_policy is not None and client in codec_of:
                    # Teach the policy the codec the reply ACTUALLY used
                    # (a legacy client ignoring the request still updates
                    # the right codec's estimate).
                    used, nbytes = codec_of[client]
                    self._codec_policy.observe(
                        rank, used, nbytes, latencies[client]
                    )
            except (grpc.RpcError, wire.WireError) as e:
                if is_stale_coordinator(e):
                    # The peer has seen a higher coordinator epoch: WE are
                    # the stale side of a healed partition. (In tier mode
                    # the aggregator RELAYS a cohort client's rejection
                    # upstream on the same typed status, so the evidence
                    # reaches here whichever tier observed the newer
                    # lineage.) The peer is healthy — never mark it
                    # failed; flip the fence and let the round loop void
                    # this round and re-base.
                    self._handle_stale(rpc_name, client, e)
                    return
                # Only a FATAL status or an exhausted retry budget lands
                # here — the designed path to mark_failed. In tier mode
                # that includes an aggregator's typed SUB_QUORUM /
                # UNSYNCED_AGGREGATOR aborts (FAILED_PRECONDITION, never
                # retried): the whole cohort becomes ONE masked row and
                # the heartbeat/resync machinery revives the aggregator.
                if isinstance(e, grpc.RpcError):
                    log.warning(
                        "%s %s failed during %s: %s %s",
                        "aggregator" if tiered else "client", client,
                        rpc_name, e.code(), e.details(),
                    )
                else:
                    log.warning(
                        "%s %s reply still corrupt after retries: %s",
                        client, rpc_name, e,
                    )
                tel.counter(
                    "fedtpu_rpc_failures_total",
                    "RpcErrors by failing RPC",
                    labels={"rpc": rpc_name},
                ).inc()
                self.registry.mark_failed(client)

        # A straggler whose previous-round StartTrain is STILL in flight must
        # not be handed a second concurrent StartTrain (the two handlers
        # would race on the client's trainer state / error-feedback
        # residual); it sits this round out and rejoins once its old call
        # drains.
        still_busy = [
            c for c in active
            if c in self._inflight and self._inflight[c].is_alive()
        ]
        if still_busy:
            log.warning("stragglers still in flight, skipping: %s", still_busy)
        # In sparse-delta mode a client whose LAST broadcast is still in
        # flight has a stale baseline: its top-k delta (and error-feedback
        # residual) would be computed against a model the server has since
        # replaced, silently corrupting aggregation (the hazard
        # sync_clients' docstring warns about). It sits training out until
        # its send drains. Dense mode keeps training: full weights are
        # delta'd against the CURRENT global server-side, so a stale base
        # is ordinary bounded staleness, not corruption.
        unsynced = []
        if cfg.fed.compression != "none":
            unsynced = [
                c for c in active
                if c not in still_busy
                and c in self._sends and self._sends[c].is_alive()
            ]
            if unsynced:
                log.warning(
                    "sparse mode: broadcast still in flight, baselines "
                    "stale, sitting out: %s", unsynced,
                )
        # Stub snapshot for the launch (under the member lock): an eviction
        # landing after this point still completes the already-launched
        # RPC on the old channel; one landing before it drops the client
        # from the launch list.
        with self._member_lock:
            stub_of = dict(self._stubs)
        # Each client trains its OWN seat's shard, regardless of which
        # clients were sampled or skipped this round: rank is the client's
        # stable membership SEAT, not its position in the launch list.
        # Positional ranks would retrain shards 0..k-1 every round under
        # participation sampling (shards k.. never trained) and move a
        # client's shard between rounds — breaking engine parity (the
        # engine's alive-mask semantics) and run_async, which already
        # assigns seat ranks.
        rank_of = self.registry.seat_map()
        launch = [
            c for c in active
            if c not in still_busy and c not in unsynced
            and c in stub_of and c in rank_of
        ]
        if stream and launch:
            row_of.update({c: i for i, c in enumerate(launch)})
            padded = self._flat_layout.padded
            host_rows.append(np.zeros((len(launch), padded), np.float32))
            buf = jnp.zeros((len(launch), padded), jnp.float32)
            if tiered:
                # Tier mode: the combine surface is [aggregators, P] —
                # shard it on the ROW axis so each local device owns whole
                # partial rows and the finalize's axis-0 sum becomes one
                # cross-device reduce (no-op on a single device, where the
                # helper degrades to ordinary placement).
                from fedtpu.parallel.mesh import partial_row_sharding

                buf = jax.device_put(
                    buf, partial_row_sharding(len(launch))
                )
            dev_buf.append(buf)
            if tiered and tel.enabled:
                tel.gauge(
                    "fedtpu_partial_rows_buffered",
                    "partial-sum rows (one per sub-aggregator) buffered "
                    "toward this round's root combine",
                ).set(len(launch))
        t_launch = time.monotonic()
        with tel.span("collect", launched=len(launch)):
            threads = {
                client: threading.Thread(
                    target=train_one,
                    args=(rank_of[client], client, stub_of[client]),
                )
                for client in launch
            }
            for t in threads.values():
                t.start()
            if self.round_deadline_s is None:
                for t in threads.values():
                    t.join()
                stragglers = still_busy + unsynced
            else:
                deadline = time.monotonic() + self.round_deadline_s
                for t in threads.values():
                    t.join(max(0.0, deadline - time.monotonic()))
                stragglers = still_busy + unsynced + [
                    c for c, t in threads.items() if t.is_alive()
                ]
                if stragglers:
                    log.warning(
                        "round deadline %.1fs hit; aggregating without %s",
                        self.round_deadline_s, stragglers,
                    )
        t_barrier = time.monotonic()
        # Merge this round's threads over the surviving prior entries: a
        # straggler launched two rounds ago can still be running even though
        # it was never in THIS round's `threads` — dropping it would hand
        # the client a second concurrent StartTrain next round.
        self._inflight = {
            c: t
            for c, t in {**self._inflight, **threads}.items()
            if t.is_alive()
        }

        # Snapshot completed replies under a NEW name: train_one writes to
        # the `results` free variable, so a straggler finishing
        # mid-aggregation lands its late write in the discarded per-round
        # dict, never in this round's inputs.
        completed = {
            c: results[c]
            for c in active
            if c in results and c not in stragglers
        }

        # Fenced mid-round (a collect worker hit STALE_COORDINATOR): VOID
        # the round before anything commits — same clean-abort contract as
        # the quorum path below (global model and optimizer state untouched,
        # lineage counter frozen). Whatever replies arrived belong to a
        # superseded lineage; run() re-bases via handle_fence before the
        # next attempt.
        if self._fenced:
            with stream_lock:
                dev_buf.clear()
            self._did_initial_sync = False
            log.warning(
                "round %d voided: coordinator fenced mid-round (epoch %d "
                "superseded); global model untouched",
                self._round_counter, self._coord_epoch,
            )
            tel.counter(
                "fedtpu_round_aborts_total",
                "rounds aborted below quorum (global model untouched)",
            ).inc()
            self.flight.record(
                "round_abort", round=self._round_counter,
                participants=len(completed), fenced=True,
            )
            rec = {
                "round": self._round_counter,
                "epoch": self._coord_epoch,
                "participants": len(completed),
                "stragglers": len(stragglers),
                "world": world,
                "alive": [self.registry.is_alive(c) for c in roster_now],
                "membership_version": membership_version,
                "aborted": True,
                "fenced": True,
                "bytes_up": int(bytes_up.value),
                "bytes_down": 0,
                "pipeline": self.server_pipeline,
                "t_collect_s": round(t_barrier - t_launch, 6),
                "t_decode_s": round(decode_s.value, 6),
                "t_h2d_s": round(h2d_s.value, 6),
                "t_aggregate_s": 0.0,
                "t_post_barrier_s": 0.0,
            }
            self.history.append(rec)
            return rec

        # Round quorum (cfg.fed.round_quorum, fraction of this round's
        # SAMPLED clients): below it the round aborts CLEANLY — the global
        # model and server-optimizer state are left bit-identical to their
        # pre-round values (nothing below this point runs, so there is no
        # partial average to undo), the lineage counter does not advance,
        # and the caller re-runs the round (run()'s abort loop). Clearing
        # _did_initial_sync forces a re-broadcast of the unchanged global
        # before the re-run: clients that DID train this round have
        # advanced their local weights, and in sparse-delta mode their next
        # delta must be computed against the server's global, not that
        # drift.
        quorum = cfg.fed.round_quorum
        # Quorum counts against the CURRENT membership (post join/evict),
        # never the startup roster: a federation where half the members
        # are dead-but-not-evicted must abort rather than quietly commit
        # with the survivors, and EVICTING the departed (shrinking the
        # denominator) is the operator's way to move on. Under
        # participation sampling (frac < 1) the sampled subset is the
        # round's electorate, so the base stays the sampled count.
        quorum_base = len(active) if frac < 1.0 else members_now
        needed = max(1, math.ceil(quorum * quorum_base)) if quorum > 0 else 0
        if needed and len(completed) < needed:
            with stream_lock:
                dev_buf.clear()  # close the stream buffer; rows discarded
            self._did_initial_sync = False
            log.warning(
                "round %d aborted: %d/%d replies below quorum %.2f of %d "
                "members; global model untouched, will re-run",
                self._round_counter, len(completed), needed, quorum,
                quorum_base,
            )
            tel.counter(
                "fedtpu_round_aborts_total",
                "rounds aborted below quorum (global model untouched)",
            ).inc()
            self.flight.record(
                "round_abort", round=self._round_counter,
                participants=len(completed), quorum_needed=needed,
            )
            rec = {
                "round": self._round_counter,
                "epoch": self._coord_epoch,
                "participants": len(completed),
                "stragglers": len(stragglers),
                "world": world,
                "alive": [self.registry.is_alive(c) for c in roster_now],
                "membership_version": membership_version,
                "aborted": True,
                "quorum_needed": needed,
                "bytes_up": int(bytes_up.value),
                "bytes_down": 0,
                "pipeline": self.server_pipeline,
                "t_collect_s": round(t_barrier - t_launch, 6),
                "t_decode_s": round(decode_s.value, 6),
                "t_h2d_s": round(h2d_s.value, 6),
                "t_aggregate_s": 0.0,
                "t_post_barrier_s": 0.0,
            }
            self.history.append(rec)
            return rec

        self.status.update(phase="aggregate")
        order = [c for c in active if c in completed]
        srows = None
        if stream and dev_buf:
            # Close the round's buffer under the lock first: a deadline
            # straggler must not donate-invalidate the handle we are about
            # to read. When a launched client failed or straggled, gather
            # the surviving rows so the reduce runs over EXACTLY the rows
            # the barrier path would stack (same [k, P] shape -> the same
            # order-stable reduce -> bit parity).
            with stream_lock:
                srows = dev_buf.pop()
            if order != launch:
                srows = srows[
                    jnp.asarray([row_of[c] for c in order], jnp.int32)
                ]
        # ---- fused screening + reputation (docs/FAULT_TOLERANCE.md) ----
        screened_names: List[str] = []
        if self._screen_jit is not None and order:
            with tel.span("screen", participants=len(order)):
                if stream:
                    rows_in = srows  # already device-resident, zero syncs
                else:
                    from fedtpu.ops import flat as flat_ops

                    host = np.zeros(
                        (len(order), self._screen_layout.padded), np.float32
                    )
                    for i, c in enumerate(order):
                        flat_ops.pack_row_host(
                            self._screen_layout, completed[c][0], out=host[i]
                        )
                    rows_in = jnp.asarray(host)
                # Quarantined rows must not pollute the reference stats
                # (median direction, median/MAD) but still get verdicts.
                live = jnp.asarray(
                    [c not in quarantined_now for c in order], jnp.float32
                )
                keep, _sstats = self._screen_jit(rows_in, live)
                keep = np.asarray(keep)
            screened_names = [
                c for i, c in enumerate(order) if not bool(keep[i])
            ]
            self._update_reputation(
                order, set(screened_names), quarantined_now
            )
            if screened_names:
                log.warning(
                    "round %d: screening rejected %s",
                    self._round_counter, screened_names,
                )
                tel.counter(
                    "fedtpu_screening_rejected_total",
                    "client rows rejected by the fused screening stage, "
                    "by surface",
                    labels={"surface": "server"},
                ).inc(len(screened_names))
        # Drop screened rows AND anything a quarantined client delivered —
        # a quarantined (or just-screened) late reply is log-and-ignored
        # exactly like an evicted id's, never aggregated.
        dropped = set(screened_names) | (quarantined_now & set(completed))
        if quarantined_now & set(completed):
            log.info(
                "round %d: ignoring quarantined updates from %s",
                self._round_counter, sorted(quarantined_now & set(completed)),
            )
        if dropped:
            keep_idx = [
                i for i, c in enumerate(order) if c not in dropped
            ]
            if stream and srows is not None and len(keep_idx) != len(order):
                srows = srows[jnp.asarray(keep_idx, jnp.int32)]
            order = [c for c in order if c not in dropped]

        if order:
            with tel.span("aggregate", participants=len(order)):
                if cfg.fed.weighted or tiered:
                    # Tier mode always takes this arm: completed[c][1] is
                    # the partial's WEIGHT SUM — the leaf already applied
                    # the configured weighting (example counts or 1.0 per
                    # client), so an unweighted federation's partials carry
                    # the cohort's contributor count here.
                    weights = jnp.asarray(
                        [completed[c][1] for c in order], jnp.float32
                    )
                else:
                    weights = jnp.ones((len(order),), jnp.float32)
                if stream:
                    # The rows are already device-resident (shipped on
                    # arrival) — the only post-barrier work is ONE fused
                    # finalize over the surviving rows. Tier mode's rows
                    # are pre-weighted partial SUMS and take the
                    # single-division combine (_finalize_partial_impl).
                    rows = srows
                    finalize = (
                        self._finalize_partial if tiered
                        else self._finalize_stream
                    )
                    new_global, self._server_opt_state = (
                        finalize(
                            {"params": self.params,
                             "batch_stats": self.batch_stats},
                            rows,
                            weights,
                            self._server_opt_state,
                        )
                    )
                else:
                    stacked = jax.tree.map(
                        lambda *leaves: jnp.stack(leaves),
                        *[completed[c][0] for c in order],
                    )
                    new_global, self._server_opt_state = self._aggregate(
                        {"params": self.params,
                         "batch_stats": self.batch_stats},
                        stacked,
                        weights,
                        self._server_opt_state,
                        jnp.asarray(self._round_counter, jnp.int32),
                    )
                self.params = new_global["params"]
                self.batch_stats = new_global["batch_stats"]
                # Block for the timing marks: the broadcast below needs the
                # values host-side moments later anyway (model_bytes), so
                # this costs nothing and makes the post-barrier gap honest.
                jax.block_until_ready(self.params)
        t_done = time.monotonic()
        # Advance the lineage counter BEFORE replication: the replica must
        # carry the next round's index, or a promoted backup would redraw
        # this round's DP noise key against a different aggregate.
        self._round_counter += 1

        self.status.update(phase="broadcast")
        payload = self.model_bytes()
        # Backup first (parity: replication before client broadcast,
        # src/server.py:141-153). The backup gets the replica payload —
        # model + server-optimizer moments — not the client payload.
        if self.backup_stub is not None:
            replica = self.replica_bytes()
            try:
                with tel.span("replicate", parent=rspan.id):
                    call_with_retry(
                        self.retry_policy, "SendModel",
                        lambda: self.backup_stub.SendModel(
                            proto.SendModelRequest(
                                model=replica,
                                epoch=self._coord_epoch, role=self._role,
                            ),
                            timeout=self._deadlines["SendModel"],
                        ),
                        peer="backup", telemetry=tel,
                        rand=self._retry_rand,
                    )
                bytes_down.inc(len(replica))
            except grpc.RpcError as e:
                if is_stale_coordinator(e):
                    self._handle_stale("Replicate", "backup", e)
                else:
                    log.warning("backup unreachable during replication")
                    tel.counter(
                        "fedtpu_rpc_failures_total",
                        "RpcErrors by failing RPC",
                        labels={"rpc": "Replicate"},
                    ).inc()

        def send_one(client: str) -> None:
            stub = self._stub(client)
            if stub is None:
                return  # evicted since the broadcast list was drawn
            try:
                with tel.span("broadcast", parent=rspan.id, client=client):
                    call_with_retry(
                        self.retry_policy, "SendModel",
                        lambda: stub.SendModel(
                            proto.SendModelRequest(
                                model=payload,
                                epoch=self._coord_epoch, role=self._role,
                            ),
                            timeout=self._deadlines["SendModel"],
                        ),
                        peer=client, telemetry=tel,
                        rand=self._retry_rand,
                    )
                bytes_down.inc(len(payload))
            except grpc.RpcError as e:
                if is_stale_coordinator(e):
                    self._handle_stale("SendModel", client, e)
                    return  # WE are stale; the client stays alive
                log.warning(
                    "client %s failed during SendModel: %s %s",
                    client, e.code(), e.details(),
                )
                tel.counter(
                    "fedtpu_rpc_failures_total",
                    "RpcErrors by failing RPC",
                    labels={"rpc": "SendModel"},
                ).inc()
                self.registry.mark_failed(client)

        # A client whose PREVIOUS round's broadcast is still in flight sits
        # this broadcast out: two concurrent SendModels to one client can
        # land out of order and install the older model last, silently
        # desyncing it for a round. (Mirrors the _inflight guard for
        # StartTrain.) The skipped client catches up next round — same
        # at-most-one-round-stale guarantee a straggler already has.
        send_busy = [
            c for c in self.registry.active_clients()
            if c in self._sends and self._sends[c].is_alive()
        ]
        if send_busy:
            log.warning("previous broadcast still in flight, skipping: %s",
                        send_busy)
        send_threads = {
            c: threading.Thread(target=send_one, args=(c,))
            for c in self.registry.active_clients()
            if c not in send_busy
        }
        for t in send_threads.values():
            t.start()
        if self.round_deadline_s is None:
            for t in send_threads.values():
                t.join()
        else:
            # The broadcast gets its own deadline budget too — an overloaded
            # client's slow SendModel+eval must not re-introduce the
            # blocking-on-slowest behavior the flag removes. A send still in
            # flight simply keeps running; RpcError marks failure as usual.
            deadline = time.monotonic() + self.round_deadline_s
            for t in send_threads.values():
                t.join(max(0.0, deadline - time.monotonic()))
        self._sends = {
            c: t
            for c, t in {**self._sends, **send_threads}.items()
            if t.is_alive()
        }

        rec = {
            # The LINEAGE round index (monotone across failovers and
            # rolling upgrades — the replica carries the counter), vs
            # "step", each generation's local 0-based count. The churn
            # soak's monotone-counter gate reads this field.
            "round": self._round_counter - 1,
            # The fencing epoch this round committed under: lineage
            # accounting across a healed partition keys on it (a stale
            # fork's records carry the superseded epoch).
            "epoch": self._coord_epoch,
            "participants": len(completed),
            "stragglers": len(stragglers),
            "world": world,
            # Rows that actually entered the combine (participants minus
            # screening rejections and ignored quarantined deliveries).
            "aggregated": len(order),
            "alive": [self.registry.is_alive(c) for c in roster_now],
            "membership_version": membership_version,
            # Flat-buffer footprint of this round's streaming collect (host
            # rows + the device twin; 0 on the barrier path) — with
            # process RSS, the leak axes the long-haul soaks watch.
            "buffer_bytes": (
                2 * int(host_rows[0].nbytes) if stream and host_rows else 0
            ),
            # Wire accounting (successful transfers only) — the reference
            # can't report this at all; its payloads are opaque base64 blobs
            # (src/client.py:21).
            "bytes_up": int(bytes_up.value),
            "bytes_down": int(bytes_down.value),
            "pipeline": self.server_pipeline,
            # Per-codec breakdown of bytes_up (successful replies only;
            # codec = what the record actually was, 'none' = dense).
            "bytes_up_by_codec": _sum_codec_bytes(
                codec_of[c] for c in completed if c in codec_of
            ),
            # Phase timing: collect is launch->last join; decode/h2d are
            # summed per-client (overlapped with network wait under
            # "stream", so they can exceed nothing of the wall clock);
            # post_barrier is the last-reply -> new-global gap the
            # streaming pipeline exists to shrink.
            "t_collect_s": round(t_barrier - t_launch, 6),
            "t_decode_s": round(decode_s.value, 6),
            "t_h2d_s": round(h2d_s.value, 6),
            "t_aggregate_s": round(t_done - t_barrier, 6),
            "t_post_barrier_s": round(t_done - t_barrier, 6),
            "t_round_s": round(t_done - t_launch, 6),
        }
        if tiered:
            # Topology accounting: participants above counts DIRECT peers
            # (aggregators); clients_aggregated is the leaf-client total
            # behind this round's partials — the fan-in bench's
            # work-vs-clients gate reads both.
            rec["tier_fanout"] = self.tier_fanout
            rec["clients_aggregated"] = int(clients_in.value)
        from fedtpu.obs.profile import latency_summary

        lat = latency_summary(
            [(c, latencies[c]) for c in completed if c in latencies]
        )
        if lat:
            # Straggler attribution: percentile spread + named top-3
            # slowest — the "which client is dragging the barrier" readout
            # the per-phase sums can't give (collect is launch->LAST join).
            rec["client_latency"] = lat
        if self._weights_ignored:
            # Operator-facing flag (satellite): the robust aggregator ran
            # UNWEIGHTED even though weighted=True — by design, not a bug.
            rec["weights_ignored"] = True
        if self._screen_jit is not None:
            rec["screened"] = screened_names
            rec["quarantined"] = sorted(
                self.registry.quarantined_clients()
            )
        self.history.append(rec)
        return rec

    # -------------------------------------------------------- async (FedBuff)
    def run_async(
        self,
        num_updates: int,
        buffer_k: int = 2,
        staleness_power: float = 0.5,
        stop: Optional[Callable[[], bool]] = None,
        on_update: Optional[Callable[[int, dict], None]] = None,
        staleness_damping: bool = True,
    ) -> List[dict]:
        """Semi-asynchronous orchestration (FedBuff, Nguyen et al. 2022).

        Instead of the synchronous round barrier, every live client loops
        independently: receive the current global model, train, reply. The
        server buffers incoming deltas and applies an aggregation as soon as
        ``buffer_k`` have arrived, weighting each by
        ``num_examples / (1 + staleness)**staleness_power`` where staleness
        is how many server updates landed since that client's base model.
        Fast clients contribute often; a slow client's (stale) delta still
        counts, just discounted — no one blocks anyone.

        ``staleness_damping`` (default True): the discount scales the
        applied update's MAGNITUDE (paper semantics, sum(disc*w*d)/sum(w));
        False is the weight-normalized mean, where a uniform-staleness
        buffer cancels the discount entirely — the mechanism behind the
        engine-side homogeneous-speed stall measured in round 5
        (:mod:`fedtpu.core.async_engine` docstring, the engine twin).

        The reference has no async mode at all (its barrier is
        ``src/server.py:132-135``); this composes with the plain mean
        aggregator + server optimizer only: compression (sparse deltas
        against stale baselines), robust aggregators (buffer_k is too small
        a population), and DP (per-update participation accounting differs)
        are rejected.

        Returns per-update records; runs until ``num_updates`` aggregations
        (or ``stop()``).
        """
        import queue

        fed = self.cfg.fed
        tel = self.telemetry
        if fed.compression != "none":
            raise ValueError(
                "run_async requires compression='none': sparse deltas "
                "against stale baselines corrupt aggregation."
            )
        if fed.aggregator != "mean":
            raise ValueError(
                "run_async requires aggregator='mean': a buffer of "
                f"{buffer_k} is too small a population for robust statistics."
            )
        if fed.dp_clip_norm > 0:
            raise ValueError(
                "run_async does not support DP: per-update participation "
                "accounting differs from the synchronous analysis."
            )
        if self._screen_jit is not None:
            raise ValueError(
                "run_async does not support update screening: the "
                f"buffer of {buffer_k} is too small a population for the "
                "median/MAD reference statistics. Use the synchronous "
                "round loop."
            )
        if buffer_k < 1:
            raise ValueError(f"buffer_k must be >= 1, got {buffer_k}")

        replies: "queue.Queue" = queue.Queue()
        done = threading.Event()
        version_lock = threading.Lock()
        self._async_version = 0

        def snapshot():
            """(version, payload, host base) for the CURRENT global model —
            computed ONCE per version (a full encode + device->host copy per
            worker iteration would serialize everyone on version_lock)."""
            return (
                self._async_version,
                self.model_bytes(),
                {
                    "params": jax.tree.map(np.asarray, self.params),
                    "batch_stats": jax.tree.map(np.asarray, self.batch_stats),
                },
            )

        current = [snapshot()]  # guarded by version_lock

        def worker(client: str, rank: int) -> None:
            """One client's loop: sync -> train -> enqueue, until done."""
            while not done.is_set():
                if not self.registry.is_alive(client):
                    time.sleep(0.2)  # heartbeat monitor may revive it
                    continue
                stub = self._stub(client)
                if stub is None:
                    return  # evicted mid-run: this worker retires
                try:
                    with version_lock:
                        base_version, payload, base = current[0]
                    call_with_retry(
                        self.retry_policy, "SendModel",
                        lambda: stub.SendModel(
                            proto.SendModelRequest(
                                model=payload,
                                epoch=self._coord_epoch, role=self._role,
                            ),
                            timeout=self._deadlines["SendModel"],
                        ),
                        peer=client, telemetry=tel,
                        rand=self._retry_rand,
                    )
                    tel.counter(
                        "fedtpu_rpc_bytes_down_total",
                        "server -> client/backup broadcast bytes (successful)",
                    ).inc(len(payload))

                    def train_attempt():
                        # RPC + decode as one retryable unit: a corrupt
                        # reply (WireError) is re-requested like any
                        # transient (see round()'s train_one).
                        reply = stub.StartTrain(
                            proto.TrainRequest(
                                # Each client keeps its OWN seat's shard;
                                # the synchronous path assigns the same
                                # stable seat ranks (see round()'s rank_of).
                                rank=rank, world=self.registry.capacity(),
                                epoch=self._coord_epoch,
                            ),
                            timeout=self._deadlines["StartTrain"],
                        )
                        tree = wire.decode(
                            reply.message,
                            _payload_template(self.model, self.cfg),
                        )
                        return reply, tree

                    reply, tree = call_with_retry(
                        self.retry_policy, "StartTrain", train_attempt,
                        peer=client, telemetry=tel,
                        rand=self._retry_rand,
                    )
                    tel.counter(
                        "fedtpu_rpc_bytes_up_total",
                        "client -> server StartTrain reply bytes (successful)",
                    ).inc(len(reply.message))
                    delta = jax.tree.map(
                        lambda a, g: np.asarray(a) - g,
                        {"params": tree["params"],
                         "batch_stats": tree["batch_stats"]},
                        base,
                    )
                    replies.put(
                        (client, delta, float(tree["num_examples"]),
                         base_version)
                    )
                except (grpc.RpcError, wire.WireError) as e:
                    if is_stale_coordinator(e):
                        # We are superseded: the client stays alive; this
                        # worker retires and the caller re-bases.
                        self._handle_stale("AsyncWorker", client, e)
                        return
                    if isinstance(e, grpc.RpcError):
                        log.warning(
                            "async client %s failed: %s %s",
                            client, e.code(), e.details(),
                        )
                    else:
                        log.warning(
                            "async client %s reply still corrupt after "
                            "retries: %s", client, e,
                        )
                    tel.counter(
                        "fedtpu_rpc_failures_total",
                        "RpcErrors by failing RPC",
                        labels={"rpc": "AsyncWorker"},
                    ).inc()
                    self.registry.mark_failed(client)

        self.monitor.start()
        if self.pinger is not None:
            self.pinger.tick()
            self.pinger.start()
        # One worker per member AT START; members admitted mid-run are
        # replicated/heartbeat-managed but only join the training loop on
        # the next run_async invocation (documented in FAULT_TOLERANCE.md).
        workers = [
            threading.Thread(target=worker, args=(c, rank), daemon=True)
            for c, rank in sorted(self.registry.seat_map().items())
        ]
        for w in workers:
            w.start()
        all_dead_since: List[Optional[float]] = [None]

        def hopeless() -> bool:
            """True when no reply can plausibly ever arrive again: every
            client dead (workers sleep-loop awaiting heartbeat revival, so
            thread liveness can't signal this), nothing buffered, and the
            state has persisted past several heartbeat cycles."""
            if self.registry.active_clients() or not replies.empty():
                all_dead_since[0] = None
                return False
            if all_dead_since[0] is None:
                all_dead_since[0] = time.monotonic()
            return time.monotonic() - all_dead_since[0] > 10.0

        poll_s = fed.async_poll_s
        # Async quorum (cfg.fed.round_quorum): an update only applies while
        # at least that fraction of the CURRENT membership (not the startup
        # roster — members join and leave) is alive — below it the
        # buffered deltas are held (global untouched) until the heartbeat
        # monitor revives enough clients, the async analogue of the
        # synchronous round abort. 0 = apply whenever buffer_k arrive.
        quorum_n = (
            max(1, math.ceil(fed.round_quorum * self.registry.size))
            if fed.round_quorum > 0 else 0
        )
        try:
            while self._async_version < num_updates:
                if stop is not None and stop():
                    break
                buf = []
                while len(buf) < buffer_k:
                    try:
                        buf.append(replies.get(timeout=poll_s))
                    except queue.Empty:
                        if (stop is not None and stop()) or hopeless():
                            break
                if len(buf) < buffer_k:
                    if hopeless():
                        log.warning("all async clients dead; stopping")
                        break
                    continue
                if quorum_n and len(self.registry.active_clients()) < quorum_n:
                    log.warning(
                        "async update held: %d alive < quorum %d; waiting "
                        "for recovery",
                        len(self.registry.active_clients()), quorum_n,
                    )
                    tel.counter(
                        "fedtpu_round_aborts_total",
                        "rounds aborted below quorum (global model untouched)",
                    ).inc()
                    while (len(self.registry.active_clients()) < quorum_n
                           and not hopeless()
                           and not (stop is not None and stop())):
                        time.sleep(poll_s)
                    if len(self.registry.active_clients()) < quorum_n:
                        log.warning("quorum never recovered; stopping")
                        break
                with tel.span("async_update"), version_lock:
                    v = self._async_version
                    stalenesses = [v - b for _, _, _, b in buf]
                    raw = [n if fed.weighted else 1.0 for _, _, n, _ in buf]
                    disc = [
                        w / (1.0 + s) ** staleness_power
                        for w, s in zip(raw, stalenesses)
                    ]
                    weights = jnp.asarray(disc, jnp.float32)
                    stacked = jax.tree.map(
                        lambda *leaves: jnp.stack(leaves),
                        *[d for _, d, _, _ in buf],
                    )
                    if staleness_damping:
                        # sum(disc*w*d)/sum(w): rescale so the discount
                        # damps the applied magnitude (see docstring).
                        # Scale in f32 and cast the PRODUCT back: rounding
                        # the factor itself to a narrow leaf dtype (bf16
                        # wire payloads) would silently diverge from the
                        # engine's f32 damping math.
                        damp = jnp.asarray(
                            sum(disc) / max(sum(raw), 1e-9), jnp.float32
                        )
                        stacked = jax.tree.map(
                            lambda l: (
                                l.astype(jnp.float32) * damp
                            ).astype(l.dtype),
                            stacked,
                        )
                    new_global, self._server_opt_state = self._aggregate(
                        {"params": self.params,
                         "batch_stats": self.batch_stats},
                        stacked,
                        weights,
                        self._server_opt_state,
                        jnp.asarray(v, jnp.int32),
                    )
                    self.params = new_global["params"]
                    self.batch_stats = new_global["batch_stats"]
                    self._async_version = v + 1
                    # Keep the lineage counter monotone across modes so a
                    # backup promoted from async replicas (which runs the
                    # synchronous loop) continues the PRNG sequence.
                    self._round_counter += 1
                    current[0] = snapshot()
                if self.backup_stub is not None:
                    try:
                        call_with_retry(
                            self.retry_policy, "SendModel",
                            lambda: self.backup_stub.SendModel(
                                proto.SendModelRequest(
                                    model=self.replica_bytes(),
                                    epoch=self._coord_epoch, role=self._role,
                                ),
                                timeout=self._deadlines["SendModel"],
                            ),
                            peer="backup", telemetry=tel,
                            rand=self._retry_rand,
                        )
                    except grpc.RpcError as e:
                        if is_stale_coordinator(e):
                            self._handle_stale("Replicate", "backup", e)
                        else:
                            log.warning(
                                "backup unreachable during replication"
                            )
                rec = {
                    "update": self._async_version,
                    "contributors": [c for c, _, _, _ in buf],
                    "staleness": stalenesses,
                    "alive": self.registry.alive_mask().tolist(),
                }
                self.history.append(rec)
                self.status.update(
                    round=self._round_counter, phase="async",
                    async_update=self._async_version,
                )
                self.flight.record(
                    "async_update",
                    update=self._async_version,
                    contributors=len(buf),
                )
                if tel.enabled:
                    tel.counter(
                        "fedtpu_async_updates_total",
                        "FedBuff server updates applied",
                    ).inc()
                    stale_hist = tel.histogram(
                        "fedtpu_async_staleness",
                        "staleness (server updates) of buffered deltas at "
                        "apply time",
                        buckets=(0, 1, 2, 4, 8, 16, 32, 64),
                    )
                    for s in stalenesses:
                        stale_hist.observe(s)
                log.info("async update %s", rec)
                if on_update is not None:
                    on_update(self._async_version, rec)
            # Deliver the FINAL model: workers stop syncing once done is
            # set, and without this every client would end at least one
            # update stale (the synchronous path broadcasts every round).
            done.set()
            for w in workers:
                w.join(timeout=self.rpc_timeout)
            self.sync_clients()
        finally:
            done.set()
            self.monitor.stop()
            if self.pinger is not None:
                self.pinger.stop()
        return self.history

    def run(
        self,
        num_rounds: Optional[int] = None,
        stop: Optional[Callable[[], bool]] = None,
        on_round: Optional[Callable[[int, dict], None]] = None,
    ) -> List[dict]:
        """Drive rounds with background heartbeat + backup ping threads.
        ``stop()`` is polled between rounds (used by failover demotion);
        ``on_round(r, record)`` runs after each round (checkpointing,
        metrics)."""
        if num_rounds is None:
            num_rounds = self.cfg.fed.num_rounds
        self.monitor.start()
        if self.pinger is not None:
            # First ping synchronously: if the backup was acting primary, the
            # demotion + model fetch must land before we train round 0.
            self.pinger.tick()
            self.pinger.start()
        # The first round() call broadcasts the global model before training
        # (see sync_clients) — after the pinger tick above, so a model
        # fetched from a demoting backup is what gets synced.
        try:
            r = 0
            consecutive_aborts = 0
            while r < num_rounds:
                if stop is not None and stop():
                    log.info("round loop stopped (demotion) after %d rounds", r)
                    break
                if self._fenced:
                    # Superseded by a higher epoch (healed partition):
                    # re-base on the winning lineage before training again.
                    self.handle_fence()
                    continue
                rec = self.round()
                if rec.get("aborted"):
                    # Sub-quorum round: the global is untouched; re-run it
                    # once the heartbeat monitor (running in this loop) has
                    # had a chance to revive clients. The abort IS reported
                    # (an ``aborted: true`` record in the round log — an
                    # operator must see it), it just doesn't count toward
                    # num_rounds. A federation that NEVER recovers must not
                    # spin forever.
                    if on_round is not None:
                        on_round(r, rec)
                    consecutive_aborts += 1
                    if consecutive_aborts >= 50:
                        log.error(
                            "round %d aborted %d times in a row below "
                            "quorum; giving up", r, consecutive_aborts,
                        )
                        break
                    if rec.get("fenced"):
                        continue  # re-base immediately, no heartbeat wait
                    time.sleep(self.monitor.period)
                    continue
                consecutive_aborts = 0
                log.info("round %d: %s", r, rec)
                if on_round is not None:
                    on_round(r, rec)
                r += 1
        finally:
            self.monitor.stop()
            if self.pinger is not None:
                self.pinger.stop()
        return self.history


# ----------------------------------------------------------------------- gate
class _MembershipGate(TrainerServicer):
    """The coordinator's inbound membership surface: Join admits the
    caller's advertised serving address into the primary's
    :class:`~fedtpu.ft.membership.MembershipTable` (and resyncs it with the
    current global model through the heartbeat-revival path), Leave evicts
    it gracefully. Hosted by :meth:`PrimaryServer.start_gate`; all other
    RPCs stay UNIMPLEMENTED — the gate is not a Trainer."""

    def __init__(self, primary: "PrimaryServer"):
        self.primary = primary

    def Join(self, request: proto.JoinRequest, context) -> proto.JoinReply:
        address = request.address.decode()
        if not address:
            return proto.JoinReply(admitted=0, message=b"empty address")
        out = self.primary.admit_client(address)
        return proto.JoinReply(
            admitted=1, seat=out["seat"], world=out["world"],
            version=out["version"],
            message=b"resynced" if out["resynced"] else b"pending resync",
        )

    def Leave(self, request: proto.LeaveRequest, context) -> proto.LeaveReply:
        address = request.address.decode()
        out = self.primary.remove_client(address, reason="leave")
        return proto.LeaveReply(
            left=1 if out["left"] else 0, version=out["version"]
        )

    def HeartBeat(self, request: proto.Request, context) -> proto.HeartBeatResponse:
        return proto.HeartBeatResponse(status=1)


# --------------------------------------------------------------------- backup
class BackupServer(TrainerServicer):
    """Backup-side servicer + failover driver (parity:
    ``src/server.py:235-264``): absorbs model replication, answers primary
    pings, and promotes to acting primary on watchdog expiry. On promotion it
    runs the primary round loop seeded with the replicated model; a
    recovering primary's first ping demotes it back."""

    def __init__(
        self,
        cfg: RoundConfig,
        clients: List[str],
        compress: bool = False,
        watchdog_timeout: Optional[float] = None,
        round_deadline_s: Optional[float] = None,
        flight: Optional[FlightRecorder] = None,
        chaos=None,
        on_acting_round: Optional[Callable[[int, dict], None]] = None,
    ):
        """``on_acting_round(r, record)``: forwarded to the acting
        primary's round loop after a promotion — the hook rolling-upgrade
        and churn drills use to keep their per-round bookkeeping (round
        records, scripted churn) running across the failover window."""
        self.cfg = cfg
        self.clients = clients
        self.compress = compress
        # Forwarded to the acting PrimaryServer on promotion, so straggler
        # mitigation (and fault injection) survive failover.
        self.round_deadline_s = round_deadline_s
        self.chaos = chaos
        self.on_acting_round = on_acting_round
        if watchdog_timeout is None:
            watchdog_timeout = cfg.fed.ft_watchdog_timeout_s
        log.info(
            "backup timings: watchdog=%.1fs chaos=%s",
            watchdog_timeout,
            chaos.describe() if chaos is not None else "off",
        )
        self.latest_model: Optional[bytes] = None
        self.acting: Optional[PrimaryServer] = None
        self.telemetry = Telemetry(cfg.fed.telemetry, role="backup")
        # The black box this module exists for: the state machine dumps it
        # on EVERY promote/demote, so the run-up to a role flip survives
        # even if the promoted process dies seconds later.
        self.flight = flight if flight is not None else FlightRecorder(
            role="backup"
        )
        self.machine = FailoverStateMachine(
            timeout=watchdog_timeout,
            on_promote=self._promote,
            on_demote=self._demote,
            metrics=(
                self.telemetry.registry if self.telemetry.enabled else None
            ),
            flight=self.flight,
        )
        self.watchdog = WatchdogRunner(self.machine)
        # Per-promotion stop event: a primary flap must not re-arm a stopped
        # acting primary (each promotion gets a fresh event + thread).
        self._acting_stop: Optional[threading.Event] = None
        self._promote_thread: Optional[threading.Thread] = None
        # Fencing (docs/FAULT_TOLERANCE.md §Fencing): the max coordinator
        # epoch this backup has seen — on replication, on pings, and on its
        # own promotions (each mint advances it). A lower-epoch replication
        # or steady-state ping is a superseded primary and gets the typed
        # STALE_COORDINATOR rejection.
        self._epoch_seen = -1

    # ------------------------------------------------------------- servicer
    def _fence_check(self, epoch: int, rpc: str, context) -> None:
        """Track the max coordinator epoch; abort a stale sender (same
        contract as ClientAgent._fence_check)."""
        if epoch < 0:
            return  # pre-fencing peer
        if epoch >= self._epoch_seen:
            self._epoch_seen = epoch
            return
        log.warning(
            "%s from stale coordinator epoch %d rejected (newest seen %d)",
            rpc, epoch, self._epoch_seen,
        )
        self.telemetry.counter(
            "fedtpu_ft_stale_rejected_total",
            "coordinator RPCs rejected for a stale fencing epoch, by rpc",
            labels={"rpc": rpc},
        ).inc()
        context.abort(
            grpc.StatusCode.FAILED_PRECONDITION,
            f"STALE_COORDINATOR: epoch {epoch} < {self._epoch_seen}",
        )

    def SendModel(self, request: proto.SendModelRequest, context) -> proto.SendModelReply:
        # A stale primary's replica must never overwrite the replication
        # slot: after we promoted past it, its lineage is void.
        self._fence_check(request.epoch, "Replicate", context)
        self.latest_model = request.model
        return proto.SendModelReply(reply=b"replicated")

    def CheckIfPrimaryUp(self, request: proto.PingRequest, context) -> proto.PingResponse:
        recovering = request.req == b"1"
        # Steady-state pings from a superseded primary are fenced — they
        # must not keep resetting our watchdog (that would let a stale
        # coordinator suppress re-promotion forever). The RECOVERING ping
        # is the heal handshake (demote + FetchModel re-base) and must
        # pass whatever its epoch, or a fenced ex-primary could never
        # re-base through us.
        if not recovering:
            self._fence_check(request.epoch, "CheckIfPrimaryUp", context)
        elif request.epoch > self._epoch_seen:
            self._epoch_seen = request.epoch
        return proto.PingResponse(value=self.machine.on_ping(recovering))

    def HeartBeat(self, request: proto.Request, context) -> proto.HeartBeatResponse:
        return proto.HeartBeatResponse(status=1)

    def FetchModel(self, request: proto.Request, context) -> proto.SendModelRequest:
        """Hand the newest model we hold to a recovered primary — the acting
        primary's final model if we trained in its absence, else the last
        replicated blob. Waits for a draining acting round to finish so the
        returned model is settled, not mid-aggregation (the caller's fetch
        timeout is generous)."""
        self._stop_acting(wait=300.0)
        acting = self.acting
        if acting is not None and acting.history:
            return proto.SendModelRequest(model=acting.replica_bytes())
        return proto.SendModelRequest(model=self.latest_model or b"")

    def Join(self, request: proto.JoinRequest, context) -> proto.JoinReply:
        """Membership during a failover window: the backup's address is the
        STABLE join target — while it is acting primary, joins land in the
        acting coordinator's roster (and replicate back to the recovered
        primary through the state tree); in the backup role it refuses,
        pointing the joiner back at the primary's gate."""
        from fedtpu.ft import Role

        acting = self.acting
        if self.machine.role is Role.ACTING_PRIMARY and acting is not None:
            return _MembershipGate(acting).Join(request, context)
        return proto.JoinReply(admitted=0, message=b"not primary")

    def Leave(self, request: proto.LeaveRequest, context) -> proto.LeaveReply:
        from fedtpu.ft import Role

        acting = self.acting
        if self.machine.role is Role.ACTING_PRIMARY and acting is not None:
            return _MembershipGate(acting).Leave(request, context)
        return proto.LeaveReply(left=0)

    def status_snapshot(self) -> dict:
        """``/statusz`` feed for the backup role: failover state + (when
        promoted) the acting primary's own status nested under
        ``acting``."""
        machine = self.machine
        since = machine.seconds_since_ping()
        snap = {
            "role": machine.role.value,
            "pid": os.getpid(),
            "watchdog_timeout_s": machine.timeout,
            "seconds_since_primary_ping": (
                None if since == float("inf") else round(since, 3)
            ),
            "has_replica": self.latest_model is not None,
            "epoch_seen": self._epoch_seen,
        }
        acting = self.acting
        if acting is not None and machine.role.value == "acting_primary":
            snap["acting"] = acting.status_snapshot()
        return snap

    def health(self) -> Tuple[bool, str]:
        """Honest /healthz for the backup role: while acting primary,
        delegate to the acting coordinator's verdict (fenced / quorum);
        in the backup role the process is healthy by construction."""
        from fedtpu.ft import Role

        acting = self.acting
        if self.machine.role is Role.ACTING_PRIMARY and acting is not None:
            return acting.health()
        return True, "ok"

    # -------------------------------------------------------------- failover
    def _promote(self) -> None:
        log.warning("watchdog expired: promoting to acting primary")
        self._stop_acting()
        stop_event = threading.Event()
        self._acting_stop = stop_event
        try:
            acting = PrimaryServer(
                self.cfg,
                self.clients,
                compress=self.compress,
                initial_model=self.latest_model,
                round_deadline_s=self.round_deadline_s,
                flight=self.flight,
                chaos=self.chaos,
            )
        except wire.WireError:
            # A corrupted replica must fail loudly — but not by silently
            # killing the watchdog thread and leaving the federation with NO
            # primary at all. Promote with a fresh model: degraded (the
            # trajectory restarts) but live, and the log says exactly why.
            log.exception(
                "replicated model is corrupted or config-mismatched; "
                "promoting with a freshly initialised model"
            )
            acting = PrimaryServer(
                self.cfg,
                self.clients,
                compress=self.compress,
                round_deadline_s=self.round_deadline_s,
                flight=self.flight,
                chaos=self.chaos,
            )
        # Mint the promotion epoch: strictly past both the replicated
        # lineage's epoch (installed above from the replica payload) and
        # anything this backup has ever seen on the wire. From now on the
        # old primary's epoch is stale everywhere this coordinator speaks.
        acting._set_epoch(max(acting._coord_epoch, self._epoch_seen) + 1)
        acting._role = 2
        self._epoch_seen = acting._coord_epoch
        log.warning("promotion minted coordinator epoch %d",
                    acting._coord_epoch)
        self.acting = acting

        def run_acting():
            acting.run(stop=stop_event.is_set,
                       on_round=self.on_acting_round)
            # Whatever the acting primary trained becomes the replication
            # state, so a later re-promotion (or FetchModel from the
            # recovered primary) starts from its progress, not from the
            # pre-failover snapshot.
            if acting.history:
                self.latest_model = acting.replica_bytes()

        self._promote_thread = threading.Thread(target=run_acting, daemon=True)
        self._promote_thread.start()

    def _demote(self) -> None:
        # Runs inside the CheckIfPrimaryUp handler: signal only, never join —
        # the recovering primary's ping has a 2 s deadline. The drain is
        # awaited by FetchModel (or the next promotion).
        log.warning("primary recovered: demoting to backup")
        if self._acting_stop is not None:
            self._acting_stop.set()

    def _stop_acting(self, wait: float = 120.0) -> None:
        if self._acting_stop is not None:
            self._acting_stop.set()
        if self._promote_thread is not None:
            self._promote_thread.join(timeout=wait)
            if not self._promote_thread.is_alive():
                self._promote_thread = None

    def start(self, address: str):
        """Host the backup servicer + watchdog; returns the grpc server."""
        server = create_server(
            address, self, compress=self.compress, chaos=self.chaos
        )
        server.start()
        self.watchdog.start()
        return server
