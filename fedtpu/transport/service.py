"""gRPC ``federated.Trainer`` service — stub, servicer, server builder.

Replays the reference's service surface (``federated.proto:24-29``: four
unary RPCs — StartTrain, SendModel, HeartBeat, CheckIfPrimaryUp) on method
paths identical to protoc's output (``/federated.Trainer/<Method>``), built
from generic handlers + the hand-rolled codec in
:mod:`fedtpu.transport.proto` since no protoc Python plugin is available.

Transport knobs match the reference: 1 GiB message caps on both channels and
servers (``src/server.py:42-45,209-212``, ``src/client.py:40-48``) and
optional transport gzip for ``-c Y`` parity (``src/server.py:104-107``,
``src/client.py:39-43``) — though the TPU-native compression path
(:mod:`fedtpu.ops.compression`) is the one that actually shrinks collective
traffic.
"""

from __future__ import annotations

from concurrent import futures
from typing import Callable, Optional

import grpc

from fedtpu.transport import proto

SERVICE_NAME = "federated.Trainer"
MAX_MESSAGE_BYTES = 1024 * 1024 * 1024  # 1 GiB, reference: src/server.py:42-45

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", MAX_MESSAGE_BYTES),
    ("grpc.max_receive_message_length", MAX_MESSAGE_BYTES),
]

_METHODS = {
    # name: (request type, response type)
    "StartTrain": (proto.TrainRequest, proto.TrainReply),
    "SendModel": (proto.SendModelRequest, proto.SendModelReply),
    "HeartBeat": (proto.Request, proto.HeartBeatResponse),
    "CheckIfPrimaryUp": (proto.PingRequest, proto.PingResponse),
    # Additive extension beyond the reference's 4 RPCs: lets a recovered
    # primary PULL the newer global model from a backup that acted as
    # primary in its absence. The reference has no such path — an acting
    # primary's training progress is silently reverted on demotion (its
    # primary restarts from its own stale files). Unknown methods don't
    # affect interop on the original 4.
    "FetchModel": (proto.Request, proto.SendModelRequest),
    # Elastic membership (docs/FAULT_TOLERANCE.md): a client announces the
    # address it serves on and is admitted into (Join) or removed from
    # (Leave) the coordinator's MembershipTable. Served by the primary's
    # membership gate and by the backup (which delegates to its acting
    # primary after a failover, so joiners keep working mid-outage).
    "Join": (proto.JoinRequest, proto.JoinReply),
    "Leave": (proto.LeaveRequest, proto.LeaveReply),
    # Hierarchical aggregation (docs/ARCHITECTURE.md §Multi-tier): the root
    # PULLS one partial reduce per round from each leaf AggregatorServer —
    # same dial-out direction as StartTrain, so retry/quorum/fencing/trace
    # machinery applies unchanged. Additive method: legacy peers answer it
    # UNIMPLEMENTED (a fatal, non-retried code) and never see new bytes on
    # the original RPCs.
    "SubmitPartial": (proto.SubmitPartialRequest, proto.SubmitPartialReply),
}


class TrainerStub:
    """Client-side stub, same call surface as protoc's ``TrainerStub``
    (reference ``src/federated_pb2_grpc.py:8-36``)."""

    def __init__(self, channel: grpc.Channel):
        # Kept for lifecycle management: dynamic membership closes a
        # member's channel on eviction instead of leaking it.
        self._channel = channel
        for name, (req_t, resp_t) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{SERVICE_NAME}/{name}",
                    request_serializer=lambda m: m.encode(),
                    response_deserializer=resp_t.decode,
                ),
            )


class TrainerServicer:
    """Abstract servicer, same surface as protoc's ``TrainerServicer``
    (reference ``src/federated_pb2_grpc.py:39-64``). Subclass and override."""

    def StartTrain(self, request: proto.TrainRequest, context) -> proto.TrainReply:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def SendModel(self, request: proto.SendModelRequest, context) -> proto.SendModelReply:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def HeartBeat(self, request: proto.Request, context) -> proto.HeartBeatResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def CheckIfPrimaryUp(self, request: proto.PingRequest, context) -> proto.PingResponse:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def FetchModel(self, request: proto.Request, context) -> proto.SendModelRequest:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def Join(self, request: proto.JoinRequest, context) -> proto.JoinReply:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def Leave(self, request: proto.LeaveRequest, context) -> proto.LeaveReply:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError

    def SubmitPartial(
        self, request: proto.SubmitPartialRequest, context
    ) -> proto.SubmitPartialReply:
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        raise NotImplementedError


def add_trainer_servicer(servicer: TrainerServicer, server: grpc.Server) -> None:
    """Register ``servicer`` on ``server`` (parity:
    ``add_TrainerServicer_to_server``, ``src/federated_pb2_grpc.py:67-92``)."""
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_t.decode,
            response_serializer=lambda m: m.encode(),
        )
        for name, (req_t, resp_t) in _METHODS.items()
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )


def trace_context_of(context):
    """Extract the propagated ``fedtpu-trace-bin`` trace context from a
    servicer's handler context (None when the caller attached none or the
    payload is malformed — extraction must never fail an RPC). The
    injection side is :func:`fedtpu.obs.propagate.instrument_channel`."""
    from fedtpu.obs import propagate

    try:
        return propagate.from_metadata(context.invocation_metadata())
    except Exception:
        return None


def create_channel(address: str, compress: bool = False,
                   trace_source=None, chaos=None) -> grpc.Channel:
    """Insecure channel with 1 GiB caps and optional gzip (parity:
    ``createChannel``, ``src/server.py:103-107``). ``trace_source`` (a
    ``() -> Optional[TraceContext]``) wraps the channel with the
    trace-propagation interceptor; ``chaos`` (a
    :class:`fedtpu.ft.chaos.FaultSchedule`) with the fault-injection
    interceptor keyed to this peer. None keeps the plain channel."""
    kwargs = {}
    if compress:
        kwargs["compression"] = grpc.Compression.Gzip
    channel = grpc.insecure_channel(address, options=_CHANNEL_OPTIONS, **kwargs)
    if trace_source is not None:
        from fedtpu.obs import propagate

        channel = propagate.instrument_channel(channel, trace_source)
    if chaos is not None:
        channel = grpc.intercept_channel(
            channel, chaos.client_interceptor(address)
        )
    return channel


def create_server(
    address: str,
    servicer: TrainerServicer,
    compress: bool = False,
    max_workers: int = 10,
    chaos=None,
) -> grpc.Server:
    """Build (not start) a server hosting ``servicer`` on ``address``
    (parity: ``serve``, ``src/client.py:38-52`` — 10 workers, 1 GiB caps,
    optional gzip, insecure port). ``chaos`` arms the server-side
    fault-injection interceptor on every inbound RPC."""
    kwargs = {}
    if compress:
        kwargs["compression"] = grpc.Compression.Gzip
    if chaos is not None:
        kwargs["interceptors"] = (chaos.server_interceptor(),)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
        **kwargs,
    )
    add_trainer_servicer(servicer, server)
    server.add_insecure_port(address)
    return server


def announce_join(
    gate_address: str, my_address: str, timeout_s: float = 60.0,
    poll_s: float = 0.5,
) -> Optional[TrainerStub]:
    """Client-side half of dynamic membership: announce ``my_address`` (the
    address this client SERVES on — its member identity) to the
    coordinator's membership gate, retrying with a flat backoff until
    admitted or ``timeout_s`` elapses. The gate may come up after the
    client (a rolling restart), so refusal and unreachability both just
    wait. Returns the gate stub (reusable for :func:`announce_leave`) on
    admission, None on timeout."""
    import logging
    import time

    stub = TrainerStub(create_channel(gate_address))
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            reply = stub.Join(
                proto.JoinRequest(address=my_address.encode()), timeout=5.0
            )
            if reply.admitted:
                logging.info(
                    "admitted by gate %s: seat=%d world=%d membership v%d "
                    "(%s)", gate_address, reply.seat, reply.world,
                    reply.version, reply.message.decode(errors="replace"),
                )
                return stub
        except grpc.RpcError as exc:
            logging.info("gate %s not ready (%s); retrying",
                         gate_address, exc.code())
        time.sleep(poll_s)
    return None


def announce_leave(stub: TrainerStub, my_address: str) -> bool:
    """Graceful departure: best-effort Leave against an
    :func:`announce_join` gate stub (False when the gate is unreachable —
    the heartbeat machinery then handles us as a silent leaver)."""
    import logging

    try:
        reply = stub.Leave(
            proto.LeaveRequest(address=my_address.encode()), timeout=5.0
        )
        return bool(reply.left)
    except grpc.RpcError as exc:
        logging.warning("Leave failed (%s); departing silently", exc.code())
        return False


def probe(
    stub: TrainerStub, timeout: float = 1.0, policy=None, telemetry=None
) -> Optional[proto.HeartBeatResponse]:
    """One HeartBeat RPC; None on any RpcError (the reference's liveness
    probe semantics, ``src/server.py:86-99``). With ``policy`` (a
    :class:`fedtpu.config.RetryPolicy`) transient failures retry with
    backoff first, so a one-packet blip during an FT probe doesn't read as
    a dead peer."""
    try:
        if policy is None:
            return stub.HeartBeat(proto.Request(), timeout=timeout)
        from fedtpu.transport.retry import call_with_retry

        return call_with_retry(
            policy, "HeartBeat",
            lambda: stub.HeartBeat(proto.Request(), timeout=timeout),
            telemetry=telemetry,
        )
    except grpc.RpcError:
        return None
