"""Hand-rolled proto3 codec for the reference's ``federated.proto`` schema.

The reference generates ``federated_pb2.py`` with protoc
(``federated.proto:24-63``); this environment has no Python protoc plugin, so
the eight messages are encoded/decoded directly — they are tiny (at most two
scalar fields each) and the proto3 wire format for them is just field-tagged
varints and length-delimited blobs. Field numbers and wire types match the
reference schema exactly, so these bytes interoperate with any stock
``federated_pb2`` peer:

    TrainRequest{rank=1:int32, world=2:int32}     (federated.proto:39-42)
    TrainReply{message=1}                         (:45-47)
    SendModelRequest{model=1}                     (:49-51)
    SendModelReply{reply=1}                       (:53-55)
    Request{}                                     (:31)
    HeartBeatResponse{status=1:int32}             (:33-36)
    PingRequest{req=1}                            (:57-59)
    PingResponse{value=1:int32}                   (:61-63)

One deliberate divergence: payload fields (``TrainReply.message``,
``SendModelRequest.model``) are treated as *bytes*, not UTF-8 strings. Proto3
strings and bytes share wire type 2, but gRPC's protobuf runtime rejects
non-UTF-8 strings — which is exactly why the reference pays the 33% base64
tax (``src/client.py:21``). Owning the codec lets raw model bytes ride the
same field number with zero inflation.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

_VARINT = 0
_LEN = 2


class ProtoError(ValueError):
    """Malformed message bytes."""


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64  # proto int32 negatives are 10-byte varints
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ProtoError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ProtoError("varint too long")
    return result, pos


def _encode_fields(fields: List[Tuple[int, int, object]]) -> bytes:
    """fields: [(field_number, wire_type, value)]; proto3 default values
    (0 / empty) are omitted, matching canonical encoders."""
    out = bytearray()
    for num, wtype, value in fields:
        if wtype == _VARINT:
            if value == 0:
                continue
            _write_varint(out, (num << 3) | _VARINT)
            _write_varint(out, int(value))
        elif wtype == _LEN:
            if not value:
                continue
            _write_varint(out, (num << 3) | _LEN)
            _write_varint(out, len(value))
            out += value
        else:
            raise ProtoError(f"unsupported wire type {wtype}")
    return bytes(out)


def _decode_fields(data: bytes) -> Dict[int, object]:
    """Last-one-wins scalar decode (proto3 semantics); unknown fields are
    skipped, as generated code does."""
    fields: Dict[int, object] = {}
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        num, wtype = key >> 3, key & 0x7
        if wtype == _VARINT:
            value, pos = _read_varint(data, pos)
            fields[num] = value
        elif wtype == _LEN:
            size, pos = _read_varint(data, pos)
            if pos + size > len(data):
                raise ProtoError("truncated length-delimited field")
            fields[num] = data[pos : pos + size]
            pos += size
        elif wtype in (5, 1):  # fixed32 / fixed64 — skip
            width = 4 if wtype == 5 else 8
            if pos + width > len(data):
                raise ProtoError("truncated fixed-width field")
            pos += width
        else:
            raise ProtoError(f"unsupported wire type {wtype}")
    return fields


def _int32(value: int) -> int:
    """Reinterpret a decoded uint64 varint as int32 (sign wrap)."""
    value &= 0xFFFFFFFF
    return value - (1 << 32) if value >= 1 << 31 else value


@dataclasses.dataclass
class TrainRequest:
    rank: int = 0
    world: int = 0
    # Additive field 3 (beyond the reference schema's two): the
    # coordinator's LINEAGE round for this StartTrain, or -1 when unknown
    # (older peers, async workers). Carried so a client can detect a
    # coordinator REPLAY after disaster recovery (the resumed round is
    # behind the client's local counter) and roll its local state back to
    # the matching per-round snapshot instead of silently training a
    # diverged round (docs/OPERATIONS.md §Disaster recovery). Encoded as
    # round+1 so proto3's omit-zero default reads back as "absent" (-1),
    # never as round -1 colliding with a real round 0; stock
    # ``federated_pb2`` peers skip the unknown field.
    round: int = -1
    # Additive field 4: the sender's coordinator EPOCH, or -1 when absent
    # (pre-fencing peers). Minted on every promotion; receivers track the
    # max epoch seen and reject lower-epoch senders with STALE_COORDINATOR
    # so a healed partition cannot fork the lineage
    # (docs/FAULT_TOLERANCE.md §Fencing). Same +1 omit-zero trick as
    # ``round``: epoch 0 stays distinguishable from "absent".
    epoch: int = -1
    # Additive field 5: the coordinator's per-round CODEC CHOICE for this
    # client (the adaptive codec policy, docs/OPERATIONS.md §Adaptive
    # codec). 0 = unset — the client keeps its static configured codec, and
    # proto3 omit-zero means the field costs zero wire bytes in that (the
    # common) case; legacy peers skip the unknown field and likewise keep
    # their static codec. Nonzero values name a codec via
    # CODEC_IDS/CODEC_NAMES below.
    codec: int = 0

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _VARINT, self.rank),
            (2, _VARINT, self.world),
            (3, _VARINT, self.round + 1),
            (4, _VARINT, self.epoch + 1),
            (5, _VARINT, self.codec),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "TrainRequest":
        f = _decode_fields(data)
        return cls(
            rank=_int32(f.get(1, 0)),
            world=_int32(f.get(2, 0)),
            round=_int32(f.get(3, 0)) - 1,
            epoch=_int32(f.get(4, 0)) - 1,
            codec=_int32(f.get(5, 0)),
        )


# TrainRequest.codec wire ids (0 = unset/static). An enum by convention —
# kept as module constants so the hand-rolled codec stays dataclass-plain.
CODEC_IDS = {"none": 1, "int8": 2, "topk": 3, "rotq": 4, "randk": 5}
CODEC_NAMES = {v: k for k, v in CODEC_IDS.items()}


@dataclasses.dataclass
class TrainReply:
    message: bytes = b""

    def encode(self) -> bytes:
        return _encode_fields([(1, _LEN, self.message)])

    @classmethod
    def decode(cls, data: bytes) -> "TrainReply":
        return cls(message=_decode_fields(data).get(1, b""))


@dataclasses.dataclass
class SendModelRequest:
    model: bytes = b""
    # Additive fields 2/3: coordinator epoch (+1 encoded, -1 = absent, see
    # TrainRequest.epoch) and the sender's ROLE (0 = unset/legacy,
    # 1 = configured primary, 2 = acting primary). Role rides along so the
    # backup and flight recorder can attribute a replica stream without
    # decoding the payload; proto3 omit-zero keeps legacy bytes identical.
    epoch: int = -1
    role: int = 0

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _LEN, self.model),
            (2, _VARINT, self.epoch + 1),
            (3, _VARINT, self.role),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "SendModelRequest":
        f = _decode_fields(data)
        return cls(
            model=f.get(1, b""),
            epoch=_int32(f.get(2, 0)) - 1,
            role=_int32(f.get(3, 0)),
        )


@dataclasses.dataclass
class SendModelReply:
    reply: bytes = b""

    def encode(self) -> bytes:
        return _encode_fields([(1, _LEN, self.reply)])

    @classmethod
    def decode(cls, data: bytes) -> "SendModelReply":
        return cls(reply=_decode_fields(data).get(1, b""))


@dataclasses.dataclass
class Request:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "Request":
        _decode_fields(data)  # validate framing of any unknown fields
        return cls()


@dataclasses.dataclass
class HeartBeatResponse:
    status: int = 0

    def encode(self) -> bytes:
        return _encode_fields([(1, _VARINT, self.status)])

    @classmethod
    def decode(cls, data: bytes) -> "HeartBeatResponse":
        return cls(status=_int32(_decode_fields(data).get(1, 0)))


@dataclasses.dataclass
class PingRequest:
    req: bytes = b""
    # Additive field 2: coordinator epoch (+1 encoded, -1 = absent). Lets
    # the backup fence a stale primary's liveness probes — a partitioned
    # ex-primary must not keep resetting the watchdog of a backup that has
    # already promoted past it.
    epoch: int = -1

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _LEN, self.req),
            (2, _VARINT, self.epoch + 1),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "PingRequest":
        f = _decode_fields(data)
        return cls(req=f.get(1, b""), epoch=_int32(f.get(2, 0)) - 1)


@dataclasses.dataclass
class PingResponse:
    value: int = 0

    def encode(self) -> bytes:
        return _encode_fields([(1, _VARINT, self.value)])

    @classmethod
    def decode(cls, data: bytes) -> "PingResponse":
        return cls(value=_int32(_decode_fields(data).get(1, 0)))


# Elastic-membership extension beyond the reference's 8 messages (the
# reference freezes its registry at startup, src/server.py:281-282). A
# joiner announces the address it SERVES on — the coordinator dials
# clients, so the address is the member identity — and learns its seat
# (rank / data shard), the world (partition width) and the membership
# epoch. Leave is the graceful counterpart; silent departures are handled
# by the heartbeat machinery instead.
@dataclasses.dataclass
class JoinRequest:
    address: bytes = b""

    def encode(self) -> bytes:
        return _encode_fields([(1, _LEN, self.address)])

    @classmethod
    def decode(cls, data: bytes) -> "JoinRequest":
        return cls(address=_decode_fields(data).get(1, b""))


@dataclasses.dataclass
class JoinReply:
    admitted: int = 0
    seat: int = 0
    world: int = 0
    version: int = 0
    message: bytes = b""

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _VARINT, self.admitted),
            (2, _VARINT, self.seat),
            (3, _VARINT, self.world),
            (4, _VARINT, self.version),
            (5, _LEN, self.message),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "JoinReply":
        f = _decode_fields(data)
        return cls(
            admitted=_int32(f.get(1, 0)),
            seat=_int32(f.get(2, 0)),
            world=_int32(f.get(3, 0)),
            version=_int32(f.get(4, 0)),
            message=f.get(5, b""),
        )


# Hierarchical-aggregation extension (docs/ARCHITECTURE.md §Multi-tier):
# the ROOT coordinator pulls one partial reduce per round from each leaf
# AggregatorServer over SubmitPartial. Both messages are additive — new
# method name, new field numbers, proto3 omit-zero throughout — so a
# legacy peer that never speaks SubmitPartial sees zero new wire bytes on
# the original RPCs, and an unset message encodes to b"" (pinned in
# tests/test_transport.py).
@dataclasses.dataclass
class SubmitPartialRequest:
    # First cohort rank this aggregator hands out: cohort member i trains
    # shard ``rank_base + i`` of the root-wide ``world``-way partition, so
    # tiers tile the data partition without coordination.
    rank_base: int = 0
    world: int = 0
    # Coordinator lineage round / fencing epoch, +1 omit-zero encoded
    # exactly like TrainRequest fields 3/4 (-1 reads back as "absent").
    round: int = -1
    epoch: int = -1

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _VARINT, self.rank_base),
            (2, _VARINT, self.world),
            (3, _VARINT, self.round + 1),
            (4, _VARINT, self.epoch + 1),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "SubmitPartialRequest":
        f = _decode_fields(data)
        return cls(
            rank_base=_int32(f.get(1, 0)),
            world=_int32(f.get(2, 0)),
            round=_int32(f.get(3, 0)) - 1,
            epoch=_int32(f.get(4, 0)) - 1,
        )


@dataclasses.dataclass
class SubmitPartialReply:
    # One FSP1 ``partial_flat`` record (fedtpu.transport.sparse): the
    # cohort's pre-weighted sum row + weight sum, framed/CRC'd like every
    # other delta payload.
    record: bytes = b""
    # How many cohort replies folded into the record (telemetry/records
    # only — the combine weight travels INSIDE the record, where it is
    # covered by the frame CRC).
    clients: int = 0

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _LEN, self.record),
            (2, _VARINT, self.clients),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "SubmitPartialReply":
        f = _decode_fields(data)
        return cls(record=f.get(1, b""), clients=_int32(f.get(2, 0)))


@dataclasses.dataclass
class LeaveRequest:
    address: bytes = b""

    def encode(self) -> bytes:
        return _encode_fields([(1, _LEN, self.address)])

    @classmethod
    def decode(cls, data: bytes) -> "LeaveRequest":
        return cls(address=_decode_fields(data).get(1, b""))


@dataclasses.dataclass
class LeaveReply:
    left: int = 0
    version: int = 0

    def encode(self) -> bytes:
        return _encode_fields([
            (1, _VARINT, self.left),
            (2, _VARINT, self.version),
        ])

    @classmethod
    def decode(cls, data: bytes) -> "LeaveReply":
        f = _decode_fields(data)
        return cls(left=_int32(f.get(1, 0)), version=_int32(f.get(2, 0)))
