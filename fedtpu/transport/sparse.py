"""Sparse/quantized *delta* payloads for the DCN edge.

The reference's ``-c Y`` gzips a base64 dense checkpoint — the wire still
carries every parameter (``src/server.py:104-107``). When fedtpu's delta
compression is on, the distributed edge ships what the codec actually kept:
top-k ``(indices, values)`` pairs or int8 codes + scale per leaf, framed and
CRC-checked like :mod:`fedtpu.transport.wire` (magic ``FSP1`` vs the dense
format's ``FTP1``, so a receiver can dispatch on the first 4 bytes).

Wire size: top-k at fraction f costs ~``8 * f * n`` bytes (int32 idx + f32
val) vs ``4n`` dense — a 50x reduction at f=0.01; int8 costs ``n`` bytes —
4x. Encoding uses the native codec (:mod:`fedtpu.native`) when built.

Payloads are self-describing msgpack (no template needed to decode — nnz
varies per round), with leaf order = ``jax.tree_util.tree_flatten`` order of
the delta pytree, which both ends derive from the same model definition.
"""

from __future__ import annotations

import math
import struct
import zlib
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from fedtpu.native import (
    dequant_int8,
    kth_magnitude,
    pack_sparse,
    pack_sparse_with_residual,
    quant_int8,
    unpack_sparse,
)
from fedtpu.transport.wire import WireError

Pytree = Any

_MAGIC = b"FSP1"
_VERSION = 1
_HEADER = struct.Struct("<4sBBI")


def is_sparse_payload(data: bytes) -> bool:
    return data[:4] == _MAGIC


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(
        _MAGIC, _VERSION, 0, zlib.crc32(payload) & 0xFFFFFFFF
    ) + payload


def _unframe(data: bytes) -> bytes:
    if len(data) < _HEADER.size or data[:4] != _MAGIC:
        raise WireError("not a fedtpu sparse payload")
    _, version, _, crc = _HEADER.unpack_from(data)
    if version != _VERSION:
        raise WireError(f"unsupported sparse wire version {version}")
    payload = data[_HEADER.size :]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WireError("sparse payload CRC mismatch")
    return payload


def encode_topk(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
) -> Tuple[bytes, Optional[Pytree]]:
    """Sparsify a delta pytree to wire bytes; returns (payload, residuals).

    ``residuals`` (same structure) are added to the deltas before selection
    and replaced by the dropped mass — client-side error feedback, the edge
    analogue of :mod:`fedtpu.ops.compression`. With
    ``collect_residual=False`` (error feedback off) no residual tree is
    materialised and None is returned in its place.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out_leaves, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        k = max(1, int(math.ceil(fraction * x.size)))
        thresh = kth_magnitude(x, k)
        if thresh == 0.0:
            # Degenerate all-(near-)zero leaf: |x| >= 0 would "keep" every
            # element, making the sparse form 2x dense. Keep only true
            # nonzeros; the residual is exactly zero.
            idx = np.flatnonzero(x).astype(np.int32)
            vals = x[idx]
            residual = np.zeros_like(x) if collect_residual else None
        elif collect_residual:
            idx, vals, residual = pack_sparse_with_residual(x, thresh)
        else:
            idx, vals = pack_sparse(x, thresh)
            residual = None
        out_leaves.append(
            {"idx": idx, "vals": vals, "size": np.int64(x.size)}
        )
        if collect_residual:
            new_res.append(residual.reshape(np.shape(leaf)))
    body = {
        "kind": "topk",
        "leaves": {str(i): l for i, l in enumerate(out_leaves)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def encode_int8(
    deltas: Pytree,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = False,
) -> Tuple[bytes, Optional[Pytree]]:
    """Quantize a delta pytree to wire bytes; returns (payload, residuals).

    With ``collect_residual=True`` the per-round quantization error
    (``input - dequant(quant(input))``) is returned for error feedback,
    matching the simulated engine's int8 codec semantics
    (:func:`fedtpu.ops.compression.make_int8`).
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        codes, scale = quant_int8(x)
        out.append(
            {"codes": codes, "scale": np.float32(scale), "size": np.int64(x.size)}
        )
        if collect_residual:
            back = dequant_int8(codes, scale, x.size)
            new_res.append((x - back).reshape(np.shape(leaf)))
    body = {
        "kind": "int8",
        "leaves": {str(i): l for i, l in enumerate(out)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def decode(data: bytes, like: Pytree) -> Tuple[Pytree, dict]:
    """Reconstruct a dense delta pytree shaped like ``like``; returns
    (deltas, extra)."""
    body = serialization.msgpack_restore(_unframe(data))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(body["leaves"]) != len(leaves):
        raise WireError(
            f"sparse payload has {len(body['leaves'])} leaves, template has "
            f"{len(leaves)}"
        )
    enc = [body["leaves"][str(i)] for i in range(len(leaves))]
    out = []
    for leaf, e in zip(leaves, enc):
        n = int(e["size"])
        if n != np.size(leaf):
            raise WireError("sparse leaf size mismatch with template")
        if body["kind"] == "topk":
            idx = np.ascontiguousarray(e["idx"], np.int32)
            # Wire data is untrusted: the native scatter writes out[idx[i]]
            # unchecked, so out-of-range indices would be a heap write.
            if idx.size and (idx.min() < 0 or idx.max() >= n):
                raise WireError("sparse index out of range")
            dense = unpack_sparse(idx, e["vals"], n)
        elif body["kind"] == "int8":
            dense = dequant_int8(e["codes"], float(e["scale"]), n)
        else:
            raise WireError(f"unknown sparse kind {body['kind']!r}")
        out.append(dense.reshape(np.shape(leaf)).astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, out), dict(body.get("extra", {}))
