"""Sparse/quantized *delta* payloads for the DCN edge.

The reference's ``-c Y`` gzips a base64 dense checkpoint — the wire still
carries every parameter (``src/server.py:104-107``). When fedtpu's delta
compression is on, the distributed edge ships what the codec actually kept:
top-k ``(indices, values)`` pairs or int8 codes + scale per leaf, framed and
CRC-checked like :mod:`fedtpu.transport.wire` (magic ``FSP1`` vs the dense
format's ``FTP1``, so a receiver can dispatch on the first 4 bytes).

Wire size: top-k at fraction f costs ~``8 * f * n`` bytes (int32 idx + f32
val) vs ``4n`` dense — a 50x reduction at f=0.01; int8 costs ``n`` bytes —
4x. Encoding uses the native codec (:mod:`fedtpu.native`) when built.

Payloads are self-describing msgpack (no template needed to decode — nnz
varies per round), with leaf order = ``jax.tree_util.tree_flatten`` order of
the delta pytree, which both ends derive from the same model definition.

Flat records (kinds ``topk_flat`` / ``int8_flat``, the wire form of the
engine's ``FedConfig.delta_layout='flat'`` pipeline, :mod:`fedtpu.ops.flat`):
instead of one msgpack map entry per leaf — hundreds of small records on
deep zoo models — the whole delta travels as ONE contiguous index/value (or
int8 code) block over the concatenated flat vector, plus a ``sizes`` offsets
table for validation. Top-k selection is then GLOBAL across the model (one
``kth_magnitude`` over the concatenation); int8 keeps per-leaf scales (a
``[num_leaves]`` f32 array), matching the engine's flat codec bit-for-bit.
The same ``FSP1`` frame carries all the kinds; :func:`decode` dispatches on
``kind``, so receivers need no code change to accept flat senders.

Hierarchical fan-in adds a fifth kind, ``partial_flat``
(:func:`encode_partial_flat`): ONE dense f32 row carrying a leaf
aggregator's pre-weighted SUM of its cohort's flat delta rows plus the
summed combine weight (``extra['weight_sum']``) — the payload of the
``SubmitPartial`` RPC (docs/FLAT_DELTA.md §FSP1 record kinds).

The sketched-update codecs add two more kinds (docs/FLAT_DELTA.md §Codec
matrix):

- ``rotq_flat`` (:func:`encode_rotq_flat`): the delta vector rotated
  through a SEEDED randomized Hadamard transform and uniform-quantized to
  b bits per coordinate with stochastic rounding — ``b*h/8`` bytes of
  packed codes plus four scalars (seed, bits, lo, scale) in the extra
  block. The receiver regenerates the rotation from the seed and
  inverse-rotates; nothing model-sized beyond the codes travels.
- ``randk_flat`` (:func:`encode_randk_flat`): a SEEDED uniform draw of k
  coordinates — only the k f32 values travel; the index set is
  regenerated from the seed on the receiver (the wire advantage over
  top-k, which must ship explicit indices).

Both are deterministic functions of (input, seed): encoding the same delta
with the same seed is byte-identical, and decode is a pure function of the
record — the bit-identical-replay property ``tests/test_properties.py``
pins. The per-record PRNG is ``numpy``'s Philox keyed by the record seed,
with a fixed draw order (signs/indices FIRST, encoder-only stochastic-
rounding uniforms after) so the decoder can stop after the shared prefix.
"""

from __future__ import annotations

import math
import struct
import zlib
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from fedtpu.native import (
    dequant_int8,
    kth_magnitude,
    pack_sparse,
    pack_sparse_with_residual,
    quant_int8,
    unpack_sparse,
)
from fedtpu.transport.wire import WireError, frame as _wire_frame, unframe as _wire_unframe

Pytree = Any

_MAGIC = b"FSP1"
# Tracks the shared frame version (fedtpu.transport.wire): v2 frames CRC
# the header bytes too; v1 frames from older senders still decode.
_VERSION = 2
_HEADER = struct.Struct("<4sBBI")


def is_sparse_payload(data: bytes) -> bool:
    return data[:4] == _MAGIC


def _frame(payload: bytes) -> bytes:
    return _wire_frame(_MAGIC, payload, 0, version=_VERSION)


def _unframe(data: bytes) -> bytes:
    return _wire_unframe(_MAGIC, data, "sparse", version=_VERSION)[1]


def encode_topk(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
) -> Tuple[bytes, Optional[Pytree]]:
    """Sparsify a delta pytree to wire bytes; returns (payload, residuals).

    ``residuals`` (same structure) are added to the deltas before selection
    and replaced by the dropped mass — client-side error feedback, the edge
    analogue of :mod:`fedtpu.ops.compression`. With
    ``collect_residual=False`` (error feedback off) no residual tree is
    materialised and None is returned in its place.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out_leaves, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        k = max(1, int(math.ceil(fraction * x.size)))
        thresh = kth_magnitude(x, k)
        if thresh == 0.0:
            # Degenerate all-(near-)zero leaf: |x| >= 0 would "keep" every
            # element, making the sparse form 2x dense. Keep only true
            # nonzeros; the residual is exactly zero.
            idx = np.flatnonzero(x).astype(np.int32)
            vals = x[idx]
            residual = np.zeros_like(x) if collect_residual else None
        elif collect_residual:
            idx, vals, residual = pack_sparse_with_residual(x, thresh)
        else:
            idx, vals = pack_sparse(x, thresh)
            residual = None
        out_leaves.append(
            {"idx": idx, "vals": vals, "size": np.int64(x.size)}
        )
        if collect_residual:
            new_res.append(residual.reshape(np.shape(leaf)))
    body = {
        "kind": "topk",
        "leaves": {str(i): l for i, l in enumerate(out_leaves)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def encode_int8(
    deltas: Pytree,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = False,
) -> Tuple[bytes, Optional[Pytree]]:
    """Quantize a delta pytree to wire bytes; returns (payload, residuals).

    With ``collect_residual=True`` the per-round quantization error
    (``input - dequant(quant(input))``) is returned for error feedback,
    matching the simulated engine's int8 codec semantics
    (:func:`fedtpu.ops.compression.make_int8`).
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        codes, scale = quant_int8(x)
        out.append(
            {"codes": codes, "scale": np.float32(scale), "size": np.int64(x.size)}
        )
        if collect_residual:
            back = dequant_int8(codes, scale, x.size)
            new_res.append((x - back).reshape(np.shape(leaf)))
    body = {
        "kind": "int8",
        "leaves": {str(i): l for i, l in enumerate(out)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def _flat_concat(
    leaves, res_leaves
) -> Tuple[np.ndarray, list]:
    """Concatenate leaves (+ residuals) into one f32 vector; returns
    (vector, per-leaf sizes)."""
    sizes = [int(np.size(l)) for l in leaves]
    x = (
        np.concatenate([np.asarray(l, np.float32).ravel() for l in leaves])
        if leaves
        else np.zeros((0,), np.float32)
    )
    if res_leaves is not None:
        x = x + np.concatenate(
            [np.asarray(r, np.float32).ravel() for r in res_leaves]
        )
    return x, sizes


def _split_flat(vec: np.ndarray, leaves, treedef) -> Pytree:
    """Inverse of the concat: slice ``vec`` back into leaf shapes."""
    out, off = [], 0
    for leaf in leaves:
        n = int(np.size(leaf))
        out.append(vec[off : off + n].reshape(np.shape(leaf)))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def encode_topk_flat(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
) -> Tuple[bytes, Optional[Pytree]]:
    """Flat top-k wire record: ONE ``(indices, values)`` block over the
    concatenated delta vector instead of one record per leaf.

    The keep budget ``k = ceil(fraction * total)`` is GLOBAL across the
    model (one :func:`fedtpu.native.kth_magnitude` over the concatenation) —
    the wire twin of the engine's ``delta_layout='flat'`` top-k codec.
    Error-feedback semantics match :func:`encode_topk`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    k = max(1, int(math.ceil(fraction * max(x.size, 1))))
    thresh = kth_magnitude(x, k)
    if thresh == 0.0:
        # Degenerate all-(near-)zero vector: keep only true nonzeros (the
        # same rule as the per-leaf encoder's zero-leaf guard).
        idx = np.flatnonzero(x).astype(np.int32)
        vals = x[idx]
        residual = np.zeros_like(x) if collect_residual else None
    elif collect_residual:
        idx, vals, residual = pack_sparse_with_residual(x, thresh)
    else:
        idx, vals = pack_sparse(x, thresh)
        residual = None
    body = {
        "kind": "topk_flat",
        "sizes": np.asarray(sizes, np.int64),
        "idx": idx,
        "vals": vals,
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        _split_flat(residual, leaves, treedef) if collect_residual else None
    )
    return payload, residual_tree


def encode_int8_flat(
    deltas: Pytree,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = False,
) -> Tuple[bytes, Optional[Pytree]]:
    """Flat int8 wire record: ONE contiguous code block + a ``[num_leaves]``
    scale array instead of one record per leaf.

    Scales stay PER LEAF (``max|leaf| / 127``) so the reconstruction is
    bit-identical to :func:`encode_int8` — the same invariant the engine's
    flat int8 codec pins against its per-leaf twin.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    codes = np.empty(x.size, np.int8)
    scales = np.empty(len(sizes), np.float32)
    residual = np.empty(x.size, np.float32) if collect_residual else None
    off = 0
    for i, n in enumerate(sizes):
        seg = x[off : off + n]
        c, s = quant_int8(seg)
        codes[off : off + n] = c
        scales[i] = s
        if collect_residual:
            residual[off : off + n] = seg - dequant_int8(c, s, n)
        off += n
    body = {
        "kind": "int8_flat",
        "sizes": np.asarray(sizes, np.int64),
        "codes": codes,
        "scales": scales,
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        _split_flat(residual, leaves, treedef) if collect_residual else None
    )
    return payload, residual_tree


def encode_partial_flat(
    row: np.ndarray, sizes, extra: Optional[dict] = None
) -> bytes:
    """Hierarchical-aggregation wire record (kind ``partial_flat``): ONE
    dense f32 row — a cohort's PRE-WEIGHTED sum of flat delta rows
    (:func:`fedtpu.ops.flat.partial_reduce_rows`) — plus the per-leaf
    ``sizes`` table for validation. A sum of many clients' updates has no
    exploitable sparsity, so the record is dense by design; what the
    hierarchy saves is FAN-IN (the root decodes one record per aggregator,
    not one per client), not per-record bytes.

    ``extra`` MUST carry ``weight_sum`` (the cohort's summed combine
    weights — the root's combine weight for this row) and conventionally
    carries ``clients`` / ``t_leaf_s`` for records and the fan-in bench.
    ``row`` is the UNPADDED ``[total]`` prefix (pad coordinates of a
    pad-clean buffer are zero under a weighted sum, so they never travel).
    """
    sizes = [int(s) for s in sizes]
    row = np.ascontiguousarray(row, np.float32)
    if row.ndim != 1 or row.size != sum(sizes):
        raise ValueError(
            f"partial row has {row.shape} coordinates, sizes table sums to "
            f"{sum(sizes)}"
        )
    body = {
        "kind": "partial_flat",
        "sizes": np.asarray(sizes, np.int64),
        "row": row,
        "extra": extra or {},
    }
    return _frame(serialization.msgpack_serialize(body))


# --------------------------------------------------------------------------
# Seeded sketch codecs: rotq_flat (rotated b-bit quantization) and
# randk_flat (random-coordinate subsampling). Shared-seed regeneration means
# the model-sized side information (rotation signs, index set) never travels.
# --------------------------------------------------------------------------

# Bit widths the rotq wire codec packs (byte-aligned packing below covers
# exactly the divisors of 8). Mirrors fedtpu.ops.compression.ROTQ_BIT_WIDTHS.
ROTQ_BITS = (1, 2, 4, 8)


def _next_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1) (fedtpu.ops.flat.next_pow2 twin —
    local copy so the wire layer stays importable without the engine ops)."""
    return 1 << max(n - 1, 0).bit_length()


def _fwht_np(x: np.ndarray) -> np.ndarray:
    """Unnormalized fast Walsh-Hadamard transform of a 1-D f32 vector.

    Same stride-doubling butterfly as the engine kernel
    (:func:`fedtpu.ops.pallas_kernels.hadamard_rotate`), in numpy for the
    wire hot path (the decode side runs on the serving thread, no jax
    dispatch). ``x.size`` must be a power of two.
    """
    h = x.size
    y = np.array(x, np.float32, copy=True)
    step = 1
    while step < h:
        v = y.reshape(h // (2 * step), 2, step)
        a = v[:, 0, :].copy()
        b = v[:, 1, :].copy()
        v[:, 0, :] = a + b
        v[:, 1, :] = a - b
        step *= 2
    return y


def _philox(seed: int) -> np.random.Generator:
    """The per-record PRNG: counter-based, so the stream for a seed is a
    platform-independent pure function — the replay property both ends and
    the tests rely on."""
    return np.random.Generator(np.random.Philox(int(seed) & (2**64 - 1)))


def _rotq_signs(rng: np.random.Generator, h: int) -> np.ndarray:
    """Rademacher diagonal — the FIRST ``h`` draws of the record stream, so
    the decoder (which needs nothing else) can stop here while the encoder
    keeps drawing its stochastic-rounding uniforms from the same stream."""
    return rng.integers(0, 2, size=h).astype(np.float32) * 2.0 - 1.0


def _pack_codes(q: np.ndarray, bits: int) -> np.ndarray:
    """Pack uint8 codes < 2**bits into a dense byte array (little-endian
    within the byte for bits in {2, 4}; numpy's MSB-first convention for
    bits == 1 — each is its own unpack's exact inverse)."""
    if bits == 8:
        return np.ascontiguousarray(q, np.uint8)
    if bits == 1:
        return np.packbits(np.ascontiguousarray(q, np.uint8))
    per = 8 // bits
    pad = (-q.size) % per
    if pad:
        q = np.concatenate([q, np.zeros(pad, np.uint8)])
    q = np.ascontiguousarray(q, np.uint8).reshape(-1, per)
    out = np.zeros(q.shape[0], np.uint8)
    for j in range(per):
        out |= q[:, j] << np.uint8(bits * j)
    return out


def _unpack_codes(codes: np.ndarray, bits: int, h: int) -> np.ndarray:
    """Inverse of :func:`_pack_codes`; validates the byte count (untrusted
    wire data) and returns exactly ``h`` uint8 codes."""
    codes = np.ascontiguousarray(codes, np.uint8)
    if codes.size != (h * bits + 7) // 8:
        raise WireError("rotq_flat code block size mismatch")
    if bits == 8:
        q = codes
    elif bits == 1:
        q = np.unpackbits(codes)
    else:
        per = 8 // bits
        mask = np.uint8((1 << bits) - 1)
        q = np.empty(codes.size * per, np.uint8)
        for j in range(per):
            q[j::per] = (codes >> np.uint8(bits * j)) & mask
    return q[:h]


def _rotq_dequant(
    q: np.ndarray, lo: float, scale: float, signs: np.ndarray, h: int
) -> np.ndarray:
    """Shared reconstruction: dequantize codes and inverse-rotate. The
    encoder uses the SAME function for its error-feedback residual, so the
    client's residual is computed against exactly what the server will
    reconstruct — no encoder/decoder drift."""
    safe = np.float32(scale) if float(scale) > 0.0 else np.float32(1.0)
    zq = np.float32(lo) + q.astype(np.float32) * safe
    return _fwht_np(zq) * np.float32(1.0 / math.sqrt(h)) * signs


def encode_rotq_flat(
    deltas: Pytree,
    bits: int = 4,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
    seed: int = 0,
) -> Tuple[bytes, Optional[Pytree]]:
    """Rotated-quantization wire record (kind ``rotq_flat``).

    The concatenated delta vector is zero-padded to the next power of two,
    rotated by the seeded SRHT ``R = (1/sqrt(h)) H D`` (signs regenerated
    from ``seed`` on both ends), and uniform-quantized to ``bits`` bits per
    coordinate with stochastic rounding — conditionally unbiased, and the
    rotation spreads outlier coordinates so the uniform grid wastes no
    range. Wire cost: ``bits * h / 8`` bytes of packed codes + four scalars
    (seed / bits / lo / scale) riding in the record's extra block — 8x
    smaller than dense f32 at bits=4, 16x at bits=2.

    Error feedback: with ``collect_residual=True`` the returned residual is
    ``input - reconstruct(record)`` via the same :func:`_rotq_dequant` the
    decoder runs, composing with the client's EF buffer exactly like the
    engine codec (:func:`fedtpu.ops.compression.make_rotq`).

    Same (input, seed) => byte-identical payload (Philox is counter-based).
    """
    if bits not in ROTQ_BITS:
        raise ValueError(f"rotq bits must be one of {ROTQ_BITS}, got {bits}")
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    total = x.size
    h = _next_pow2(max(total, 1))
    rng = _philox(seed)
    signs = _rotq_signs(rng, h)
    xp = np.zeros(h, np.float32)
    xp[:total] = x
    z = _fwht_np(xp * signs) * np.float32(1.0 / math.sqrt(h))
    levels = np.float32(2**bits - 1)
    lo = np.float32(z.min())
    scale = np.float32((z.max() - lo) / levels)
    safe = scale if float(scale) > 0.0 else np.float32(1.0)
    # Stochastic rounding: floor(z/safe + u), u ~ U[0,1) — E[q] recovers z
    # exactly (conditionally unbiased given the rotation). Drawn AFTER the
    # signs from the same stream; the decoder never needs them.
    u = rng.random(h, dtype=np.float32)
    q = np.clip(np.floor((z - lo) / safe + u), 0.0, float(levels)).astype(
        np.uint8
    )
    body = {
        "kind": "rotq_flat",
        "sizes": np.asarray(sizes, np.int64),
        "codes": _pack_codes(q, bits),
        "extra": {
            **(extra or {}),
            "seed": np.uint64(seed),
            "bits": np.int64(bits),
            "lo": lo,
            "scale": scale,
        },
    }
    payload = _frame(serialization.msgpack_serialize(body))
    if not collect_residual:
        return payload, None
    back = _rotq_dequant(q, lo, scale, signs, h)
    residual = x - back[:total]
    return payload, _split_flat(residual, leaves, treedef)


def _rotq_reconstruct(body: dict, total: int) -> np.ndarray:
    """Decode a ``rotq_flat`` body to the dense ``[total]`` vector
    (regenerate signs from the seed, dequantize, inverse-rotate, drop the
    pow2 pad). All fields are untrusted wire data and validated."""
    ex = body.get("extra", {})
    try:
        bits = int(ex["bits"])
        seed = int(ex["seed"])
        lo = float(ex["lo"])
        scale = float(ex["scale"])
    except (KeyError, TypeError, ValueError):
        raise WireError("rotq_flat record missing codec scalars")
    if bits not in ROTQ_BITS:
        raise WireError(f"rotq_flat unsupported bit width {bits}")
    if not (math.isfinite(lo) and math.isfinite(scale)) or scale < 0.0:
        raise WireError("rotq_flat non-finite quantization scalars")
    h = _next_pow2(max(total, 1))
    q = _unpack_codes(np.asarray(body["codes"]), bits, h)
    signs = _rotq_signs(_philox(seed), h)
    return _rotq_dequant(q, np.float32(lo), np.float32(scale), signs, h)[
        :total
    ]


def _randk_indices(seed: int, total: int, k: int) -> np.ndarray:
    """The shared seeded index set: a uniform draw of k coordinates WITHOUT
    replacement, sorted for a cache-friendly scatter. Pure function of
    (seed, total, k) — the decoder regenerates it instead of receiving it."""
    if total <= 0 or k <= 0:
        return np.zeros(0, np.int64)
    rng = _philox(seed)
    return np.sort(rng.choice(total, size=k, replace=False).astype(np.int64))


def encode_randk_flat(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
    seed: int = 0,
) -> Tuple[bytes, Optional[Pytree]]:
    """Random-k wire record (kind ``randk_flat``): ship only the f32 values
    at a SEEDED uniform draw of ``k = ceil(fraction * total)`` coordinates.
    No index block travels (the receiver regenerates it from ``seed``), so
    the record costs ``4k`` bytes where flat top-k costs ``8k`` — the
    importance-sampling end of the codec frontier.

    Error-feedback rule (pinned, mirrors
    :func:`fedtpu.ops.compression.make_randk`): with
    ``collect_residual=True`` the kept values travel UNSCALED and the
    dropped mass goes to the residual — kept + residual == input exactly,
    the contraction EF needs. With ``collect_residual=False`` the values
    are pre-scaled by ``total / k`` on the encoder (unbiased estimator);
    the decoder just scatters either way.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    total = x.size
    k = (
        min(max(1, int(math.ceil(fraction * total))), total)
        if total
        else 0
    )
    idx = _randk_indices(seed, total, k)
    vals = np.ascontiguousarray(x[idx], np.float32)
    if not collect_residual and 0 < k < total:
        vals = vals * np.float32(total / k)
    body = {
        "kind": "randk_flat",
        "sizes": np.asarray(sizes, np.int64),
        "vals": vals,
        "extra": {
            **(extra or {}),
            "seed": np.uint64(seed),
            "k": np.int64(k),
        },
    }
    payload = _frame(serialization.msgpack_serialize(body))
    if not collect_residual:
        return payload, None
    residual = x.copy()
    residual[idx] = 0.0
    return payload, _split_flat(residual, leaves, treedef)


def _randk_scatter(body: dict, total: int, out: np.ndarray) -> None:
    """Decode a ``randk_flat`` body into ``out[:total]`` (zeros elsewhere in
    the real-coordinate range). Untrusted fields validated."""
    ex = body.get("extra", {})
    try:
        k = int(ex["k"])
        seed = int(ex["seed"])
    except (KeyError, TypeError, ValueError):
        raise WireError("randk_flat record missing codec scalars")
    vals = np.asarray(body["vals"], np.float32)
    if k < 0 or k > total or vals.size != k:
        raise WireError("randk_flat k/value-block mismatch")
    idx = _randk_indices(seed, total, k)
    out[:total] = 0.0
    out[idx] = vals


def _decode_flat(body: dict, leaves, treedef) -> Pytree:
    """Reconstruct a dense delta pytree from a flat record body."""
    sizes = np.asarray(body["sizes"], np.int64)
    if len(sizes) != len(leaves):
        raise WireError(
            f"flat payload has {len(sizes)} leaves, template has {len(leaves)}"
        )
    for n, leaf in zip(sizes, leaves):
        if int(n) != np.size(leaf):
            raise WireError("flat leaf size mismatch with template")
    total = int(sizes.sum())
    if body["kind"] == "partial_flat":
        dense = np.asarray(body["row"], np.float32)
        if dense.size != total:
            raise WireError("partial_flat row size mismatch with template")
    elif body["kind"] == "rotq_flat":
        dense = _rotq_reconstruct(body, total)
    elif body["kind"] == "randk_flat":
        dense = np.zeros(total, np.float32)
        _randk_scatter(body, total, dense)
    elif body["kind"] == "topk_flat":
        idx = np.ascontiguousarray(body["idx"], np.int32)
        # Untrusted wire data: the native scatter writes unchecked.
        if idx.size and (idx.min() < 0 or idx.max() >= total):
            raise WireError("sparse index out of range")
        dense = unpack_sparse(idx, body["vals"], total)
    else:  # int8_flat
        codes = np.ascontiguousarray(body["codes"], np.int8)
        if codes.size != total:
            raise WireError("int8_flat code block size mismatch")
        scales = np.asarray(body["scales"], np.float32)
        if scales.size != len(sizes):
            raise WireError("int8_flat scale table size mismatch")
        dense = np.empty(total, np.float32)
        off = 0
        for n, s in zip(sizes, scales):
            n = int(n)
            dense[off : off + n] = dequant_int8(
                codes[off : off + n], float(s), n
            )
            off += n
    out = []
    off = 0
    for leaf in leaves:
        n = int(np.size(leaf))
        out.append(
            dense[off : off + n]
            .reshape(np.shape(leaf))
            .astype(np.asarray(leaf).dtype)
        )
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def decode_into_row(
    data: bytes, sizes, out: np.ndarray
) -> dict:
    """Decode a sparse payload DIRECTLY into a preallocated f32 row.

    The streaming server pipeline's decode: no per-leaf template trees, no
    ``tree_unflatten``, no per-leaf reshape/astype — the record's values
    land straight in ``out[: total]``, the row of the server's
    ``[clients, P]`` flat buffer (``fedtpu.ops.flat`` coordinate order,
    which both ends derive from the shared model definition). ``sizes`` is
    the per-leaf scalar-count table (``FlatLayout.sizes``). Every real
    coordinate of ``out`` is written (kept values, zeros for dropped top-k
    coordinates); ``out[total:]`` — the lane padding — is never touched, so
    a zero-initialised reusable buffer stays pad-clean across rounds.

    Returns the record's ``extra`` dict. Raises :class:`WireError` on any
    template mismatch or out-of-range index, exactly like :func:`decode`.
    """
    body = serialization.msgpack_restore(_unframe(data))
    sizes = [int(s) for s in sizes]
    total = sum(sizes)
    if out.shape[0] < total or out.dtype != np.float32:
        raise ValueError(
            f"row buffer too small or not f32: {out.shape} {out.dtype} "
            f"for {total} coordinates"
        )
    kind = body.get("kind")
    if kind in (
        "topk_flat",
        "int8_flat",
        "partial_flat",
        "rotq_flat",
        "randk_flat",
    ):
        wire_sizes = np.asarray(body["sizes"], np.int64)
        if len(wire_sizes) != len(sizes):
            raise WireError(
                f"flat payload has {len(wire_sizes)} leaves, layout has "
                f"{len(sizes)}"
            )
        for n, m in zip(wire_sizes, sizes):
            if int(n) != m:
                raise WireError("flat leaf size mismatch with layout")
        if kind == "partial_flat":
            # Hierarchical partial sum: a dense f32 row lands verbatim —
            # the straight-copy degenerate case of the streaming decode
            # (the root's per-aggregator cost is ONE memcpy + validation,
            # the O(aggregators) claim the fan-in bench measures).
            row = np.asarray(body["row"], np.float32)
            if row.size != total:
                raise WireError("partial_flat row size mismatch with layout")
            out[:total] = row
        elif kind == "rotq_flat":
            out[:total] = _rotq_reconstruct(body, total)
        elif kind == "randk_flat":
            _randk_scatter(body, total, out)
        elif kind == "topk_flat":
            idx = np.ascontiguousarray(body["idx"], np.int32)
            # Untrusted wire data: the scatter below writes unchecked.
            if idx.size and (idx.min() < 0 or idx.max() >= total):
                raise WireError("sparse index out of range")
            out[:total] = 0.0
            out[idx] = np.asarray(body["vals"], np.float32)
        else:  # int8_flat
            codes = np.ascontiguousarray(body["codes"], np.int8)
            if codes.size != total:
                raise WireError("int8_flat code block size mismatch")
            scales = np.asarray(body["scales"], np.float32)
            if scales.size != len(sizes):
                raise WireError("int8_flat scale table size mismatch")
            off = 0
            for n, s in zip(sizes, scales):
                out[off : off + n] = dequant_int8(
                    codes[off : off + n], float(s), n
                )
                off += n
        extra = dict(body.get("extra", {}))
        # Advisory decode-side codec tag for the per-codec wire accounting
        # (fedtpu_rpc_bytes_*_total{codec=...}); transport-internal, popped
        # by the server before extras reach user records.
        extra["_codec"] = kind
        return extra
    # Per-leaf record kinds (topk | int8): one entry per leaf, scattered
    # into the leaf's slice of the row.
    if len(body["leaves"]) != len(sizes):
        raise WireError(
            f"sparse payload has {len(body['leaves'])} leaves, layout has "
            f"{len(sizes)}"
        )
    off = 0
    for i, n in enumerate(sizes):
        e = body["leaves"][str(i)]
        if int(e["size"]) != n:
            raise WireError("sparse leaf size mismatch with layout")
        if kind == "topk":
            idx = np.ascontiguousarray(e["idx"], np.int32)
            if idx.size and (idx.min() < 0 or idx.max() >= n):
                raise WireError("sparse index out of range")
            out[off : off + n] = 0.0
            out[off + idx] = np.asarray(e["vals"], np.float32)
        elif kind == "int8":
            out[off : off + n] = dequant_int8(e["codes"], float(e["scale"]), n)
        else:
            raise WireError(f"unknown sparse kind {kind!r}")
        off += n
    extra = dict(body.get("extra", {}))
    extra["_codec"] = kind
    return extra


def decode(data: bytes, like: Pytree) -> Tuple[Pytree, dict]:
    """Reconstruct a dense delta pytree shaped like ``like``; returns
    (deltas, extra)."""
    body = serialization.msgpack_restore(_unframe(data))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if body.get("kind") in (
        "topk_flat",
        "int8_flat",
        "partial_flat",
        "rotq_flat",
        "randk_flat",
    ):
        extra = dict(body.get("extra", {}))
        extra["_codec"] = body["kind"]
        return _decode_flat(body, leaves, treedef), extra
    if len(body["leaves"]) != len(leaves):
        raise WireError(
            f"sparse payload has {len(body['leaves'])} leaves, template has "
            f"{len(leaves)}"
        )
    enc = [body["leaves"][str(i)] for i in range(len(leaves))]
    out = []
    for leaf, e in zip(leaves, enc):
        n = int(e["size"])
        if n != np.size(leaf):
            raise WireError("sparse leaf size mismatch with template")
        if body["kind"] == "topk":
            idx = np.ascontiguousarray(e["idx"], np.int32)
            # Wire data is untrusted: the native scatter writes out[idx[i]]
            # unchecked, so out-of-range indices would be a heap write.
            if idx.size and (idx.min() < 0 or idx.max() >= n):
                raise WireError("sparse index out of range")
            dense = unpack_sparse(idx, e["vals"], n)
        elif body["kind"] == "int8":
            dense = dequant_int8(e["codes"], float(e["scale"]), n)
        else:
            raise WireError(f"unknown sparse kind {body['kind']!r}")
        out.append(dense.reshape(np.shape(leaf)).astype(np.asarray(leaf).dtype))
    extra = dict(body.get("extra", {}))
    extra["_codec"] = body["kind"]
    return jax.tree_util.tree_unflatten(treedef, out), extra
