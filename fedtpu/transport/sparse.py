"""Sparse/quantized *delta* payloads for the DCN edge.

The reference's ``-c Y`` gzips a base64 dense checkpoint — the wire still
carries every parameter (``src/server.py:104-107``). When fedtpu's delta
compression is on, the distributed edge ships what the codec actually kept:
top-k ``(indices, values)`` pairs or int8 codes + scale per leaf, framed and
CRC-checked like :mod:`fedtpu.transport.wire` (magic ``FSP1`` vs the dense
format's ``FTP1``, so a receiver can dispatch on the first 4 bytes).

Wire size: top-k at fraction f costs ~``8 * f * n`` bytes (int32 idx + f32
val) vs ``4n`` dense — a 50x reduction at f=0.01; int8 costs ``n`` bytes —
4x. Encoding uses the native codec (:mod:`fedtpu.native`) when built.

Payloads are self-describing msgpack (no template needed to decode — nnz
varies per round), with leaf order = ``jax.tree_util.tree_flatten`` order of
the delta pytree, which both ends derive from the same model definition.

Flat records (kinds ``topk_flat`` / ``int8_flat``, the wire form of the
engine's ``FedConfig.delta_layout='flat'`` pipeline, :mod:`fedtpu.ops.flat`):
instead of one msgpack map entry per leaf — hundreds of small records on
deep zoo models — the whole delta travels as ONE contiguous index/value (or
int8 code) block over the concatenated flat vector, plus a ``sizes`` offsets
table for validation. Top-k selection is then GLOBAL across the model (one
``kth_magnitude`` over the concatenation); int8 keeps per-leaf scales (a
``[num_leaves]`` f32 array), matching the engine's flat codec bit-for-bit.
The same ``FSP1`` frame carries all the kinds; :func:`decode` dispatches on
``kind``, so receivers need no code change to accept flat senders.

Hierarchical fan-in adds a fifth kind, ``partial_flat``
(:func:`encode_partial_flat`): ONE dense f32 row carrying a leaf
aggregator's pre-weighted SUM of its cohort's flat delta rows plus the
summed combine weight (``extra['weight_sum']``) — the payload of the
``SubmitPartial`` RPC (docs/FLAT_DELTA.md §FSP1 record kinds).
"""

from __future__ import annotations

import math
import struct
import zlib
from typing import Any, Optional, Tuple

import jax
import numpy as np
from flax import serialization

from fedtpu.native import (
    dequant_int8,
    kth_magnitude,
    pack_sparse,
    pack_sparse_with_residual,
    quant_int8,
    unpack_sparse,
)
from fedtpu.transport.wire import WireError, frame as _wire_frame, unframe as _wire_unframe

Pytree = Any

_MAGIC = b"FSP1"
# Tracks the shared frame version (fedtpu.transport.wire): v2 frames CRC
# the header bytes too; v1 frames from older senders still decode.
_VERSION = 2
_HEADER = struct.Struct("<4sBBI")


def is_sparse_payload(data: bytes) -> bool:
    return data[:4] == _MAGIC


def _frame(payload: bytes) -> bytes:
    return _wire_frame(_MAGIC, payload, 0, version=_VERSION)


def _unframe(data: bytes) -> bytes:
    return _wire_unframe(_MAGIC, data, "sparse", version=_VERSION)[1]


def encode_topk(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
) -> Tuple[bytes, Optional[Pytree]]:
    """Sparsify a delta pytree to wire bytes; returns (payload, residuals).

    ``residuals`` (same structure) are added to the deltas before selection
    and replaced by the dropped mass — client-side error feedback, the edge
    analogue of :mod:`fedtpu.ops.compression`. With
    ``collect_residual=False`` (error feedback off) no residual tree is
    materialised and None is returned in its place.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out_leaves, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        k = max(1, int(math.ceil(fraction * x.size)))
        thresh = kth_magnitude(x, k)
        if thresh == 0.0:
            # Degenerate all-(near-)zero leaf: |x| >= 0 would "keep" every
            # element, making the sparse form 2x dense. Keep only true
            # nonzeros; the residual is exactly zero.
            idx = np.flatnonzero(x).astype(np.int32)
            vals = x[idx]
            residual = np.zeros_like(x) if collect_residual else None
        elif collect_residual:
            idx, vals, residual = pack_sparse_with_residual(x, thresh)
        else:
            idx, vals = pack_sparse(x, thresh)
            residual = None
        out_leaves.append(
            {"idx": idx, "vals": vals, "size": np.int64(x.size)}
        )
        if collect_residual:
            new_res.append(residual.reshape(np.shape(leaf)))
    body = {
        "kind": "topk",
        "leaves": {str(i): l for i, l in enumerate(out_leaves)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def encode_int8(
    deltas: Pytree,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = False,
) -> Tuple[bytes, Optional[Pytree]]:
    """Quantize a delta pytree to wire bytes; returns (payload, residuals).

    With ``collect_residual=True`` the per-round quantization error
    (``input - dequant(quant(input))``) is returned for error feedback,
    matching the simulated engine's int8 codec semantics
    (:func:`fedtpu.ops.compression.make_int8`).
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else [None] * len(leaves)
    )
    out, new_res = [], []
    for leaf, res in zip(leaves, res_leaves):
        x = np.asarray(leaf, np.float32).ravel()
        if res is not None:
            x = x + np.asarray(res, np.float32).ravel()
        codes, scale = quant_int8(x)
        out.append(
            {"codes": codes, "scale": np.float32(scale), "size": np.int64(x.size)}
        )
        if collect_residual:
            back = dequant_int8(codes, scale, x.size)
            new_res.append((x - back).reshape(np.shape(leaf)))
    body = {
        "kind": "int8",
        "leaves": {str(i): l for i, l in enumerate(out)},
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        jax.tree_util.tree_unflatten(treedef, new_res)
        if collect_residual
        else None
    )
    return payload, residual_tree


def _flat_concat(
    leaves, res_leaves
) -> Tuple[np.ndarray, list]:
    """Concatenate leaves (+ residuals) into one f32 vector; returns
    (vector, per-leaf sizes)."""
    sizes = [int(np.size(l)) for l in leaves]
    x = (
        np.concatenate([np.asarray(l, np.float32).ravel() for l in leaves])
        if leaves
        else np.zeros((0,), np.float32)
    )
    if res_leaves is not None:
        x = x + np.concatenate(
            [np.asarray(r, np.float32).ravel() for r in res_leaves]
        )
    return x, sizes


def _split_flat(vec: np.ndarray, leaves, treedef) -> Pytree:
    """Inverse of the concat: slice ``vec`` back into leaf shapes."""
    out, off = [], 0
    for leaf in leaves:
        n = int(np.size(leaf))
        out.append(vec[off : off + n].reshape(np.shape(leaf)))
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def encode_topk_flat(
    deltas: Pytree,
    fraction: float,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = True,
) -> Tuple[bytes, Optional[Pytree]]:
    """Flat top-k wire record: ONE ``(indices, values)`` block over the
    concatenated delta vector instead of one record per leaf.

    The keep budget ``k = ceil(fraction * total)`` is GLOBAL across the
    model (one :func:`fedtpu.native.kth_magnitude` over the concatenation) —
    the wire twin of the engine's ``delta_layout='flat'`` top-k codec.
    Error-feedback semantics match :func:`encode_topk`.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    k = max(1, int(math.ceil(fraction * max(x.size, 1))))
    thresh = kth_magnitude(x, k)
    if thresh == 0.0:
        # Degenerate all-(near-)zero vector: keep only true nonzeros (the
        # same rule as the per-leaf encoder's zero-leaf guard).
        idx = np.flatnonzero(x).astype(np.int32)
        vals = x[idx]
        residual = np.zeros_like(x) if collect_residual else None
    elif collect_residual:
        idx, vals, residual = pack_sparse_with_residual(x, thresh)
    else:
        idx, vals = pack_sparse(x, thresh)
        residual = None
    body = {
        "kind": "topk_flat",
        "sizes": np.asarray(sizes, np.int64),
        "idx": idx,
        "vals": vals,
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        _split_flat(residual, leaves, treedef) if collect_residual else None
    )
    return payload, residual_tree


def encode_int8_flat(
    deltas: Pytree,
    residuals: Optional[Pytree] = None,
    extra: Optional[dict] = None,
    collect_residual: bool = False,
) -> Tuple[bytes, Optional[Pytree]]:
    """Flat int8 wire record: ONE contiguous code block + a ``[num_leaves]``
    scale array instead of one record per leaf.

    Scales stay PER LEAF (``max|leaf| / 127``) so the reconstruction is
    bit-identical to :func:`encode_int8` — the same invariant the engine's
    flat int8 codec pins against its per-leaf twin.
    """
    leaves, treedef = jax.tree_util.tree_flatten(deltas)
    res_leaves = (
        jax.tree_util.tree_flatten(residuals)[0]
        if residuals is not None
        else None
    )
    x, sizes = _flat_concat(leaves, res_leaves)
    codes = np.empty(x.size, np.int8)
    scales = np.empty(len(sizes), np.float32)
    residual = np.empty(x.size, np.float32) if collect_residual else None
    off = 0
    for i, n in enumerate(sizes):
        seg = x[off : off + n]
        c, s = quant_int8(seg)
        codes[off : off + n] = c
        scales[i] = s
        if collect_residual:
            residual[off : off + n] = seg - dequant_int8(c, s, n)
        off += n
    body = {
        "kind": "int8_flat",
        "sizes": np.asarray(sizes, np.int64),
        "codes": codes,
        "scales": scales,
        "extra": extra or {},
    }
    payload = _frame(serialization.msgpack_serialize(body))
    residual_tree = (
        _split_flat(residual, leaves, treedef) if collect_residual else None
    )
    return payload, residual_tree


def encode_partial_flat(
    row: np.ndarray, sizes, extra: Optional[dict] = None
) -> bytes:
    """Hierarchical-aggregation wire record (kind ``partial_flat``): ONE
    dense f32 row — a cohort's PRE-WEIGHTED sum of flat delta rows
    (:func:`fedtpu.ops.flat.partial_reduce_rows`) — plus the per-leaf
    ``sizes`` table for validation. A sum of many clients' updates has no
    exploitable sparsity, so the record is dense by design; what the
    hierarchy saves is FAN-IN (the root decodes one record per aggregator,
    not one per client), not per-record bytes.

    ``extra`` MUST carry ``weight_sum`` (the cohort's summed combine
    weights — the root's combine weight for this row) and conventionally
    carries ``clients`` / ``t_leaf_s`` for records and the fan-in bench.
    ``row`` is the UNPADDED ``[total]`` prefix (pad coordinates of a
    pad-clean buffer are zero under a weighted sum, so they never travel).
    """
    sizes = [int(s) for s in sizes]
    row = np.ascontiguousarray(row, np.float32)
    if row.ndim != 1 or row.size != sum(sizes):
        raise ValueError(
            f"partial row has {row.shape} coordinates, sizes table sums to "
            f"{sum(sizes)}"
        )
    body = {
        "kind": "partial_flat",
        "sizes": np.asarray(sizes, np.int64),
        "row": row,
        "extra": extra or {},
    }
    return _frame(serialization.msgpack_serialize(body))


def _decode_flat(body: dict, leaves, treedef) -> Pytree:
    """Reconstruct a dense delta pytree from a flat record body."""
    sizes = np.asarray(body["sizes"], np.int64)
    if len(sizes) != len(leaves):
        raise WireError(
            f"flat payload has {len(sizes)} leaves, template has {len(leaves)}"
        )
    for n, leaf in zip(sizes, leaves):
        if int(n) != np.size(leaf):
            raise WireError("flat leaf size mismatch with template")
    total = int(sizes.sum())
    if body["kind"] == "partial_flat":
        dense = np.asarray(body["row"], np.float32)
        if dense.size != total:
            raise WireError("partial_flat row size mismatch with template")
    elif body["kind"] == "topk_flat":
        idx = np.ascontiguousarray(body["idx"], np.int32)
        # Untrusted wire data: the native scatter writes unchecked.
        if idx.size and (idx.min() < 0 or idx.max() >= total):
            raise WireError("sparse index out of range")
        dense = unpack_sparse(idx, body["vals"], total)
    else:  # int8_flat
        codes = np.ascontiguousarray(body["codes"], np.int8)
        if codes.size != total:
            raise WireError("int8_flat code block size mismatch")
        scales = np.asarray(body["scales"], np.float32)
        if scales.size != len(sizes):
            raise WireError("int8_flat scale table size mismatch")
        dense = np.empty(total, np.float32)
        off = 0
        for n, s in zip(sizes, scales):
            n = int(n)
            dense[off : off + n] = dequant_int8(
                codes[off : off + n], float(s), n
            )
            off += n
    out = []
    off = 0
    for leaf in leaves:
        n = int(np.size(leaf))
        out.append(
            dense[off : off + n]
            .reshape(np.shape(leaf))
            .astype(np.asarray(leaf).dtype)
        )
        off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def decode_into_row(
    data: bytes, sizes, out: np.ndarray
) -> dict:
    """Decode a sparse payload DIRECTLY into a preallocated f32 row.

    The streaming server pipeline's decode: no per-leaf template trees, no
    ``tree_unflatten``, no per-leaf reshape/astype — the record's values
    land straight in ``out[: total]``, the row of the server's
    ``[clients, P]`` flat buffer (``fedtpu.ops.flat`` coordinate order,
    which both ends derive from the shared model definition). ``sizes`` is
    the per-leaf scalar-count table (``FlatLayout.sizes``). Every real
    coordinate of ``out`` is written (kept values, zeros for dropped top-k
    coordinates); ``out[total:]`` — the lane padding — is never touched, so
    a zero-initialised reusable buffer stays pad-clean across rounds.

    Returns the record's ``extra`` dict. Raises :class:`WireError` on any
    template mismatch or out-of-range index, exactly like :func:`decode`.
    """
    body = serialization.msgpack_restore(_unframe(data))
    sizes = [int(s) for s in sizes]
    total = sum(sizes)
    if out.shape[0] < total or out.dtype != np.float32:
        raise ValueError(
            f"row buffer too small or not f32: {out.shape} {out.dtype} "
            f"for {total} coordinates"
        )
    kind = body.get("kind")
    if kind in ("topk_flat", "int8_flat", "partial_flat"):
        wire_sizes = np.asarray(body["sizes"], np.int64)
        if len(wire_sizes) != len(sizes):
            raise WireError(
                f"flat payload has {len(wire_sizes)} leaves, layout has "
                f"{len(sizes)}"
            )
        for n, m in zip(wire_sizes, sizes):
            if int(n) != m:
                raise WireError("flat leaf size mismatch with layout")
        if kind == "partial_flat":
            # Hierarchical partial sum: a dense f32 row lands verbatim —
            # the straight-copy degenerate case of the streaming decode
            # (the root's per-aggregator cost is ONE memcpy + validation,
            # the O(aggregators) claim the fan-in bench measures).
            row = np.asarray(body["row"], np.float32)
            if row.size != total:
                raise WireError("partial_flat row size mismatch with layout")
            out[:total] = row
        elif kind == "topk_flat":
            idx = np.ascontiguousarray(body["idx"], np.int32)
            # Untrusted wire data: the scatter below writes unchecked.
            if idx.size and (idx.min() < 0 or idx.max() >= total):
                raise WireError("sparse index out of range")
            out[:total] = 0.0
            out[idx] = np.asarray(body["vals"], np.float32)
        else:  # int8_flat
            codes = np.ascontiguousarray(body["codes"], np.int8)
            if codes.size != total:
                raise WireError("int8_flat code block size mismatch")
            scales = np.asarray(body["scales"], np.float32)
            if scales.size != len(sizes):
                raise WireError("int8_flat scale table size mismatch")
            off = 0
            for n, s in zip(sizes, scales):
                out[off : off + n] = dequant_int8(
                    codes[off : off + n], float(s), n
                )
                off += n
        return dict(body.get("extra", {}))
    # Per-leaf record kinds (topk | int8): one entry per leaf, scattered
    # into the leaf's slice of the row.
    if len(body["leaves"]) != len(sizes):
        raise WireError(
            f"sparse payload has {len(body['leaves'])} leaves, layout has "
            f"{len(sizes)}"
        )
    off = 0
    for i, n in enumerate(sizes):
        e = body["leaves"][str(i)]
        if int(e["size"]) != n:
            raise WireError("sparse leaf size mismatch with layout")
        if kind == "topk":
            idx = np.ascontiguousarray(e["idx"], np.int32)
            if idx.size and (idx.min() < 0 or idx.max() >= n):
                raise WireError("sparse index out of range")
            out[off : off + n] = 0.0
            out[off + idx] = np.asarray(e["vals"], np.float32)
        elif kind == "int8":
            out[off : off + n] = dequant_int8(e["codes"], float(e["scale"]), n)
        else:
            raise WireError(f"unknown sparse kind {kind!r}")
        off += n
    return dict(body.get("extra", {}))


def decode(data: bytes, like: Pytree) -> Tuple[Pytree, dict]:
    """Reconstruct a dense delta pytree shaped like ``like``; returns
    (deltas, extra)."""
    body = serialization.msgpack_restore(_unframe(data))
    leaves, treedef = jax.tree_util.tree_flatten(like)
    if body.get("kind") in ("topk_flat", "int8_flat", "partial_flat"):
        return (
            _decode_flat(body, leaves, treedef),
            dict(body.get("extra", {})),
        )
    if len(body["leaves"]) != len(leaves):
        raise WireError(
            f"sparse payload has {len(body['leaves'])} leaves, template has "
            f"{len(leaves)}"
        )
    enc = [body["leaves"][str(i)] for i in range(len(leaves))]
    out = []
    for leaf, e in zip(leaves, enc):
        n = int(e["size"])
        if n != np.size(leaf):
            raise WireError("sparse leaf size mismatch with template")
        if body["kind"] == "topk":
            idx = np.ascontiguousarray(e["idx"], np.int32)
            # Wire data is untrusted: the native scatter writes out[idx[i]]
            # unchecked, so out-of-range indices would be a heap write.
            if idx.size and (idx.min() < 0 or idx.max() >= n):
                raise WireError("sparse index out of range")
            dense = unpack_sparse(idx, e["vals"], n)
        elif body["kind"] == "int8":
            dense = dequant_int8(e["codes"], float(e["scale"]), n)
        else:
            raise WireError(f"unknown sparse kind {body['kind']!r}")
        out.append(dense.reshape(np.shape(leaf)).astype(np.asarray(leaf).dtype))
    return jax.tree_util.tree_unflatten(treedef, out), dict(body.get("extra", {}))
