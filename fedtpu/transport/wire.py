"""Model-payload wire format for the DCN/gRPC edge.

The reference ships models as ``base64(pickle(torch state_dict))`` inside a
proto *string* field (``src/client.py:19-23``, ``src/server.py:55-58``) — a
33% inflation before any compression, plus pickle's arbitrary-code-execution
surface. fedtpu's edge payload is a flax msgpack pytree (raw little-endian
array bytes, no base64, no pickle) with a small framed header:

    magic(4) | version(1) | flags(1) | crc32(4) | payload

``flags`` bit 0 marks zlib compression of the payload — the explicit,
measurable form of the reference's transport-gzip ``-c Y`` switch
(``src/server.py:104-107``). The CRC covers the (possibly compressed)
payload so corrupted replication streams fail loudly instead of averaging
garbage into the global model.

``flags`` bit 1 marks the payload KIND: set = backup-replica payload
(model + server-optimizer moments + round counter), clear = plain model
payload. The receiver selects its decode template from this flag instead of
guessing by trying templates and catching exceptions — a corrupted or
config-mismatched replica therefore fails loudly rather than silently
downgrading to "model-only, drop the moments".

Version history: v1 frames CRC the payload only, so a bit-flipped
``flags`` byte could silently re-kind (or un-zlib) an otherwise-valid
payload. v2 (current) extends the CRC over ``version | flags | payload``,
closing the header hole. Decoders accept BOTH: v1 frames produced by older
peers or read back from old checkpoints still decode (each version is
checked under its own CRC rule), so a mixed-version fleet interoperates.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any

import jax
import numpy as np
from flax import serialization

Pytree = Any

_MAGIC = b"FTP1"
_VERSION = 2
_FLAG_ZLIB = 1
_FLAG_REPLICA = 2
_HEADER = struct.Struct("<4sBBI")


class WireError(ValueError):
    """Malformed or corrupted payload."""


def _crc(version: int, flags: int, payload: bytes) -> int:
    """The frame checksum under each version's coverage rule: v1 covered
    the payload only; v2+ also covers the version and flags bytes, so a
    corrupted header fails the CRC instead of silently re-kinding the
    payload."""
    if version == 1:
        return zlib.crc32(payload) & 0xFFFFFFFF
    return zlib.crc32(payload, zlib.crc32(bytes((version, flags)))) & 0xFFFFFFFF


def frame(
    magic: bytes, payload: bytes, flags: int = 0, version: int = _VERSION
) -> bytes:
    """Frame ``payload`` under the shared fedtpu header layout
    ``magic(4) | version(1) | flags(1) | crc32(4)`` — ONE implementation for
    every wire format (dense ``FTP1`` here, sparse/flat ``FSP1`` in
    :mod:`fedtpu.transport.sparse`), so the header structs cannot drift.
    ``version=1`` emits a legacy frame (payload-only CRC) for compat
    testing; current frames are v2 (header+payload CRC)."""
    if not 1 <= version <= _VERSION:
        raise ValueError(f"unknown frame version {version}")
    return _HEADER.pack(magic, version, flags, _crc(version, flags, payload)) + payload


def unframe(
    magic: bytes, data: bytes, what: str = "wire", version: int = _VERSION
):
    """Validate + strip a :func:`frame` header; returns ``(flags, payload)``.
    ``version`` is the NEWEST version the caller understands — every frame
    version from 1 up to it decodes, each checked under its own CRC rule
    (old frames from pre-v2 peers/checkpoints stay readable). Raises
    :class:`WireError` on wrong magic, unknown version, or CRC mismatch."""
    if len(data) < _HEADER.size or data[:4] != magic:
        raise WireError(f"not a fedtpu {what} payload")
    _, ver, flags, crc = _HEADER.unpack_from(data)
    if not 1 <= ver <= version:
        raise WireError(f"unsupported {what} version {ver}")
    payload = data[_HEADER.size :]
    if _crc(ver, flags, payload) != crc:
        raise WireError(f"{what} payload CRC mismatch")
    return flags, payload


def encode(
    tree: Pytree, compress: bool = False, level: int = 6, kind: str = "model"
) -> bytes:
    """Serialize a pytree of arrays to framed bytes.

    ``kind`` is stamped into the frame flags (``"model"`` or ``"replica"``)
    so the receiver can pick the matching decode template explicitly.

    Device arrays are fetched to host first (one transfer per leaf); for the
    intra-pod path this function is never called — arrays stay in HBM.
    """
    if kind not in ("model", "replica"):
        raise ValueError(f"unknown payload kind {kind!r}")
    host = jax.tree.map(np.asarray, tree)
    payload = serialization.to_bytes(host)
    flags = _FLAG_REPLICA if kind == "replica" else 0
    if compress:
        payload = zlib.compress(payload, level)
        flags |= _FLAG_ZLIB
    return frame(_MAGIC, payload, flags)


def payload_kind(data: bytes) -> str:
    """``"model"`` or ``"replica"`` from the frame flags (header-validated)."""
    if len(data) < _HEADER.size or data[:4] != _MAGIC:
        raise WireError("not a fedtpu wire payload")
    _, version, flags, _ = _HEADER.unpack_from(data)
    if not 1 <= version <= _VERSION:
        raise WireError(f"unsupported wire version {version}")
    return "replica" if flags & _FLAG_REPLICA else "model"


def decode(data: bytes, like: Pytree) -> Pytree:
    """Inverse of :func:`encode`. ``like`` supplies the pytree structure and
    leaf dtypes (flax msgpack restores *into* a template)."""
    flags, payload = unframe(_MAGIC, data)
    if flags & _FLAG_ZLIB:
        payload = zlib.decompress(payload)
    return serialization.from_bytes(like, payload)


def decode_raw(data: bytes) -> Pytree:
    """Decode a framed payload WITHOUT a template: raw msgpack restore to
    nested dicts of numpy arrays. For tools that inspect a payload whose
    config they do not hold — e.g. fingerprinting the final checkpoint of
    a disaster drill against its control run (``tools/chaos_soak.py
    --disaster``). Framing (magic, version, CRC) is still validated."""
    flags, payload = unframe(_MAGIC, data)
    if flags & _FLAG_ZLIB:
        payload = zlib.decompress(payload)
    return serialization.msgpack_restore(payload)


def decode_into_row(
    data: bytes, like: Pytree, base: Pytree, out: "np.ndarray"
) -> dict:
    """Decode a dense model payload and write its DELTA against ``base``
    leaf-by-leaf into the preallocated f32 row ``out``.

    The streaming server pipeline's dense fallback (unsynced clients and
    ``compression='none'`` fleets ship full weights): the payload still
    decodes through the msgpack template (flax restores *into* a
    structure), but the per-leaf subtraction lands straight in the row's
    leaf slices — no intermediate delta pytree, no per-leaf stacking later.
    ``base`` is the host copy of the round's global model with the same
    ``{"params", "batch_stats"}`` structure; leaf order (and therefore the
    row coordinate order) is the shared ``jax.tree_util.tree_flatten``
    order both ends derive from the model definition. Returns the payload's
    non-model fields (e.g. ``num_examples``).
    """
    tree = decode(data, like)
    packed = {k: tree[k] for k in ("params", "batch_stats")}
    base_leaves = jax.tree_util.tree_leaves(base)
    leaves = jax.tree_util.tree_leaves(packed)
    if len(leaves) != len(base_leaves):
        raise WireError(
            f"payload has {len(leaves)} model leaves, base has "
            f"{len(base_leaves)}"
        )
    off = 0
    for leaf, b in zip(leaves, base_leaves):
        n = int(np.size(b))
        if int(np.size(leaf)) != n:
            raise WireError("dense leaf size mismatch with base model")
        out[off : off + n] = (
            np.asarray(leaf, np.float32).ravel()
            - np.asarray(b, np.float32).ravel()
        )
        off += n
    return {k: v for k, v in tree.items() if k not in ("params", "batch_stats")}


def payload_size(tree: Pytree) -> int:
    """Uncompressed wire size in bytes (sans header) — the number the
    reference inflates by 4/3 with base64 (``src/client.py:21``)."""
    host = jax.tree.map(np.asarray, tree)
    return len(serialization.to_bytes(host))
