"""Retry/backoff execution of federation RPCs under a ``RetryPolicy``.

One helper, :func:`call_with_retry`, wraps every RPC the coordinator
issues (StartTrain fan-out, SendModel broadcast/initial sync/resync,
backup replication, FT probes, async workers). The unit of retry is the
caller's whole *attempt* closure — RPC **plus** reply decode — so a reply
whose payload fails the wire CRC (:class:`fedtpu.transport.wire.WireError`,
a corrupted record in flight) is rejected and re-requested exactly like a
transient status code, instead of silently losing the client's round.

Classification is data-driven from ``RetryPolicy.transient_codes``
(status-code *names*, so the policy stays a hashable config value):
transient codes retry with exponential backoff + jitter and count into
``fedtpu_rpc_retries_total{rpc}``; fatal codes (UNIMPLEMENTED,
INVALID_ARGUMENT, ...) and exhausted budgets re-raise to the caller's
existing failure path — only THOSE ever reach ``mark_failed``.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, TypeVar

import grpc

from fedtpu.config import RetryPolicy
from fedtpu.transport.wire import WireError

log = logging.getLogger("fedtpu.retry")

T = TypeVar("T")


def status_name(exc: grpc.RpcError) -> str:
    """The status-code NAME of an RpcError (``"UNKNOWN"`` when the error
    carries no code — e.g. a channel torn down mid-call)."""
    try:
        code = exc.code()
    except Exception:
        code = None
    return code.name if code is not None else "UNKNOWN"


def is_stale_coordinator(exc: BaseException) -> bool:
    """Is this a typed STALE_COORDINATOR fence rejection? Receivers abort
    with FAILED_PRECONDITION and a details string starting with the token,
    so a fenced sender can distinguish "I have been superseded" (self-demote
    and re-base) from an ordinary fatal RPC error (mark the peer failed).
    FAILED_PRECONDITION is deliberately NOT in ``transient_codes`` — a
    fence rejection must never be retried."""
    if not isinstance(exc, grpc.RpcError):
        return False
    try:
        code = exc.code()
        details = exc.details() or ""
    except Exception:
        return False
    return (code == grpc.StatusCode.FAILED_PRECONDITION
            and "STALE_COORDINATOR" in details)


def is_transient(exc: BaseException, policy: RetryPolicy) -> bool:
    """Retryable under ``policy``? Wire corruption is always transient
    (reject-and-retry: the bytes were damaged in flight, the peer is
    healthy); RpcErrors classify by status-code name; anything else —
    a programming error — is never retried."""
    if isinstance(exc, WireError):
        return True
    if isinstance(exc, grpc.RpcError):
        return status_name(exc) in policy.transient_codes
    return False


def backoff_s(policy: RetryPolicy, attempt: int,
              rand: Callable[[], float] = random.random) -> float:
    """Sleep before attempt ``attempt + 1`` (attempt is 1-based): exponential
    from ``backoff_s``, capped at ``backoff_max_s``, with up to ``jitter``
    fractional randomization on top."""
    base = min(
        policy.backoff_s * policy.backoff_multiplier ** (attempt - 1),
        policy.backoff_max_s,
    )
    return base * (1.0 + policy.jitter * rand())


def call_with_retry(
    policy: RetryPolicy,
    rpc: str,
    attempt_fn: Callable[[], T],
    peer: str = "",
    telemetry: Optional[object] = None,
    sleep: Callable[[float], None] = time.sleep,
    rand: Optional[Callable[[], float]] = None,
) -> T:
    """Run ``attempt_fn`` (one full RPC attempt, including reply decode) up
    to ``policy.max_attempts`` times. Transient failures back off and
    retry, incrementing ``fedtpu_rpc_retries_total{rpc}`` on ``telemetry``
    (a :class:`fedtpu.obs.Telemetry`, or None); the final (or first fatal)
    exception propagates unchanged so callers keep their existing
    ``except grpc.RpcError`` / ``except WireError`` handling. ``rand``
    (a 0..1 draw, e.g. a seeded ``random.Random(...).random``) replaces
    the global jitter source so chaos-soak timing replays
    deterministically; None keeps the module default."""
    attempts = max(1, policy.max_attempts)
    for attempt in range(1, attempts + 1):
        try:
            return attempt_fn()
        except Exception as exc:
            if attempt >= attempts or not is_transient(exc, policy):
                raise
            if telemetry is not None:
                telemetry.counter(
                    "fedtpu_rpc_retries_total",
                    "transient RPC failures retried, by rpc",
                    labels={"rpc": rpc},
                ).inc()
            delay = backoff_s(policy, attempt, rand or random.random)
            why = (
                status_name(exc)
                if isinstance(exc, grpc.RpcError)
                else f"corrupt payload ({exc})"
            )
            log.warning(
                "transient %s%s failed (%s), attempt %d/%d; retrying in %.2fs",
                rpc, f" to {peer}" if peer else "", why, attempt, attempts,
                delay,
            )
            sleep(delay)
    raise AssertionError("unreachable")  # loop always returns or raises
