"""Telemetry-driven adaptive codec selection for the distributed edge.

The codec frontier (docs/PERF_ANALYSIS.md §Communication-efficiency
frontier) is per-client: a client behind a fast local link is cheapest
uncompressed (no encode latency, no quantization noise), one behind a
congested WAN hop wants the smallest record that still converges. The
static ``FedConfig.compression`` knob picks ONE point for the whole
federation; :class:`AdaptiveCodecPolicy` instead picks per client per
round from the observed *cost* of each codec on that client's actual
link.

Cost model: ``bytes_up x RTT`` — the two measurements the server already
has for every StartTrain (the ``fedtpu_rpc_bytes_up_total`` counter input
and the ``fedtpu_client_rpc_seconds`` sample). Bytes alone would always
pick the smallest codec (ignoring that a fast link makes compression
pointless); RTT alone is noisy under scheduling jitter. Their product is
the bandwidth-delay-style figure the frontier trades on, smoothed per
(client, codec) with an EWMA.

Selection is deterministic given the observation history (no RNG): during
WARMUP each client cycles through the candidate list in order until every
codec has at least one observation; after that, argmin EWMA cost with
candidate order breaking ties. The choice ships to the client in
``TrainRequest.codec`` (additive proto field 5); a legacy client skips the
unknown field and keeps its static codec — the policy then simply keeps
observing whatever codec the replies actually used.

Error-feedback safety across switches is the CLIENT's job (the
rescale-or-reset rule in ``fedtpu.transport.federation.ClientAgent``):
the dense model-space residual is codec-agnostic, so lossy->lossy
switches carry it unchanged, and a switch to 'none' flushes it into the
dense payload. The policy never needs to know.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

# Candidate order is also the warmup probe order and the tiebreak order:
# cheapest-to-encode first, so the first rounds of a federation pay the
# least encode latency while the policy is still blind.
DEFAULT_CANDIDATES: Tuple[str, ...] = ("none", "int8", "topk", "rotq", "randk")

# EWMA smoothing for the per-(client, codec) cost. 0.3 ~ a 3-round memory:
# fast enough to chase a link-quality change within a few rounds, slow
# enough that one stalled RPC doesn't exile a codec.
_ALPHA = 0.3


class AdaptiveCodecPolicy:
    """Per-client codec chooser over EWMA(bytes_up x RTT) observations.

    Thread-safe: ``observe`` runs on the server's collect workers while
    ``choose`` runs on the round thread.
    """

    def __init__(self, candidates: Sequence[str] = DEFAULT_CANDIDATES):
        if not candidates:
            raise ValueError("adaptive codec policy needs >= 1 candidate")
        self.candidates: Tuple[str, ...] = tuple(candidates)
        # rank -> codec -> (ewma_cost, observation_count)
        self._stats: Dict[int, Dict[str, Tuple[float, int]]] = {}
        self._lock = threading.Lock()

    def observe(
        self, rank: int, codec: str, bytes_up: int, rtt_s: float
    ) -> None:
        """Fold one completed StartTrain into the client's cost table.

        ``codec`` is the codec the reply ACTUALLY used (the decode-side
        ``_codec`` tag), not the one requested — a legacy client that
        ignored the request still teaches the policy about its static
        codec rather than poisoning another codec's estimate.
        """
        if codec not in self.candidates:
            return
        # Floor the RTT so a clock hiccup reporting ~0 cannot make a codec
        # look free; bytes_up >= header size keeps the product positive.
        cost = float(max(bytes_up, 1)) * max(float(rtt_s), 1e-4)
        with self._lock:
            per = self._stats.setdefault(rank, {})
            old, n = per.get(codec, (cost, 0))
            per[codec] = (old + _ALPHA * (cost - old), n + 1)

    def choose(self, rank: int) -> Optional[str]:
        """The codec this client should use next round, or the first
        unobserved candidate while warming up. Deterministic in the
        observation history."""
        with self._lock:
            per = self._stats.get(rank, {})
            for c in self.candidates:
                if per.get(c, (0.0, 0))[1] == 0:
                    return c
            return min(
                self.candidates, key=lambda c: (per[c][0], self.candidates.index(c))
            )

    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        """Cost table for /statusz: rank -> codec -> {cost, n, chosen}."""
        with self._lock:
            out: Dict[str, Dict[str, dict]] = {}
            for rank, per in sorted(self._stats.items()):
                out[str(rank)] = {
                    c: {"ewma_cost": cost, "observations": n}
                    for c, (cost, n) in sorted(per.items())
                }
            return out
