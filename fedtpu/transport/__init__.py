"""DCN-edge transport: the gRPC ``federated.Trainer`` surface + wire codec.

Intra-pod model exchange in fedtpu is XLA collectives over ICI
(:mod:`fedtpu.parallel`) — no host transport at all. This package is the
*edge*: a reference-compatible gRPC service (same RPCs, same method paths,
same field numbers as ``federated.proto``) for cross-pod/DCN federation and
interop, with raw-bytes payloads replacing the reference's base64 pickle
(``src/client.py:19-23``).
"""

from fedtpu.transport import proto, wire
from fedtpu.transport.service import (
    MAX_MESSAGE_BYTES,
    SERVICE_NAME,
    TrainerServicer,
    TrainerStub,
    add_trainer_servicer,
    announce_join,
    announce_leave,
    create_channel,
    create_server,
    probe,
)

__all__ = [
    "proto",
    "wire",
    "MAX_MESSAGE_BYTES",
    "SERVICE_NAME",
    "TrainerServicer",
    "TrainerStub",
    "add_trainer_servicer",
    "announce_join",
    "announce_leave",
    "create_channel",
    "create_server",
    "probe",
]
