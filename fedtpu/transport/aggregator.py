"""The third coordinator role: a mid-tier leaf aggregator.

One :class:`~fedtpu.transport.federation.PrimaryServer` terminates every
client RPC of a flat federation — so federation size is capped by one
process's NIC, decode pool and HBM. The hierarchical topology
(docs/ARCHITECTURE.md §Multi-tier) interposes a tier of
:class:`AggregatorServer` processes between the root and the clients:

- downstream, each aggregator owns a COHORT of ordinary client agents — it
  fans StartTrain out to them with the same retry/heartbeat/membership
  machinery the primary uses, stream-decodes their replies into a local
  flat ``[cohort, P]`` buffer through the UNCHANGED
  :func:`fedtpu.transport.sparse.decode_into_row` /
  :func:`fedtpu.transport.wire.decode_into_row` paths, and partially
  reduces the buffer to ONE pre-weighted sum row + weight sum
  (:func:`fedtpu.ops.flat.partial_reduce_rows`);
- upstream, it answers the root's ``SubmitPartial`` pull with that pair as
  a single FSP1 ``partial_flat`` record, so the ROOT's per-round work is
  O(aggregators), not O(clients) (measured: ``bench.py
  --fanin-microbench``, artifacts/FANIN_MICROBENCH.json).

Exactness: the partial is the UNNORMALIZED weighted sum — division happens
once, at the root (:func:`fedtpu.ops.flat.combine_partial_rows`) — so the
2-tier mean is bit-identical to the one-tier flat weighted mean whenever
the f32 adds are exact (the associativity contract
``tests/test_aggregator.py`` pins with dyadic-rational inputs).

Fault composition (docs/FAULT_TOLERANCE.md):

- *fencing*: the aggregator enforces the coordinator epoch on its parent
  face (max-epoch tracking, STALE_COORDINATOR rejection — same rule as
  ``ClientAgent``) and RELAYS the root's epoch downstream unchanged, so
  clients fence against the root, not against the middle tier. A cohort
  client that rejects the relayed epoch as stale proves the ROOT is
  superseded — the aggregator propagates the rejection upstream by
  aborting the SubmitPartial with the same typed status.
- *quorum*: ``FedConfig.round_quorum`` applies PER TIER — a sub-quorum
  cohort aborts the SubmitPartial (typed ``SUB_QUORUM`` status,
  FAILED_PRECONDITION so the root never burns retries on it), and the
  root masks that aggregator's row exactly like a failed client.
- *retries*: the leaf→client budget is this process's own RetryPolicy,
  independent of the root→aggregator budget.
- *tracing*: the root's propagated context is adopted and re-propagated,
  so one merged timeline spans root → aggregator → client
  (``tools/trace_merge.py --check``).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import grpc
import jax
import numpy as np

from fedtpu import models as model_zoo
from fedtpu.config import (
    RoundConfig,
    validate_retry_policy,
    validate_tier_config,
)
from fedtpu.ft import HeartbeatMonitor, MembershipTable
from fedtpu.obs import Telemetry, process_rss_bytes
from fedtpu.obs import propagate
from fedtpu.ops import flat as flat_ops
from fedtpu.transport import proto, sparse, wire
from fedtpu.transport.retry import call_with_retry, is_stale_coordinator
from fedtpu.transport.service import (
    TrainerServicer,
    TrainerStub,
    create_channel,
    create_server,
    probe,
    trace_context_of,
)

log = logging.getLogger("fedtpu.aggregator")

# A cohort source is the pluggable downstream of an aggregator: given
# (round, rank_base, world) it returns the round's encoded client reply
# payloads (FSP1/FTP1 bytes, exactly what StartTrain replies carry). The
# default source dials the real gRPC cohort; the fan-in bench plugs a
# SimFederation-backed source so 10k clients/round exercise the REAL
# decode → partial-reduce → SubmitPartial path with only the local
# training itself simulated.
CohortSource = Callable[[int, int, int], List[bytes]]


class AggregatorServer(TrainerServicer):
    """Mid-tier coordinator: StartTrain fan-out below, SubmitPartial above.

    ``clients`` is the cohort roster (addresses this process dials).
    ``cohort_source`` replaces the gRPC cohort entirely (see
    :data:`CohortSource`); ``template`` replaces the model-zoo build with
    an explicit ``{"params", "batch_stats"}`` host pytree — both are the
    bench/test seams and default to the real thing.
    """

    def __init__(
        self,
        cfg: RoundConfig,
        clients: Sequence[str] = (),
        parent: Optional[str] = None,
        compress: bool = False,
        chaos=None,
        cohort_source: Optional[CohortSource] = None,
        template: Optional[dict] = None,
        identity: str = "aggregator",
    ):
        validate_tier_config(cfg.fed, "AggregatorServer")
        self.cfg = cfg
        self.parent = parent
        self.identity = identity
        self.telemetry = Telemetry(cfg.fed.telemetry, role="aggregator")
        self.retry_policy = validate_retry_policy(cfg.fed.retry)
        rp = self.retry_policy
        self._deadlines = {
            "StartTrain": rp.start_train_timeout_s,
            "SendModel": rp.send_model_timeout_s,
        }
        self.chaos = chaos
        self._compress = compress
        if template is None:
            model = model_zoo.create(cfg.model, num_classes=cfg.num_classes)
            from fedtpu.transport.federation import _model_template

            params_t, stats_t = _model_template(model, cfg)
            template = {"params": params_t, "batch_stats": stats_t}
        # Host zero-template ({"params","batch_stats"}) — the decode
        # template for dense replies and the structure SendModel installs
        # into. The flat layout (and therefore P) derives from it, so root,
        # aggregator and clients agree on coordinates by construction.
        self._template = template
        self._flat_layout = flat_ops.make_layout(template)
        self._payload_template = dict(
            template, num_examples=np.zeros((), np.float32)
        )
        self._partial_reduce = jax.jit(flat_ops.partial_reduce_rows)
        # Current global model: raw broadcast bytes (relayed verbatim
        # downstream — no re-encode) + decoded host copy (the dense-decode
        # base). Unset until the root's first SendModel.
        self._global_bytes: Optional[bytes] = None
        self._global_host: Optional[dict] = None
        self._global_lock = threading.Lock()
        # Parent-face fencing: max coordinator epoch seen on ANY inbound
        # RPC (same rule as ClientAgent._fence_check).
        self._max_epoch = -1
        self._epoch_lock = threading.Lock()
        self._round_seen = -1
        self._last_partial: dict = {}
        self.cohort_source = cohort_source
        self.registry = MembershipTable(
            clients,
            metrics=self.telemetry.registry if self.telemetry.enabled
            else None,
        )
        self._member_lock = threading.Lock()
        self._stubs: Dict[str, TrainerStub] = {
            c: self._make_stub(c) for c in clients
        }
        self.monitor = HeartbeatMonitor(
            self.registry,
            probe=self._probe_member,
            resync=self._resync,
            period=cfg.fed.ft_heartbeat_period_s,
            metrics=self.telemetry.registry if self.telemetry.enabled
            else None,
            probe_deadline_s=rp.max_attempts
            * (rp.probe_timeout_s + rp.backoff_max_s) + 1.0,
        )
        self._server: Optional[grpc.Server] = None
        self._gate_stub: Optional[TrainerStub] = None

    # ------------------------------------------------------------ plumbing
    def _make_stub(self, client: str) -> TrainerStub:
        return TrainerStub(
            create_channel(
                client, compress=self._compress,
                trace_source=self._trace_source, chaos=self.chaos,
            )
        )

    def _stub(self, client: str) -> Optional[TrainerStub]:
        with self._member_lock:
            if client not in self._stubs and self.registry.is_member(client):
                self._stubs[client] = self._make_stub(client)
            return self._stubs.get(client)

    def _trace_source(self) -> Optional[propagate.TraceContext]:
        tracer = self.telemetry.tracer
        if tracer is None:
            return None
        return propagate.TraceContext(
            trace_id=tracer.trace_id,
            span_id=tracer.current_id() or 0,
            role=self.telemetry.role or "aggregator",
            round=self._round_seen,
        )

    def _probe_member(self, client: str) -> bool:
        stub = self._stub(client)
        if stub is None:
            return False
        return probe(
            stub, timeout=self.retry_policy.probe_timeout_s,
            policy=self.retry_policy, telemetry=self.telemetry,
        ) is not None

    def _resync(self, client: str) -> bool:
        """Re-deliver the current global to a revived cohort member (the
        resync-before-revive contract the heartbeat monitor enforces)."""
        with self._global_lock:
            payload = self._global_bytes
        if payload is None:
            return False  # nothing to resync yet; stay dead until synced
        stub = self._stub(client)
        if stub is None:
            return False
        try:
            call_with_retry(
                self.retry_policy, "SendModel",
                lambda: stub.SendModel(
                    proto.SendModelRequest(
                        model=payload, epoch=self._max_epoch,
                    ),
                    timeout=self._deadlines["SendModel"],
                ),
                peer=client, telemetry=self.telemetry,
            )
            return True
        except grpc.RpcError:
            return False

    def _fence_check(self, epoch: int, rpc: str, context) -> None:
        """Parent-face fencing (docs/FAULT_TOLERANCE.md §Fencing): track
        the max coordinator epoch; abort a stale sender. Aborting raises."""
        if epoch < 0:
            return
        with self._epoch_lock:
            if epoch >= self._max_epoch:
                self._max_epoch = epoch
                return
            newest = self._max_epoch
        log.warning(
            "%s from stale coordinator epoch %d rejected (newest seen %d)",
            rpc, epoch, newest,
        )
        self.telemetry.counter(
            "fedtpu_ft_stale_rejected_total",
            "coordinator RPCs rejected for a stale fencing epoch, by rpc",
            labels={"rpc": rpc},
        ).inc()
        context.abort(
            grpc.StatusCode.FAILED_PRECONDITION,
            f"STALE_COORDINATOR: epoch {epoch} < {newest}",
        )

    # ----------------------------------------------------- inbound surface
    def SendModel(
        self, request: proto.SendModelRequest, context
    ) -> proto.SendModelReply:
        """Install the root's global and relay it to the cohort. The relay
        re-ships the root's bytes verbatim (no re-encode) with the root's
        epoch, so downstream fencing is against the root's lineage."""
        self._fence_check(request.epoch, "SendModel", context)
        ctx = trace_context_of(context)
        propagate.adopt(self.telemetry.tracer, ctx)
        with self.telemetry.span("install_global",
                                 **propagate.span_args(ctx)):
            tree = wire.decode(request.model, self._template)
            with self._global_lock:
                self._global_bytes = request.model
                self._global_host = {
                    k: tree[k] for k in ("params", "batch_stats")
                }
        self.telemetry.counter(
            "fedtpu_rpc_bytes_down_total",
            "payload bytes shipped/received on the downstream face",
        ).inc(len(request.model))
        failed = self._relay_model(request.model, request.epoch)
        return proto.SendModelReply(
            reply=f"relayed:{self.cohort_size - failed}/"
                  f"{self.cohort_size}".encode()
        )

    def _relay_model(self, payload: bytes, epoch: int) -> int:
        """Best-effort downstream broadcast; returns the failure count.
        Failed members are marked for the heartbeat/resync machinery —
        exactly the primary's broadcast semantics, one tier down."""
        if self.cohort_source is not None:
            return 0  # simulated cohorts hold no installable state
        failures = [0]

        def send_one(client: str) -> None:
            stub = self._stub(client)
            if stub is None:
                return
            try:
                with self.telemetry.span("broadcast", client=client):
                    call_with_retry(
                        self.retry_policy, "SendModel",
                        lambda: stub.SendModel(
                            proto.SendModelRequest(model=payload, epoch=epoch),
                            timeout=self._deadlines["SendModel"],
                        ),
                        peer=client, telemetry=self.telemetry,
                    )
            except grpc.RpcError:
                failures[0] += 1
                self.telemetry.counter(
                    "fedtpu_rpc_failures_total", "RpcErrors by failing RPC",
                    labels={"rpc": "SendModel"},
                ).inc()
                self.registry.mark_failed(client)

        threads = [
            threading.Thread(target=send_one, args=(c,), daemon=True)
            for c in self.registry.active_clients()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return failures[0]

    def HeartBeat(self, request: proto.Request, context) -> proto.HeartBeatResponse:
        return proto.HeartBeatResponse(status=1)

    def SubmitPartial(
        self, request: proto.SubmitPartialRequest, context
    ) -> proto.SubmitPartialReply:
        """One pulled partial reduce: fan StartTrain out to the cohort,
        stream-decode replies into the local ``[cohort, P]`` buffer, fold
        it to one pre-weighted sum row, reply with the FSP1 record."""
        self._fence_check(request.epoch, "SubmitPartial", context)
        ctx = trace_context_of(context)
        propagate.adopt(self.telemetry.tracer, ctx)
        self._round_seen = request.round
        tel = self.telemetry
        t_start = time.monotonic()
        with tel.span("submit_partial", round=request.round,
                      rank_base=request.rank_base,
                      **propagate.span_args(ctx)) as pspan:
            reply = self._submit_partial_impl(request, context, pspan)
        tel.histogram(
            "fedtpu_round_phase_seconds",
            "per-round phase durations by phase",
            labels={"phase": "submit_partial"},
        ).observe(time.monotonic() - t_start)
        return reply

    def _submit_partial_impl(self, request, context, pspan):
        tel = self.telemetry
        layout = self._flat_layout
        cfg = self.cfg
        if self.cohort_source is not None:
            payloads = self.cohort_source(
                request.round, request.rank_base, request.world
            )
            launch = [f"sim:{i}" for i in range(len(payloads))]
            payload_of = dict(zip(launch, payloads))
            rank_of = {c: request.rank_base + i
                       for i, c in enumerate(launch)}
        else:
            with self._global_lock:
                synced = self._global_host is not None
            if not synced and cfg.fed.compression == "none":
                # Dense replies need the global as a delta base; without
                # one this tier cannot produce a partial. Typed + fatal:
                # the root masks the row and its resync path delivers the
                # model before the next pull.
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    "UNSYNCED_AGGREGATOR: no global model installed yet",
                )
            payload_of = None
            launch = self.registry.active_clients()
            seats = self.registry.seat_map()
            rank_of = {c: request.rank_base + seats[c] for c in launch}
        members_now = max(self.registry.size, 1)
        if not launch:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"SUB_QUORUM: 0/{members_now} cohort members alive",
            )

        rows = np.zeros((len(launch), layout.padded), np.float32)
        row_of = {c: i for i, c in enumerate(launch)}
        tel.gauge(
            "fedtpu_buffer_bytes",
            "host+device bytes of the round's flat delta buffers, by tier",
            labels={"tier": "leaf"},
        ).set(rows.nbytes)
        tel.gauge(
            "fedtpu_partial_rows_buffered",
            "cohort rows currently buffered toward this tier's partial "
            "reduce",
        ).set(len(launch))

        results: Dict[str, float] = {}
        stale: List[str] = []
        lock = threading.Lock()

        def decode_one(client: str, data: bytes) -> float:
            row = rows[row_of[client]]
            with tel.span("decode", client=client):
                if sparse.is_sparse_payload(data):
                    extra = sparse.decode_into_row(data, layout.sizes, row)
                else:
                    with self._global_lock:
                        base = self._global_host
                    extra = wire.decode_into_row(
                        data, self._payload_template, base, row
                    )
            tel.counter(
                "fedtpu_rpc_bytes_up_total",
                "payload bytes received on the upstream-bound face",
            ).inc(len(data))
            return float(extra["num_examples"])

        def train_one(client: str) -> None:
            def attempt() -> float:
                reply = self._stub(client).StartTrain(
                    proto.TrainRequest(
                        rank=rank_of[client], world=request.world,
                        round=request.round, epoch=request.epoch,
                    ),
                    timeout=self._deadlines["StartTrain"],
                )
                return decode_one(client, reply.message)

            try:
                with tel.span("client_rpc", parent=pspan.id, client=client):
                    n = call_with_retry(
                        self.retry_policy, "StartTrain", attempt,
                        peer=client, telemetry=tel,
                    )
                with lock:
                    results[client] = n
            except (grpc.RpcError, wire.WireError) as e:
                if is_stale_coordinator(e):
                    # A cohort client outranks our caller's epoch: the
                    # ROOT is superseded. Record for upstream propagation;
                    # never mark the client failed (it is the healthy one).
                    with lock:
                        stale.append(e.details() or "STALE_COORDINATOR")
                    return
                log.warning("cohort member %s failed StartTrain: %s",
                            client, e)
                tel.counter(
                    "fedtpu_rpc_failures_total", "RpcErrors by failing RPC",
                    labels={"rpc": "StartTrain"},
                ).inc()
                self.registry.mark_failed(client)

        t0 = time.monotonic()
        with tel.span("collect", parent=pspan.id, cohort=len(launch)):
            if payload_of is not None:
                # Simulated cohort: the payloads ARE the replies; decode
                # them through the identical streaming path.
                for client in launch:
                    try:
                        results[client] = decode_one(
                            client, payload_of[client]
                        )
                    except wire.WireError as e:
                        log.warning("sim payload for %s rejected: %s",
                                    client, e)
            else:
                threads = [
                    threading.Thread(target=train_one, args=(c,), daemon=True)
                    for c in launch
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        t_collect = time.monotonic() - t0
        tel.gauge("fedtpu_partial_rows_buffered", "").set(0)

        if stale:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, stale[0])
        quorum = cfg.fed.round_quorum
        needed = (
            max(1, int(np.ceil(quorum * members_now))) if quorum > 0 else 0
        )
        if len(results) < needed:
            tel.counter(
                "fedtpu_round_aborts_total",
                "rounds aborted below quorum, by surface",
                labels={"surface": "aggregator"},
            ).inc()
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"SUB_QUORUM: {len(results)}/{members_now} cohort replies "
                f"< quorum {quorum}",
            )
        if not results:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"SUB_QUORUM: 0/{members_now} cohort replies",
            )

        order = [c for c in launch if c in results]
        keep = rows[[row_of[c] for c in order]]
        weights = np.asarray(
            [results[c] for c in order] if cfg.fed.weighted
            else [1.0] * len(order),
            np.float32,
        )
        t1 = time.monotonic()
        with tel.span("partial_reduce", parent=pspan.id, rows=len(order)):
            sum_row, weight_sum = self._partial_reduce(keep, weights)
            sum_row = np.asarray(jax.block_until_ready(sum_row))
            weight_sum = float(weight_sum)
        tel.histogram(
            "fedtpu_round_phase_seconds", "",
            labels={"phase": "partial_reduce"},
        ).observe(time.monotonic() - t1)
        record = sparse.encode_partial_flat(
            sum_row[: layout.total], layout.sizes,
            extra={
                "weight_sum": np.float32(weight_sum),
                "clients": np.int64(len(order)),
                "t_leaf_s": np.float32(time.monotonic() - t0),
            },
        )
        self._last_partial = {
            "round": request.round,
            "clients": len(order),
            "cohort": len(launch),
            "weight_sum": weight_sum,
            "t_collect_s": t_collect,
            "buffer_bytes": int(rows.nbytes),
        }
        tel.counter("fedtpu_rounds_completed_total",
                    "partial reduces completed by this tier").inc()
        return proto.SubmitPartialReply(
            record=record, clients=len(order)
        )

    # ---------------------------------------------------------- lifecycle
    @property
    def cohort_size(self) -> int:
        return self.registry.size

    def status_snapshot(self) -> dict:
        """``/statusz`` feed for an aggregator process."""
        with self._global_lock:
            synced = self._global_host is not None
        return {
            "role": self.telemetry.role or "aggregator",
            "pid": os.getpid(),
            "tier": "leaf",
            "parent": self.parent,
            "round": self._round_seen,
            "synced": synced,
            "clients": {
                "active": len(self.registry.active_clients()),
                "dead": len(self.registry.dead_clients()),
                "total": self.registry.size,
            },
            "mem": {
                "rss_bytes": process_rss_bytes(),
                "buffer_bytes": int(
                    self._last_partial.get("buffer_bytes", 0)
                ),
                "partial_rows_buffered": (
                    int(
                        self.telemetry.registry.gauge(
                            "fedtpu_partial_rows_buffered", ""
                        ).value
                    )
                    if self.telemetry.enabled else 0
                ),
                "tier": "leaf",
            },
            "last_partial": dict(self._last_partial),
            "fencing": {"epoch_seen": self._max_epoch},
        }

    def start(self, address: str) -> grpc.Server:
        """Serve the upstream face on ``address`` and start cohort
        heartbeats; then announce this address to the parent's membership
        gate when ``parent`` is set (the aggregator IS a member of the
        root's roster — same join flow as an elastic client)."""
        self._server = create_server(
            address, self, compress=self._compress, chaos=self.chaos
        )
        self._server.start()
        if self.registry.size and self.cohort_source is None:
            self.monitor.start()
        if self.parent:
            from fedtpu.transport.service import announce_join

            self._gate_stub = announce_join(self.parent, address)
            if self._gate_stub is None:
                log.warning("parent gate %s never admitted us", self.parent)
        return self._server

    def stop(self, grace: float = 0.5) -> None:
        self.monitor.stop()
        if self._gate_stub is not None and self.identity:
            from fedtpu.transport.service import announce_leave

            announce_leave(self._gate_stub, self.identity)
        if self._server is not None:
            self._server.stop(grace)


def serve_aggregator(
    address: str,
    cfg: RoundConfig,
    clients: Sequence[str] = (),
    parent: Optional[str] = None,
    compress: bool = False,
    chaos=None,
    cohort_source: Optional[CohortSource] = None,
    template: Optional[dict] = None,
):
    """Build + start an aggregator on ``address``; returns
    (server, aggregator). The bind address doubles as the process's
    trace/flight identity, mirroring :func:`serve_client`."""
    agg = AggregatorServer(
        cfg, clients=clients, parent=parent, compress=compress, chaos=chaos,
        cohort_source=cohort_source, template=template, identity=address,
    )
    agg.telemetry.role = f"aggregator:{address}"
    server = agg.start(address)
    return server, agg
