"""Pallas TPU kernels for the update-compression hot path.

MEASURED VERDICT (round 4, real v5e chip — `artifacts/PALLAS_TPU_RUN.json`):
XLA's automatic fusion **matches or beats** both kernels at MobileNet scale
(`threshold_with_feedback`: Mosaic 0.155 ms vs XLA 0.101 ms;
`quantdequant_int8`: 71.8 vs 71.1 ms; outputs bitwise-equal both ways). The
kernels stay in the tree as the repo's documented Pallas on-ramp and as a
pinned-fusion fallback should a future surrounding program defeat XLA's
fusion heuristics — NOT as a performance claim. They are correct, tested,
and AOT-compile for v5e; the plain-XLA path is the default.

The compression pipeline (threshold mask, residual split, quantize — see
:mod:`fedtpu.ops.compression`) is a chain of elementwise ops over every
parameter of every client: at 64 clients x ~3.2M params (MobileNet, reference
``src/models/mobilenet.py``) that is ~800 MB of traffic per round if each op
round-trips HBM. XLA fuses most of the chain already; the Pallas kernels below
pin the fusion explicitly — one read of the combined delta+residual, one write
of (compressed, new_residual) — so the compression path stays
bandwidth-minimal regardless of what the surrounding program does to XLA's
fusion decisions.

Tiling obeys Mosaic's (8, 128) f32 tile rule: blocks are 8 client rows by a
lane-aligned column slice (~1 MB per operand per grid step — small enough
that the 4 double-buffered operands of the threshold kernel stay inside the
16 MB VMEM scoped limit, verified by deviceless AOT compilation for a v5e
target via ``tools/compile_pallas_tpu.py``). Per-row scalars (thresholds /
scales) ride as a ``[rows, 1]`` column so their block shape satisfies the
same rule.

Mode selection: on TPU the kernels lower through Mosaic. Off-TPU the DEFAULT
is a plain-jnp equivalent (XLA fuses the same chain; Pallas interpret mode
costs ~1000x on CPU and is pure overhead in production paths like the
cpu-scale parity bench). Pass ``interpret=True`` to force the interpreted
``pallas_call`` — the CPU test suite does this to exercise the actual kernel
bodies — or ``interpret=False`` to force Mosaic (the deviceless AOT compile
check, ``tools/compile_pallas_tpu.py``).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Max column-block size in elements: 32K f32 x 8 rows = 1 MB per operand per
# grid step — large enough that grid dispatch is negligible, small enough
# that the operands of a step (double-buffered) stay well inside VMEM.
_BLOCK_COLS = 32 * 1024
_BLOCK_ROWS = 8
assert _BLOCK_COLS % 128 == 0, "column blocks must stay lane-aligned"


# Process-wide default for the mode decision, settable because "what
# platform will this trace target?" is not knowable from inside a kernel
# wrapper during deviceless AOT lowering (default_backend() is cpu even when
# compiling FOR a TPU topology). Set BEFORE the first traced call — the
# wrappers are jitted and cache their trace.
_INTERPRET_DEFAULT: Optional[bool] = None


def set_interpret_default(value: Optional[bool]) -> None:
    global _INTERPRET_DEFAULT
    _INTERPRET_DEFAULT = value


def _mode(override: Optional[bool]) -> str:
    """'mosaic' (pallas, compiled) | 'interpret' (pallas, interpreted) |
    'xla' (plain-jnp equivalent, off-TPU default)."""
    if override is True:
        return "interpret"
    if override is False:
        return "mosaic"
    if _INTERPRET_DEFAULT is True:
        return "interpret"
    if _INTERPRET_DEFAULT is False:
        return "mosaic"
    return "mosaic" if jax.default_backend() == "tpu" else "xla"


def _blocks(rows: int, cols: int):
    """Mosaic-legal (row_block, col_block): rows tiled by 8 (or the full dim
    when smaller), columns tiled by the (lane-aligned) ``_BLOCK_COLS`` unless
    the block spans the whole dimension."""
    rb = rows if rows <= _BLOCK_ROWS else _BLOCK_ROWS
    cb = cols if cols <= _BLOCK_COLS else _BLOCK_COLS
    return rb, cb


def _threshold_kernel(y_ref, t_ref, out_ref, new_e_ref):
    """One tile of fused magnitude threshold + residual split.

    keep = |y| >= t (per-client threshold); out = y * keep; new_e = y - out.
    The caller precomputes y = delta + residual (it needs y anyway for the
    top-k threshold), so the kernel reads ONE full-size operand.
    """
    y = y_ref[...]
    keep = jnp.abs(y) >= t_ref[...]  # [rows, 1] broadcasts over [rows, cols]
    out = jnp.where(keep, y, jnp.zeros_like(y))
    out_ref[...] = out
    new_e_ref[...] = y - out


@functools.partial(jax.jit, static_argnames=("interpret",))
def threshold_with_feedback(
    y: jnp.ndarray, thresh: jnp.ndarray, interpret: Optional[bool] = None
):
    """Fused ``out = y * (|y| >= thresh); new_e = y - out``.

    ``y: [rows, cols]`` (rows = clients, cols = leaf size; the caller's
    delta + residual), ``thresh: [rows]`` per-row magnitude threshold.
    Returns ``(out, new_e)``.
    """
    rows, cols = y.shape
    mode = _mode(interpret)
    if mode == "xla":
        out = jnp.where(jnp.abs(y) >= thresh[:, None], y, jnp.zeros_like(y))
        return out, y - out
    rb, cb = _blocks(rows, cols)
    grid = (pl.cdiv(rows, rb), pl.cdiv(cols, cb))
    return pl.pallas_call(
        _threshold_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, cb), lambda r, c: (r, c)),
            pl.BlockSpec((rb, 1), lambda r, c: (r, 0)),
        ],
        out_specs=[
            pl.BlockSpec((rb, cb), lambda r, c: (r, c)),
            pl.BlockSpec((rb, cb), lambda r, c: (r, c)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(y.shape, y.dtype),
            jax.ShapeDtypeStruct(y.shape, y.dtype),
        ],
        interpret=mode == "interpret",
    )(y, thresh.reshape(rows, 1))


def _fwht_body(x: jnp.ndarray) -> jnp.ndarray:
    """Unnormalized fast Walsh–Hadamard transform over the last axis.

    Iterative stride-doubling butterfly: at step ``s`` the row is viewed as
    ``[pairs, 2, s]`` blocks and each (a, b) pair maps to (a+b, a-b) —
    log2(h) passes, each a reshape plus one add/sub, which XLA fuses into a
    handful of elementwise programs. ``h`` must be a power of two (the
    ``pow2=True`` flat layout guarantees it). H is symmetric and
    ``H @ H == h * I``, so the same body normalized by ``1/sqrt(h)`` is its
    own inverse — the property the rotq codec's decode side relies on.
    """
    rows, h = x.shape
    step = 1
    while step < h:
        x = x.reshape(rows, h // (2 * step), 2, step)
        a = x[:, :, 0, :]
        b = x[:, :, 1, :]
        x = jnp.stack([a + b, a - b], axis=2).reshape(rows, h)
        step *= 2
    return x


def _hadamard_kernel(x_ref, out_ref):
    """One row-block of the full-width FWHT butterfly.

    Unlike the elementwise kernels above, the transform MIXES every column
    of a row, so the grid tiles rows only and each step reads the whole
    ``[rb, h]`` row block — which bounds the Mosaic-compilable ``h`` by
    VMEM (~16 MB / (2 operands x rb x 4 B) ≈ 256K f32 columns at rb=8).
    Beyond that the plain-XLA path below is the production default anyway
    (same measured-verdict story as the other kernels in this file).
    """
    out_ref[...] = _fwht_body(x_ref[...])


@functools.partial(jax.jit, static_argnames=("inverse", "interpret"))
def hadamard_rotate(
    y: jnp.ndarray,
    signs: jnp.ndarray,
    inverse: bool = False,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Seeded structured random rotation ``R = (1/sqrt(h)) * H * D``.

    ``y: [rows, h]`` with ``h`` a power of two; ``signs: [h]`` the
    Rademacher diagonal D. Forward: ``R y = fwht(y * signs) / sqrt(h)``;
    ``inverse=True`` computes ``R^-1 y = fwht(y) / sqrt(h) * signs``
    (exact, because ``fwht(fwht(x)) == h * x``). The rotq codec rotates on
    the client, quantizes, and inverse-rotates on the server — both ends
    regenerate ``signs`` from the shared record seed.

    Parity: the interpreted pallas_call body is pinned against this
    function's own plain-jnp (lax) branch by ``tests/test_compression.py``.
    """
    rows, h = y.shape
    if h & (h - 1):
        raise ValueError(f"hadamard_rotate needs a power-of-two width, got {h}")
    y = y.astype(jnp.float32)
    signs = signs.astype(jnp.float32)
    norm = jnp.float32(1.0 / math.sqrt(h))
    if not inverse:
        y = y * signs[None, :]
    mode = _mode(interpret)
    if mode == "xla":
        out = _fwht_body(y) * norm
    else:
        rb = rows if rows <= _BLOCK_ROWS else _BLOCK_ROWS
        out = pl.pallas_call(
            _hadamard_kernel,
            grid=(pl.cdiv(rows, rb),),
            in_specs=[pl.BlockSpec((rb, h), lambda r: (r, 0))],
            out_specs=pl.BlockSpec((rb, h), lambda r: (r, 0)),
            out_shape=jax.ShapeDtypeStruct(y.shape, jnp.float32),
            interpret=mode == "interpret",
        )(y) * norm
    if inverse:
        out = out * signs[None, :]
    return out


def _quantdequant_kernel(x_ref, s_ref, out_ref):
    """One tile of simulated int8 quantize-dequantize: round(x/s) * s."""
    s = s_ref[...]  # [rows, 1]
    # Guard the all-zero leaf: scale 0 would produce NaN via 0/0.
    safe = jnp.where(s > 0, s, jnp.ones_like(s))
    q = jnp.clip(jnp.round(x_ref[...] / safe), -127.0, 127.0)
    out_ref[...] = q * safe


@functools.partial(jax.jit, static_argnames=("interpret",))
def quantdequant_int8(
    x: jnp.ndarray, scale: jnp.ndarray, interpret: Optional[bool] = None
) -> jnp.ndarray:
    """Simulated symmetric int8 codec: ``clip(round(x/scale), ±127) * scale``.

    ``x: [rows, cols]``, ``scale: [rows]`` (per-client max|x|/127). The wire
    format for the DCN edge transmits the int8 codes + one f32 scale per leaf
    (``fedtpu.transport.sparse.encode_int8``); on-device FedAvg uses this fused
    quantize-dequantize so aggregation sees exactly the wire numbers.
    """
    rows, cols = x.shape
    mode = _mode(interpret)
    if mode == "xla":
        s = scale[:, None]
        safe = jnp.where(s > 0, s, jnp.ones_like(s))
        return jnp.clip(jnp.round(x / safe), -127.0, 127.0) * safe
    rb, cb = _blocks(rows, cols)
    grid = (pl.cdiv(rows, rb), pl.cdiv(cols, cb))
    return pl.pallas_call(
        _quantdequant_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((rb, cb), lambda r, c: (r, c)),
            pl.BlockSpec((rb, 1), lambda r, c: (r, 0)),
        ],
        out_specs=pl.BlockSpec((rb, cb), lambda r, c: (r, c)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=mode == "interpret",
    )(x, scale.reshape(rows, 1))
