"""Pallas TPU kernels for the update-compression hot path.

The compression pipeline (threshold mask, residual split, quantize — see
:mod:`fedtpu.ops.compression`) is a chain of elementwise ops over every
parameter of every client: at 64 clients x ~3.2M params (MobileNet, reference
``src/models/mobilenet.py``) that is ~800 MB of traffic per round if each op
round-trips HBM. XLA fuses most of the chain already; the Pallas kernels below
pin the fusion explicitly — one read of the combined delta+residual, one write
of (compressed, new_residual) — so the compression path stays
bandwidth-minimal regardless of what the surrounding program does to XLA's
fusion decisions.

Kernels run in interpret mode off-TPU so the same code path is exercised by
the CPU test suite (see ``tests/conftest.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Column-block size in elements: 256K f32 = 1 MB per operand per grid step —
# large enough that grid dispatch is negligible, small enough that the 4-5
# operands of a step stay well inside the ~16 MB of VMEM.
_BLOCK = 256 * 1024


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _threshold_kernel(y_ref, t_ref, out_ref, new_e_ref):
    """One tile of fused magnitude threshold + residual split.

    keep = |y| >= t (per-client threshold); out = y * keep; new_e = y - out.
    The caller precomputes y = delta + residual (it needs y anyway for the
    top-k threshold), so the kernel reads ONE full-size operand.
    """
    y = y_ref[...]
    keep = jnp.abs(y) >= t_ref[0]
    out = jnp.where(keep, y, jnp.zeros_like(y))
    out_ref[...] = out
    new_e_ref[...] = y - out


@functools.partial(jax.jit, static_argnames=())
def threshold_with_feedback(y: jnp.ndarray, thresh: jnp.ndarray):
    """Fused ``out = y * (|y| >= thresh); new_e = y - out``.

    ``y: [rows, cols]`` (rows = clients, cols = leaf size; the caller's
    delta + residual), ``thresh: [rows]`` per-row magnitude threshold.
    Returns ``(out, new_e)``.
    """
    rows, cols = y.shape
    col_block = min(cols, _BLOCK)
    # Grid: one client row per step, columns tiled in ~1 MB blocks.
    grid = (rows, pl.cdiv(cols, col_block))
    return pl.pallas_call(
        _threshold_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, col_block), lambda r, c: (r, c)),
            pl.BlockSpec((1,), lambda r, c: (r,)),
        ],
        out_specs=[
            pl.BlockSpec((1, col_block), lambda r, c: (r, c)),
            pl.BlockSpec((1, col_block), lambda r, c: (r, c)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(y.shape, y.dtype),
            jax.ShapeDtypeStruct(y.shape, y.dtype),
        ],
        interpret=_interpret(),
    )(y, thresh)


def _quantdequant_kernel(x_ref, s_ref, out_ref):
    """One tile of simulated int8 quantize-dequantize: round(x/s) * s."""
    s = s_ref[0]
    # Guard the all-zero leaf: scale 0 would produce NaN via 0/0.
    safe = jnp.where(s > 0, s, jnp.ones_like(s))
    q = jnp.clip(jnp.round(x_ref[...] / safe), -127.0, 127.0)
    out_ref[...] = q * safe


@functools.partial(jax.jit, static_argnames=())
def quantdequant_int8(x: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Simulated symmetric int8 codec: ``clip(round(x/scale), ±127) * scale``.

    ``x: [rows, cols]``, ``scale: [rows]`` (per-client max|x|/127). The wire
    format for the DCN edge transmits the int8 codes + one f32 scale per leaf
    (:mod:`fedtpu.transport.codec`); on-device FedAvg uses this fused
    quantize-dequantize so aggregation sees exactly the wire numbers.
    """
    rows, cols = x.shape
    col_block = min(cols, _BLOCK)
    grid = (rows, pl.cdiv(cols, col_block))
    return pl.pallas_call(
        _quantdequant_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, col_block), lambda r, c: (r, c)),
            pl.BlockSpec((1,), lambda r, c: (r,)),
        ],
        out_specs=pl.BlockSpec((1, col_block), lambda r, c: (r, c)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x, scale)
