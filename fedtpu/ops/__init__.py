"""On-device ops: update compression codecs + the Pallas kernels behind them.

The TPU-native replacement for the reference's transport-level gzip
(``-c Y``, reference ``src/server.py:104-107``): deltas are sparsified or
quantized on-device before aggregation (see :mod:`fedtpu.ops.compression`).
"""

from fedtpu.ops.compression import (
    Compressor,
    make_compressor,
    make_int8,
    make_topk,
    nnz_fraction,
)
from fedtpu.ops.flat import FlatLayout, make_layout, pack_stacked, unpack_stacked
from fedtpu.ops.losses import softmax_ce_int_labels

__all__ = [
    "Compressor",
    "FlatLayout",
    "make_compressor",
    "make_int8",
    "make_layout",
    "make_topk",
    "nnz_fraction",
    "pack_stacked",
    "softmax_ce_int_labels",
    "unpack_stacked",
]
