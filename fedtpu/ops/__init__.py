"""On-device ops: update compression codecs + the Pallas kernels behind them.

The TPU-native replacement for the reference's transport-level gzip
(``-c Y``, reference ``src/server.py:104-107``): deltas are sparsified or
quantized on-device before aggregation (see :mod:`fedtpu.ops.compression`).
"""

from fedtpu.ops.compression import (
    Compressor,
    make_compressor,
    make_int8,
    make_topk,
    nnz_fraction,
)
from fedtpu.ops.losses import softmax_ce_int_labels

__all__ = [
    "Compressor",
    "make_compressor",
    "make_int8",
    "make_topk",
    "nnz_fraction",
    "softmax_ce_int_labels",
]
