"""Flat-buffer delta layout: every parameter leaf in ONE contiguous row.

The per-leaf delta pipeline (:mod:`fedtpu.ops.compression`) dispatches each
codec stage once per pytree leaf; on the zoo's deep architectures (DenseNet,
DPN, RegNet — hundreds of leaves) that is hundreds of tiny ``top_k`` /
elementwise / reduce ops per round. Communication-efficiency practice
(Konečný et al., arXiv:1610.05492; FedJAX, arXiv:2108.02117) treats the
client update as one flat vector instead. This module is the packer for that
layout: all leaves flattened into one lane-aligned ``[clients, P]`` buffer
with a static offsets table, so compression, error feedback, DP clipping and
the FedAvg reduction each run as ONE op over the whole model.

Offsets-table format (static, derived from the params template at trace
time — never serialized with the data, both ends of a wire recompute it
from the shared model definition):

- leaves are enumerated in ``jax.tree_util.tree_flatten`` order;
- ``offsets[i]`` is leaf ``i``'s start in the flat row, ``sizes[i]`` its
  scalar count (``offsets[i+1] == offsets[i] + sizes[i]``);
- ``total = sum(sizes)``; the row is padded with zeros to
  ``padded = ceil(total / 128) * 128`` (TPU lane alignment, ``LANE``), so
  the buffer tiles exactly under Mosaic's ``(8, 128)`` f32 rule and the
  fused kernels in :mod:`fedtpu.ops.pallas_kernels` apply unchanged.

Padding rule: the pad region is ALWAYS zero on entry to every op here, and
every op here preserves that (thresholding keeps zeros at zero, quantization
maps 0 -> 0, residuals of zeros are zero), so padding never leaks into
codec statistics or aggregates and is simply dropped by :func:`unpack`.

Dtype invariant: the packed buffer is ALWAYS float32 — the concat
primitives (:func:`fedtpu.utils.trees.tree_concat_rows` /
``tree_concat_flat``) cast every leaf on entry, and :func:`unpack` /
:func:`unpack_stacked` restore original leaf dtypes from the layout table.
Under ``compute_dtype=bfloat16_mixed`` deltas are taken against the f32
master params, so aggregation, FedOpt, screening statistics and checkpoint
wire bytes are bit-identical in layout to a pure-f32 run (pinned by
``tests/test_mixed_precision.py``).
"""

from __future__ import annotations

import math
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.utils import trees

Pytree = Any

# TPU vector-lane width; rows padded to a multiple of this tile exactly.
LANE = 128


class FlatLayout(NamedTuple):
    """Static description of how a params pytree maps into one flat row.

    Hashable/static (shapes and offsets are plain ints), so it can be closed
    over by jitted round steps; only the packed buffer itself is traced.
    """

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    dtypes: Tuple[Any, ...]
    offsets: Tuple[int, ...]
    sizes: Tuple[int, ...]
    total: int  # real scalar count (sum of sizes)
    padded: int  # lane-aligned row length P >= total

    @property
    def num_leaves(self) -> int:
        return len(self.sizes)

    @property
    def pad(self) -> int:
        return self.padded - self.total


def next_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    return 1 << max(n - 1, 0).bit_length()


def _padded(total: int, lane: int, pow2: bool = False) -> int:
    lane_padded = max(lane, int(math.ceil(max(total, 1) / lane)) * lane)
    if not pow2:
        return lane_padded
    # Power-of-two padding (rotated-sketch codecs): the Hadamard butterfly
    # needs the row length to be 2^m. Every pow2 >= LANE is lane-aligned,
    # so the Mosaic tiling rule still holds.
    return next_pow2(lane_padded)


def make_layout(
    template: Pytree, lane: int = LANE, pow2: bool = False
) -> FlatLayout:
    """Layout from a (single, unstacked) params-shaped pytree. Works on
    concrete arrays and on ``jax.eval_shape`` results alike — only shapes
    and dtypes are read. ``pow2=True`` pads the row to the next power of
    two instead of the next lane multiple (still lane-aligned), which is
    what the rotated-sketch codecs need for the Hadamard transform."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    shapes = tuple(tuple(int(d) for d in np.shape(l)) for l in leaves)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    offsets = tuple(int(o) for o in np.cumsum((0,) + sizes)[:-1])
    total = int(sum(sizes))
    return FlatLayout(
        treedef=treedef,
        shapes=shapes,
        dtypes=tuple(jnp.dtype(l.dtype) for l in leaves),
        offsets=offsets,
        sizes=sizes,
        total=total,
        padded=_padded(total, lane, pow2),
    )


def make_layout_stacked(
    stacked: Pytree, lane: int = LANE, pow2: bool = False
) -> FlatLayout:
    """Layout from a ``[clients, ...]``-stacked delta pytree (the leading
    axis is dropped from every leaf shape)."""
    single = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(tuple(l.shape[1:]), l.dtype), stacked
    )
    return make_layout(single, lane, pow2)


def segment_ids(layout: FlatLayout) -> np.ndarray:
    """``[padded]`` int32 map coordinate -> leaf index; padding coordinates
    get the extra segment ``num_leaves``. Host-side/static — used to compute
    per-leaf statistics (e.g. int8 scales) on the flat buffer with ONE
    segment reduction instead of one reduction per leaf."""
    ids = np.full((layout.padded,), layout.num_leaves, np.int32)
    for i, (off, size) in enumerate(zip(layout.offsets, layout.sizes)):
        ids[off : off + size] = i
    return ids


# ------------------------------------------------------------------ packing
def pack_stacked(layout: FlatLayout, stacked: Pytree) -> jnp.ndarray:
    """``[clients, ...]`` pytree -> ``[clients, padded]`` f32 buffer.

    One reshape per leaf plus one concatenate — pure data movement that XLA
    folds into the surrounding program; all codec/aggregation math then runs
    on the single result buffer.
    """
    leaves = jax.tree_util.tree_leaves(stacked)
    if len(leaves) != layout.num_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves, layout expects {layout.num_leaves}"
        )
    flat = trees.tree_concat_rows(stacked)
    if layout.pad:
        flat = jnp.pad(flat, ((0, 0), (0, layout.pad)))
    return flat


def unpack_stacked(layout: FlatLayout, flat: jnp.ndarray) -> Pytree:
    """Inverse of :func:`pack_stacked`: ``[clients, padded]`` -> stacked
    pytree (original leaf dtypes restored, padding dropped)."""
    n = flat.shape[0]
    leaves = [
        flat[:, off : off + size].reshape((n,) + shape).astype(dt)
        for off, size, shape, dt in zip(
            layout.offsets, layout.sizes, layout.shapes, layout.dtypes
        )
    ]
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def pack(layout: FlatLayout, tree: Pytree) -> jnp.ndarray:
    """Single (unstacked) pytree -> ``[padded]`` f32 row."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != layout.num_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves, layout expects {layout.num_leaves}"
        )
    flat = trees.tree_concat_flat(tree)
    if layout.pad:
        flat = jnp.pad(flat, (0, layout.pad))
    return flat


def pack_row_host(
    layout: FlatLayout, tree: Pytree, out: Optional[np.ndarray] = None
) -> np.ndarray:
    """Host-side (numpy) twin of :func:`pack`: a single pytree into a
    ``[padded]`` f32 row, written into ``out`` when given (the streaming
    server's preallocated row buffer) so no intermediate concatenation is
    materialised. ``out[total:]`` is left untouched (callers keep the pad
    region zero)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if len(leaves) != layout.num_leaves:
        raise ValueError(
            f"tree has {len(leaves)} leaves, layout expects {layout.num_leaves}"
        )
    if out is None:
        out = np.zeros((layout.padded,), np.float32)
    for leaf, off, size in zip(leaves, layout.offsets, layout.sizes):
        out[off : off + size] = np.asarray(leaf, np.float32).ravel()
    return out


def unpack(layout: FlatLayout, flat: jnp.ndarray) -> Pytree:
    """``[padded]`` row -> pytree (original dtypes, padding dropped)."""
    leaves = [
        flat[off : off + size].reshape(shape).astype(dt)
        for off, size, shape, dt in zip(
            layout.offsets, layout.sizes, layout.shapes, layout.dtypes
        )
    ]
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


# ------------------------------------------------------------- flat codecs
def topk_threshold(y: jnp.ndarray, fraction: float, total: int) -> Optional[jnp.ndarray]:
    """Per-client GLOBAL keep threshold: k-th largest |y| across the whole
    flat row, with ``k = ceil(fraction * total)`` counted against the REAL
    (unpadded) coordinate count. Returns None when k covers everything
    (keep-all). ONE ``top_k`` per round — the per-leaf path issues one per
    leaf, and its per-leaf k quantises the budget leaf-by-leaf; the global
    threshold spends the same overall budget on the globally largest
    coordinates (the documented semantic difference between layouts)."""
    k = max(1, int(math.ceil(fraction * total)))
    if k >= total:
        return None
    return jax.lax.top_k(jnp.abs(y), k)[0][:, -1]


def screen_rows(
    rows: jnp.ndarray,
    alive: jnp.ndarray,
    norm_max: float = 0.0,
    zmax: float = 0.0,
    cos_min: float = -1.0,
):
    """Fused Byzantine screening over a ``[clients, P]`` flat delta buffer.

    One program computes three per-row statistics and folds them into a
    keep/reject verdict (the thresholds are STATIC — callers close over a
    :class:`fedtpu.config.ScreenConfig`):

    - ``norm``: the row's L2 norm (per-row — under the streaming server
      pipeline this is the statistic that folds on arrival, host-side, with
      zero extra device syncs; the fused verdict below recomputes it in the
      same f32 math post-barrier).
    - ``cos``: cosine of the row against the live cohort's ROBUST
      REFERENCE DIRECTION — the mean of the norm-normalized live rows.
      Each client contributes exactly one unit vector, so a boosted
      update cannot drag the reference (the bounded-influence property a
      coordinate-wise median direction would give), and for a pure
      sign-flip minority the resultant stays exactly on the honest
      direction; unlike the median it is one elementwise pass, not a
      [clients, P] sort (measured 280 ms -> ~4 ms per round at densenet
      width on CPU — the difference between failing and passing the <=1%
      microbench gate). A sign-flipped/contrarian update scores ~-1 while
      honest heterogeneous updates stay positive.
    - ``z``: modified z-score of the row norm against the live cohort's
      median/MAD (``0.6745 * (norm - median) / MAD``, Iglewicz-Hoaglin).
      Median/MAD, not mean/std: a 30% boosted-attacker cohort inflates the
      mean and std enough to hide itself from a classical z-score, but
      cannot move the median while the honest majority holds. The check is
      ONE-SIDED (``z <= zmax`` keeps): only an inflated norm can dominate
      a combine — an unusually small update has bounded influence, and a
      two-sided cut would reject honest low-data clients.

    ``alive`` selects the rows that form the reference statistics (median
    direction, median/MAD of norms) — already-quarantined or failed rows
    must not pollute the reference population — but every row receives a
    verdict against those references, so a quarantined client keeps
    generating evidence (and can redeem itself).

    Invariances (property-pinned in ``tests/test_properties.py``): the
    per-row stats are permutation-equivariant (reordering rows reorders
    verdicts identically — median/MAD/median-direction are order-free
    reductions), and ``cos``/``z`` are invariant under a common positive
    scaling of all rows, so the relative checks need no per-model
    calibration (only ``norm_max`` is absolute by design).

    Returns ``(keep, stats)``: ``keep`` bool ``[clients]`` (True = row may
    enter the combine; a disarmed threshold never rejects), ``stats`` a
    dict of the three f32 ``[clients]`` vectors for records/telemetry.
    """
    rows = rows.astype(jnp.float32)
    live = (alive.astype(jnp.float32) > 0)
    norms = jnp.sqrt(jnp.maximum(jnp.sum(rows * rows, axis=1), 0.0))
    eps = jnp.float32(1e-12)
    # Robust reference direction: resultant of the live UNIT rows (see
    # docstring — bounded per-client influence at elementwise cost),
    # evaluated LEAVE-ONE-OUT per row: a row's own unit vector must not
    # vouch for it (at small cohorts self-inclusion inflates an outlier's
    # cosine by ~1/n_live). The LOO terms are pure dot-product algebra —
    # no second pass over the buffer.
    unit = rows / (norms + eps)[:, None]
    live_f = live.astype(jnp.float32)
    ref = jnp.sum(unit * live_f[:, None], axis=0)
    ref_sq = jnp.maximum(jnp.sum(ref * ref), 0.0)
    d = rows @ ref                      # [n]  <row_i, ref>
    u = d / (norms + eps)               # [n]  <unit_i, ref>
    loo_dot = d - live_f * norms        # <row_i, ref - unit_i> for live i
    loo_sq = jnp.maximum(ref_sq - live_f * (2.0 * u - 1.0), 0.0)
    cos = loo_dot / (norms * jnp.sqrt(loo_sq) + eps)
    # Modified z-score of the norms against the live median/MAD.
    norm_med = jnp.nan_to_num(
        jnp.nanmedian(jnp.where(live, norms, jnp.nan)), nan=0.0
    )
    mad = jnp.nan_to_num(
        jnp.nanmedian(jnp.where(live, jnp.abs(norms - norm_med), jnp.nan)),
        nan=0.0,
    )
    # MAD floor at 5% of the median scale: near convergence honest norms
    # become nearly identical and a raw MAD collapses toward 0, amplifying
    # harmless jitter into "outliers" (observed: honest evictions in the
    # 100-round Byzantine soak). A deviation within a few percent of the
    # cohort's scale is never evidence — an attacker must inflate its norm
    # by a meaningful multiple, which stays hundreds of sigmas out under
    # the floor. Scale-invariance is preserved (the floor tracks the
    # median).
    mad = jnp.maximum(mad, 0.05 * norm_med)
    z = 0.6745 * (norms - norm_med) / (mad + eps)
    keep = jnp.ones(norms.shape, bool)
    if norm_max > 0:
        keep = keep & (norms <= norm_max)
    if zmax > 0:
        keep = keep & (z <= zmax)
    if cos_min > -1.0:
        keep = keep & (cos >= cos_min)
    # Degenerate cohorts keep everything the thresholds didn't reject: with
    # <= 2 live rows the median IS the row set and MAD is 0 — the z/cos
    # checks would reject arbitrarily. Statistics need a population.
    n_live = jnp.sum(live.astype(jnp.int32))
    keep = jnp.where(n_live >= 3, keep, norms <= norm_max if norm_max > 0
                     else jnp.ones_like(keep))
    return keep, {"norm": norms, "cos": cos, "z": z}


# -------------------------------------------------- hierarchical partial sums
def partial_reduce_rows(
    rows: jnp.ndarray, weights: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fold a ``[cohort, P]`` flat buffer into ONE pre-weighted sum row.

    The fan-in primitive of the hierarchical (multi-tier) topology: a leaf
    :class:`fedtpu.transport.aggregator.AggregatorServer` reduces its
    cohort's rows to ``(sum_i rows_i * w_i, sum_i w_i)`` and ships only
    that pair upstream.

    Exact-associativity contract (the property the 2-tier parity pins in
    ``tests/test_aggregator.py`` hold): the partial is the UNNORMALIZED
    weighted sum — division happens exactly once, at the root, in
    :func:`combine_partial_rows`. Addition is associative whenever the f32
    adds are exact, so any grouping of clients into tiers produces the
    bit-identical mean the one-tier :func:`flat_weighted_mean` computes
    (a mean-of-means scheme would round at every tier and cannot satisfy
    this). Padding rule: pad coordinates are zero on entry and a weighted
    sum of zeros is zero, so the partial row stays pad-clean.
    """
    w = weights.astype(rows.dtype).reshape((-1,) + (1,) * (rows.ndim - 1))
    return jnp.sum(rows * w, axis=0), jnp.sum(weights)


def combine_partial_rows(
    sum_rows: jnp.ndarray, weight_sums: jnp.ndarray
) -> jnp.ndarray:
    """Root-side combine of the ``[aggregators, P]`` partial-sum surface:
    ``sum(sum_rows) / max(sum(weight_sums), 1e-9)`` — the single division
    of the whole hierarchy (see :func:`partial_reduce_rows`). With one
    aggregator over the whole cohort this IS ``flat_weighted_mean``'s
    program (same sum order, same epsilon guard), which is what makes the
    single-tier degenerate case trivially bit-identical."""
    total = jnp.maximum(jnp.sum(weight_sums), 1e-9)
    return jnp.sum(sum_rows, axis=0) / total.astype(sum_rows.dtype)


def int8_scales(y: jnp.ndarray, layout: FlatLayout) -> jnp.ndarray:
    """Per-coordinate int8 scale vector reproducing the per-leaf codec
    EXACTLY: scale = max|leaf| / 127 per client per leaf, computed with one
    segment-max over the flat row and gathered back to ``[clients, padded]``.
    max is order-independent, so this is bit-identical to the per-leaf
    reductions — the property the layout-parity tests pin."""
    seg = jnp.asarray(segment_ids(layout))
    maxes = jax.vmap(
        lambda row: jax.ops.segment_max(
            row,
            seg,
            num_segments=layout.num_leaves + 1,
            indices_are_sorted=True,
        )
    )(jnp.abs(y))
    return maxes[:, seg] / 127.0
