"""Loss primitives shaped for the TPU backend.

``optax.softmax_cross_entropy_with_integer_labels`` selects each example's
label logit with ``take_along_axis`` — a one-element-per-row gather whose
XLA:TPU lowering is a SERIAL per-example slice loop, with a matching scatter
in the backward pass. At the bench config (64 clients x 128 batch, vmapped)
that is 8192 serial iterations per training step; the round-4 on-chip trace
(`artifacts/MFU_PROFILE_r04_presharded.json`) shows these loops, together
with the per-example crop gather, dominating the fused-round dispatch.

The one-hot contraction below computes the same value as a dense reduction
(VPU/MXU-friendly, fuses into the log-softmax) and its backward is a dense
broadcast instead of a scatter. Exactness: the selection itself is exact
(``1.0 * logp[label] + 0.0 * rest``; adding f32 zeros preserves bits), so
any deviation from the gather formulation comes only from softmax
accumulation order — measured <= 5e-10 on f32 gradients, 1e-6 on the
forward (pinned in ``tests/test_tpu_formulations.py``). As with every
zero-weight selection identity in this codebase (see
``fedtpu.data.augment``), it requires FINITE logits: ``0.0 * inf = nan``.

Parity: the loss itself matches the reference's ``nn.CrossEntropyLoss()``
(`/root/reference/src/main.py:77`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def softmax_ce_int_labels(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Per-example softmax cross-entropy with integer labels.

    ``logits: [..., C]`` (f32), ``labels: [...]`` int. Returns ``[...]`` f32.
    Same contract as ``optax.softmax_cross_entropy_with_integer_labels`` but
    gather-free (see module docstring): delegates to optax's DENSE-label CE,
    which contracts against the one-hot instead of gathering.
    """
    onehot = jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
    return optax.softmax_cross_entropy(logits, onehot)
