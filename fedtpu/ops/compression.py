"""On-device update compression — the TPU-native form of ``-c Y``.

The reference's compression is transport-level gzip on base64-pickled
checkpoints (``src/server.py:104-107``, ``src/client.py:39-43``): lossless,
host-side, and applied *after* a 33% base64 inflation. fedtpu compresses where
it actually pays on TPU: client *deltas* are sparsified/quantized on-device
*before* aggregation, so

- the FedAvg collective moves fewer effective bytes over ICI/DCN,
- the DCN edge transport (:mod:`fedtpu.transport`) can ship the compact form
  (top-k indices+values or int8 codes) instead of dense f32,
- error feedback keeps convergence: what a round drops is carried into the
  next round's delta (residual state per client, living alongside momentum in
  :class:`fedtpu.core.round.FederatedState`).

Codecs:
- ``topk``  — per-leaf, per-client magnitude top-k (fraction ``topk_fraction``).
- ``int8``  — per-leaf, per-client symmetric int8 quantization.
- ``rotq``  — flat-layout only: seeded structured random rotation
  (subsampled randomized Hadamard transform, Konečný et al. 1610.05492)
  followed by per-row uniform b-bit quantization with stochastic rounding;
  the server inverse-rotates the dequantized row. Requires the
  power-of-two row padding (``Compressor.pad_pow2``).
- ``randk`` — flat-layout only: seeded random-coordinate subsampling.
  With error feedback the kept coordinates ship unscaled (contractive; the
  residual carries exactly the dropped mass); without it they are rescaled
  by ``total/k`` so the estimator is unbiased. The per-round coordinate
  set is one shared seeded draw, so the codec is deterministic and both
  wire ends agree without shipping indices.

Both run through the fused Pallas kernels in
:mod:`fedtpu.ops.pallas_kernels`; both are simulated on-device (compress →
decompress) so aggregation sees exactly the numbers the wire format would
carry.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from fedtpu.config import FedConfig
from fedtpu.ops import flat as flat_ops
from fedtpu.ops import pallas_kernels as pk

Pytree = Any


class Compressor(NamedTuple):
    """A stateful delta codec.

    ``init(params, num_clients)`` builds the per-client residual state (the
    empty tuple ``()`` when error feedback is off). ``apply(deltas, state)`` maps
    stacked per-client deltas ``[clients, ...]`` to (compressed deltas, new
    state). ``apply`` is pure and jit/shard_map-safe; under ``shard_map`` the
    clients axis of both deltas and state is the sharded axis.

    ``layout`` names the delta layout the codec was built for. Per-leaf
    codecs (the default) map each pytree leaf independently. Flat codecs
    (``layout="flat"``, :mod:`fedtpu.ops.flat`) additionally expose
    ``apply_flat(flat_deltas, state, flat_layout)`` operating on the packed
    ``[clients, P]`` buffer directly — the round step packs once and calls
    it so the whole codec suite is a handful of fused ops instead of
    per-leaf dispatches; residual state is then one ``[clients, P]`` buffer.
    ``apply`` still works on pytrees for flat codecs (it packs/unpacks
    internally), so standalone callers need not care about the layout.

    ``pad_pow2`` marks codecs whose flat row must be padded to a power of
    two (the Hadamard butterfly of ``rotq``): the round step and the
    residual initialiser build their layouts with
    ``make_layout(..., pow2=True)`` when it is set. Seeded codecs
    (``rotq``/``randk``) additionally accept a ``round_idx`` keyword on
    ``apply_flat`` — the per-round seed that keeps client and server (and
    replays) drawing identical rotations/coordinate sets.
    """

    init: Callable[[Pytree, int], Pytree]
    apply: Callable[[Pytree, Pytree], Tuple[Pytree, Pytree]]
    layout: str = "per_leaf"
    apply_flat: Optional[
        Callable[[jnp.ndarray, Pytree, flat_ops.FlatLayout], Tuple[jnp.ndarray, Pytree]]
    ] = None
    pad_pow2: bool = False


def _flatten_leaf(d: jnp.ndarray) -> jnp.ndarray:
    """[clients, ...] -> [clients, size] float32."""
    return d.reshape((d.shape[0], -1)).astype(jnp.float32)


def _make_init(error_feedback: bool) -> Callable[[Pytree, int], Pytree]:
    """Residual-state initialiser: per-client zeros shaped like the stacked
    params when error feedback is on; the empty pytree ``()`` otherwise (the
    same sentinel :class:`fedtpu.core.round.FederatedState` defaults to)."""

    def init(params: Pytree, num_clients: int) -> Pytree:
        if not error_feedback:
            return ()
        return jax.tree.map(
            lambda p: jnp.zeros((num_clients,) + p.shape, jnp.float32), params
        )

    return init


class _CodecPair(NamedTuple):
    """Sentinel wrapper for one leaf's (compressed, new_residual) result, so
    unzipping the mapped tree can't confuse codec outputs with tuple
    containers that happen to appear inside a caller's delta pytree."""

    compressed: jnp.ndarray
    residual: Optional[jnp.ndarray]


def _make_apply(
    leaf: Callable[[jnp.ndarray, Optional[jnp.ndarray]], Tuple[jnp.ndarray, jnp.ndarray]],
    error_feedback: bool,
) -> Callable[[Pytree, Pytree], Tuple[Pytree, Pytree]]:
    """Lift a per-leaf ``(delta, residual) -> (compressed, new_residual)``
    codec to pytrees, handling the no-error-feedback case (empty state)."""

    def apply(deltas: Pytree, state: Pytree) -> Tuple[Pytree, Pytree]:
        if error_feedback:
            pairs = jax.tree.map(lambda d, e: _CodecPair(*leaf(d, e)), deltas, state)
        else:
            pairs = jax.tree.map(lambda d: _CodecPair(*leaf(d, None)), deltas)
        is_pair = lambda x: isinstance(x, _CodecPair)
        out = jax.tree.map(lambda p: p.compressed, pairs, is_leaf=is_pair)
        if not error_feedback:
            return out, state
        new_state = jax.tree.map(lambda p: p.residual, pairs, is_leaf=is_pair)
        return out, new_state

    return apply


def _make_flat_init(
    error_feedback: bool, pow2: bool = False
) -> Callable[[Pytree, int], Pytree]:
    """Flat-layout residual initialiser: ONE ``[clients, P]`` buffer instead
    of a per-leaf pytree (or ``()`` when error feedback is off)."""

    def init(params: Pytree, num_clients: int) -> Pytree:
        if not error_feedback:
            return ()
        lay = flat_ops.make_layout(params, pow2=pow2)
        return jnp.zeros((num_clients, lay.padded), jnp.float32)

    return init


def _lift_flat(
    apply_flat, pow2: bool = False
) -> Callable[[Pytree, Pytree], Tuple[Pytree, Pytree]]:
    """Pytree-level ``apply`` for a flat codec: pack once, run the flat
    codec, unpack. Standalone-caller convenience — the round step packs its
    own buffer and calls ``apply_flat`` directly."""

    def apply(deltas: Pytree, state: Pytree) -> Tuple[Pytree, Pytree]:
        lay = flat_ops.make_layout_stacked(deltas, pow2=pow2)
        out, new_state = apply_flat(
            flat_ops.pack_stacked(lay, deltas), state, lay
        )
        return flat_ops.unpack_stacked(lay, out), new_state

    return apply


def _make_topk_flat(fraction: float, error_feedback: bool) -> Compressor:
    """Flat-layout top-k: ONE ``top_k`` + ONE threshold kernel over the
    whole ``[clients, P]`` buffer per round. The keep budget
    ``k = ceil(fraction * total)`` is GLOBAL across the model — the same
    overall budget as the per-leaf codec, spent on the globally largest
    coordinates instead of quantised leaf-by-leaf (the documented semantic
    difference between layouts; see docs/FLAT_DELTA.md)."""

    def apply_flat(y, state, lay):
        if error_feedback:
            y = y + state
        kth = flat_ops.topk_threshold(y, fraction, lay.total)
        if kth is None:  # keep-all budget: nothing dropped, residual zero
            return y, (jnp.zeros_like(y) if error_feedback else state)
        if not error_feedback:
            return jnp.where(jnp.abs(y) >= kth[:, None], y, 0.0), state
        return pk.threshold_with_feedback(y, kth)

    return Compressor(
        init=_make_flat_init(error_feedback),
        apply=_lift_flat(apply_flat),
        layout="flat",
        apply_flat=apply_flat,
    )


def _make_int8_flat(error_feedback: bool) -> Compressor:
    """Flat-layout int8: one segment-max for every leaf's scale, one fused
    elementwise quantize-dequantize over the whole buffer. Scales reproduce
    the per-leaf codec exactly (max is order-independent), so this path is
    bit-identical to ``layout='per_leaf'`` — pinned by the parity tests."""

    def apply_flat(y, state, lay):
        if error_feedback:
            y = y + state
        scale = flat_ops.int8_scales(y, lay)
        safe = jnp.where(scale > 0, scale, jnp.ones_like(scale))
        out = jnp.clip(jnp.round(y / safe), -127.0, 127.0) * safe
        if not error_feedback:
            return out, state
        return out, y - out

    return Compressor(
        init=_make_flat_init(error_feedback),
        apply=_lift_flat(apply_flat),
        layout="flat",
        apply_flat=apply_flat,
    )


# Base seeds for the per-round PRNG streams of the seeded codecs. The
# effective key is fold_in(PRNGKey(base), round_idx) — deterministic per
# round, shared by every client in the engine, and distinct between the
# rotation and subsampling codecs.
_ROTQ_SEED = 0x5EED0    # noqa: E262 — rotation/uniform stream
_RANDK_SEED = 0x5EED1   # coordinate-subsampling stream

ROTQ_BIT_WIDTHS = (1, 2, 4, 8)


def _make_rotq_flat(bits: int, error_feedback: bool) -> Compressor:
    """Flat-layout rotated-sketch quantizer (rotq): rotate the padded row
    through the seeded randomized Hadamard transform, uniform-quantize to
    ``bits`` bits per coordinate with stochastic rounding over the per-row
    [min, max] range, then inverse-rotate — so aggregation sees exactly the
    values the wire record reconstructs.

    Unbiasedness: stochastic rounding satisfies ``E[q] = z`` per rotated
    coordinate conditionally on the (z-measurable) range, and both
    rotations are linear, so ``E[out] = delta + residual`` — the property
    ``tests/test_properties.py`` pins over seeds. The rotation spreads each
    coordinate's energy across the row, so the per-row uniform grid costs
    ~O(||y||/sqrt(h)) per coordinate instead of O(max|y|) (Konečný et al.).

    Pad-clean rule: the rotated row legitimately mixes real coordinates
    into the pad region, so the codec re-zeros ``[total:]`` AFTER the
    inverse rotation. In exact math those coordinates are exactly zero
    (the pad of ``y`` is zero and the transform pair is the identity);
    only quantization noise lands there, and dropping it keeps the buffer
    invariant without biasing the real coordinates.
    """
    if bits not in ROTQ_BIT_WIDTHS:
        raise ValueError(
            f"rotq bits must be one of {ROTQ_BIT_WIDTHS}, got {bits}"
        )
    levels = float(2**bits - 1)

    def apply_flat(y, state, lay, round_idx=0):
        if error_feedback:
            y = y + state
        h = lay.padded
        if h & (h - 1):
            raise ValueError(
                f"rotq needs a power-of-two row (got padded={h}); build the "
                "layout with make_layout(..., pow2=True)"
            )
        key = jax.random.fold_in(jax.random.PRNGKey(_ROTQ_SEED), round_idx)
        k_sign, k_unif = jax.random.split(key)
        signs = jax.random.rademacher(k_sign, (h,), jnp.float32)
        z = pk.hadamard_rotate(y, signs)
        lo = jnp.min(z, axis=1, keepdims=True)
        scale = (jnp.max(z, axis=1, keepdims=True) - lo) / levels
        safe = jnp.where(scale > 0, scale, jnp.ones_like(scale))
        u = jax.random.uniform(k_unif, z.shape, jnp.float32)
        q = jnp.clip(jnp.floor((z - lo) / safe + u), 0.0, levels)
        out = pk.hadamard_rotate(lo + q * safe, signs, inverse=True)
        if lay.pad:
            out = jnp.concatenate(
                [out[:, : lay.total], jnp.zeros_like(out[:, lay.total :])],
                axis=1,
            )
        if not error_feedback:
            return out, state
        return out, y - out

    return Compressor(
        init=_make_flat_init(error_feedback, pow2=True),
        apply=_lift_flat(apply_flat, pow2=True),
        layout="flat",
        apply_flat=apply_flat,
        pad_pow2=True,
    )


def _make_randk_flat(fraction: float, error_feedback: bool) -> Compressor:
    """Flat-layout random-k subsampling (randk): one shared seeded draw of
    ``k = ceil(fraction * total)`` real coordinates per round; every client
    ships exactly those.

    The EF rescale rule (documented in docs/FLAT_DELTA.md, pinned by
    ``tests/test_properties.py``): with error feedback OFF the kept values
    are rescaled by ``total/k`` so the estimator is unbiased
    (``E[out] = y`` over the uniform coordinate draw). With error feedback
    ON the rescale is dropped — the residual then carries exactly the
    dropped mass (``out + residual == y``), which keeps the compression
    operator contractive; a rescaled-and-fed-back variant would inject the
    (total/k - 1)-amplified kept mass into the residual and diverge.
    """

    def apply_flat(y, state, lay, round_idx=0):
        if error_feedback:
            y = y + state
        k = max(1, int(math.ceil(fraction * lay.total)))
        if k >= lay.total:  # keep-all budget
            return y, (jnp.zeros_like(y) if error_feedback else state)
        key = jax.random.fold_in(jax.random.PRNGKey(_RANDK_SEED), round_idx)
        idx = jax.random.choice(key, lay.total, (k,), replace=False)
        mask = jnp.zeros((lay.padded,), jnp.float32).at[idx].set(1.0)
        kept = y * mask[None, :]
        if error_feedback:
            return kept, y - kept
        return kept * jnp.float32(lay.total / k), state

    return Compressor(
        init=_make_flat_init(error_feedback),
        apply=_lift_flat(apply_flat),
        layout="flat",
        apply_flat=apply_flat,
    )


def make_rotq(
    bits: int = 4, error_feedback: bool = True, layout: str = "flat"
) -> Compressor:
    """Rotated-sketch quantizer — flat layout only (the rotation is over
    the whole concatenated update by construction)."""
    if layout != "flat":
        raise ValueError("rotq is a flat-layout codec; set delta_layout='flat'")
    return _make_rotq_flat(bits, error_feedback)


def make_randk(
    fraction: float, error_feedback: bool = True, layout: str = "flat"
) -> Compressor:
    """Random-k coordinate subsampling — flat layout only (the coordinate
    draw is over the whole concatenated update by construction)."""
    if layout != "flat":
        raise ValueError("randk is a flat-layout codec; set delta_layout='flat'")
    return _make_randk_flat(fraction, error_feedback)


def make_topk(
    fraction: float, error_feedback: bool = True, layout: str = "per_leaf"
) -> Compressor:
    """Magnitude top-k sparsification with optional error feedback.

    Per leaf, per client: keep the ``ceil(fraction * size)`` largest-|.|
    entries of (delta + residual), zero the rest, carry the dropped mass as
    the next round's residual. Ties at the threshold may keep a few extra
    entries (threshold comparison is ``>=``) — harmless for convergence and
    it keeps the kernel a pure elementwise mask.

    ``layout="flat"`` swaps in the packed single-buffer codec
    (:func:`_make_topk_flat`): one ``top_k`` with a model-global threshold
    instead of one per leaf.
    """
    if layout == "flat":
        return _make_topk_flat(fraction, error_feedback)
    if layout != "per_leaf":
        raise ValueError(f"unknown delta layout {layout!r}; have per_leaf | flat")

    def leaf(d: jnp.ndarray, e: Optional[jnp.ndarray]):
        shape = d.shape
        y = _flatten_leaf(d)
        if e is not None:
            y = y + e.reshape(y.shape)
        size = y.shape[1]
        k = max(1, int(math.ceil(fraction * size)))
        if k >= size:
            return y.reshape(shape).astype(d.dtype), jnp.zeros(shape, jnp.float32)
        # k-th largest magnitude per client row is the keep threshold.
        kth = jax.lax.top_k(jnp.abs(y), k)[0][:, -1]
        if e is None:
            # No residual output wanted: a plain masked select, which XLA
            # fuses; the two-output kernel would force a dead full-size write.
            out = jnp.where(jnp.abs(y) >= kth[:, None], y, 0.0)
            return out.reshape(shape).astype(d.dtype), None
        out, new_e = pk.threshold_with_feedback(y, kth)
        return out.reshape(shape).astype(d.dtype), new_e.reshape(shape)

    return Compressor(init=_make_init(error_feedback), apply=_make_apply(leaf, error_feedback))


def make_int8(
    error_feedback: bool = True, layout: str = "per_leaf"
) -> Compressor:
    """Symmetric per-leaf int8 quantization with optional error feedback.

    scale = max|delta + residual| / 127 per client per leaf; wire format is
    int8 codes + one f32 scale (4096x smaller metadata than the values).
    On-device we simulate quantize→dequantize so FedAvg averages the exact
    wire numbers.

    ``layout="flat"`` swaps in the packed single-buffer codec
    (:func:`_make_int8_flat`): same per-leaf scales (bit-identical), one
    fused kernel instead of one per leaf.
    """
    if layout == "flat":
        return _make_int8_flat(error_feedback)
    if layout != "per_leaf":
        raise ValueError(f"unknown delta layout {layout!r}; have per_leaf | flat")

    def leaf(d: jnp.ndarray, e: Optional[jnp.ndarray]):
        shape = d.shape
        y = _flatten_leaf(d)
        if e is not None:
            y = y + e.reshape(y.shape)
        scale = jnp.max(jnp.abs(y), axis=1) / 127.0
        out = pk.quantdequant_int8(y, scale)
        new_e = None if e is None else (y - out).reshape(shape)
        return out.reshape(shape).astype(d.dtype), new_e

    return Compressor(init=_make_init(error_feedback), apply=_make_apply(leaf, error_feedback))


def make_compressor(fed: FedConfig) -> Optional[Compressor]:
    """Compressor from config (``FedConfig.compression`` +
    ``FedConfig.delta_layout``); None for 'none'."""
    if fed.compression == "none":
        return None
    if fed.compression == "topk":
        return make_topk(
            fed.topk_fraction, fed.error_feedback, layout=fed.delta_layout
        )
    if fed.compression == "int8":
        return make_int8(fed.error_feedback, layout=fed.delta_layout)
    if fed.compression == "rotq":
        return make_rotq(
            fed.rotq_bits, fed.error_feedback, layout=fed.delta_layout
        )
    if fed.compression == "randk":
        # randk shares the top-k keep-fraction knob: both answer "what
        # fraction of coordinates ship this round".
        return make_randk(
            fed.topk_fraction, fed.error_feedback, layout=fed.delta_layout
        )
    raise ValueError(f"unknown compression '{fed.compression}'")


def nnz_fraction(deltas: Pytree) -> jnp.ndarray:
    """Fraction of nonzero entries across a (compressed) delta pytree — an
    effective-wire-size diagnostic (used by tests and the transport edge;
    not currently part of RoundMetrics)."""
    leaves = jax.tree_util.tree_leaves(deltas)
    nnz = sum(jnp.sum(l != 0).astype(jnp.float32) for l in leaves)
    total = sum(l.size for l in leaves)
    return nnz / max(total, 1)
