"""fedtpu.obs — unified telemetry: span tracer, metrics registry, exporters.

The observability subsystem the round/transport/FT stack reports into
(docs/OBSERVABILITY.md). Three layers:

- :mod:`fedtpu.obs.registry` — thread-safe counters/gauges/histograms;
- :mod:`fedtpu.obs.trace` — nested spans, Chrome-trace (Perfetto) export,
  jax ``TraceAnnotation`` bridge;
- :mod:`fedtpu.obs.exporters` — schema-versioned JSONL round records and
  Prometheus text dumps;
- :mod:`fedtpu.obs.propagate` — trace-context propagation over gRPC
  (``fedtpu-trace-bin`` metadata; merge with ``tools/trace_merge.py``);
- :mod:`fedtpu.obs.http` — the live ``/metrics`` ``/healthz`` ``/statusz``
  endpoint (``--obs-port``) + the :class:`StatusBoard` it reads;
- :mod:`fedtpu.obs.flight` — the crash flight recorder (ring buffer dumped
  on unhandled exception, SIGUSR1, and failover transitions);
- :mod:`fedtpu.obs.profile` — the performance observatory: continuous
  MFU/roofline accounting, XLA compile observability, and the
  ``--profile-rounds`` device-trace capture windows.

:class:`Telemetry` bundles tracer+registry behind ``FedConfig.telemetry``
(``off | basic | trace``). No jax import at module scope — config-only and
FT users never pay for a backend.
"""

from fedtpu.obs.flight import FlightRecorder
from fedtpu.obs.http import ObsServer, StatusBoard
from fedtpu.obs.proc import process_fd_count, process_rss_bytes

from fedtpu.obs.exporters import (
    SCHEMA_VERSION,
    RoundRecordWriter,
    parse_prometheus_text,
    prometheus_text,
    read_round_records,
    write_prometheus,
)
from fedtpu.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_global_registry,
)
from fedtpu.obs.telemetry import (
    NULL_TELEMETRY,
    TELEMETRY_MODES,
    Telemetry,
    validate_telemetry_mode,
)
from fedtpu.obs.profile import (
    CaptureWindow,
    CompileWatcher,
    CostModel,
    RoundProfiler,
    analytic_flops,
    device_peaks,
    latency_summary,
    parse_round_window,
    roofline,
)
from fedtpu.obs.trace import SpanTracer, load_chrome_trace, write_chrome_trace

__all__ = [
    "CaptureWindow",
    "CompileWatcher",
    "CostModel",
    "RoundProfiler",
    "analytic_flops",
    "device_peaks",
    "latency_summary",
    "parse_round_window",
    "roofline",
    "FlightRecorder",
    "ObsServer",
    "StatusBoard",
    "process_fd_count",
    "process_rss_bytes",
    "SCHEMA_VERSION",
    "RoundRecordWriter",
    "parse_prometheus_text",
    "prometheus_text",
    "read_round_records",
    "write_prometheus",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_global_registry",
    "NULL_TELEMETRY",
    "TELEMETRY_MODES",
    "Telemetry",
    "validate_telemetry_mode",
    "SpanTracer",
    "load_chrome_trace",
    "write_chrome_trace",
]
