"""Performance observatory: continuous MFU/roofline accounting + compile
observability + profiler capture windows.

The headline number rides on ~1.31% MFU (artifacts/MFU_PROFILE_r04*.json),
but until this module that figure was a one-off hand-run artifact. Here the
accounting becomes *continuous*:

- :func:`analytic_flops` — an analytic per-architecture FLOP model that
  walks the jaxpr counting matmul/conv MACs, cross-checked against XLA's
  ``jax.jit(...).lower(...).compile().cost_analysis()`` (the two agree to a
  few percent on every zoo model; the ratio is stamped on the cost model so
  drift between them is visible, not silent).
- :class:`RoundProfiler` — per-round ``fedtpu_step_time_seconds``,
  ``fedtpu_achieved_flops_per_sec`` and ``fedtpu_mfu_ratio`` gauges through
  the existing registry, plus a ``snapshot()`` dict for ``/statusz`` and
  round records. Per-round cost is a handful of gauge sets (microseconds;
  gated ≤1% of a round by ``bench.py --mfu-microbench``).
- :class:`CompileWatcher` — counts and times XLA backend compilations via
  ``jax.monitoring`` listeners, with a steady-state recompile detector
  that warns + flight-records (silent steady-state recompiles are the
  classic JAX perf killer: one drifting shape and every "fast" round pays
  a multi-second compile).
- :func:`capture_window` / :class:`CaptureWindow` — programmatic
  ``jax.profiler`` windows (the CLIs' ``--profile-rounds N:M``) that also
  write a ``profile_meta.json`` sidecar carrying the wall-clock start, so
  ``tools/trace_merge.py`` can align device ops onto the host-span
  timeline.

Shared scalar conventions (same as bench.py): FLOPs/bytes are PER ROUND
from the SINGLE-round program — XLA cost analysis counts a ``lax.scan``
body once regardless of trip count, so the fused multi-round program
reports the same flops as one round. ``analytic_flops`` deliberately
follows the same scan-once convention so the cross-check compares like
with like.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("fedtpu.obs.profile")

# ------------------------------------------------------------- peak tables
# Public per-chip peak figures by PJRT device_kind substring (matched on
# the lowercase space/hyphen-stripped form): (bf16 FLOPs/s, HBM bytes/s).
# Single source of truth — bench.py and tools/bench_profile_tpu.py resolve
# through here.
PEAK_TABLE: Tuple[Tuple[Tuple[str, ...], float, Optional[float]], ...] = (
    (("v6e", "v6lite", "trillium"), 918e12, 1640e9),
    (("v5p",), 459e12, 2765e9),
    (("v5e", "v5lite"), 197e12, 819e9),
    (("v4",), 275e12, 1228e9),
    (("v3",), 123e12, 900e9),
    (("v2",), 45e12, 700e9),
)

# Operator overrides for platforms the table cannot know (CPU dev boxes,
# new chips): utilisation ratios against a wrong peak are worse than none.
PEAK_FLOPS_ENV = "FEDTPU_PEAK_FLOPS"
PEAK_HBM_ENV = "FEDTPU_PEAK_HBM_BYTES"


def device_peaks(device_kind: str) -> Tuple[Optional[float], Optional[float]]:
    """``(peak_flops_per_s, peak_hbm_bytes_per_s)`` for a PJRT device kind;
    ``(None, None)`` when unknown (CPU, future chips). The ``FEDTPU_PEAK_*``
    env overrides win over the table — the only way to get meaningful MFU
    on hardware the table doesn't cover."""
    peak_f = peak_b = None
    kind = (device_kind or "").lower().replace(" ", "").replace("-", "")
    for aliases, f, b in PEAK_TABLE:
        if any(a in kind for a in aliases):
            peak_f, peak_b = f, b
            break
    env_f = os.environ.get(PEAK_FLOPS_ENV)
    env_b = os.environ.get(PEAK_HBM_ENV)
    if env_f:
        try:
            peak_f = float(env_f)
        except ValueError:
            pass
    if env_b:
        try:
            peak_b = float(env_b)
        except ValueError:
            pass
    return peak_f, peak_b


# -------------------------------------------------------- analytic FLOPs
def _subjaxprs(params: dict):
    """Yield every jaxpr nested in an eqn's params (pjit bodies, scan/while
    bodies, cond branches, custom_* calls)."""
    for val in params.values():
        objs = val if isinstance(val, (list, tuple)) else (val,)
        for obj in objs:
            if hasattr(obj, "jaxpr"):  # ClosedJaxpr
                yield obj.jaxpr
            elif hasattr(obj, "eqns"):  # raw Jaxpr
                yield obj


def _dot_flops(eqn) -> float:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval.shape, eqn.invars[1].aval.shape
    k = math.prod(lhs[i] for i in lc)
    b = math.prod(lhs[i] for i in lb)
    m = math.prod(
        d for i, d in enumerate(lhs) if i not in lc and i not in lb
    )
    n = math.prod(
        d for i, d in enumerate(rhs) if i not in rc and i not in rb
    )
    return 2.0 * b * m * n * k


def _conv_flops(eqn) -> float:
    dnums = eqn.params["dimension_numbers"]
    rhs = eqn.invars[1].aval.shape
    out = eqn.outvars[0].aval.shape
    groups = eqn.params.get("feature_group_count", 1) or 1
    # rhs_spec = (out_chan, in_chan_per_group, *spatial)
    in_per_group = rhs[dnums.rhs_spec[1]]
    k_spatial = math.prod(rhs[i] for i in dnums.rhs_spec[2:])
    del groups  # in_chan axis of rhs is already per-group
    return 2.0 * math.prod(out) * in_per_group * k_spatial


def _count_jaxpr(jaxpr) -> float:
    flops = 0.0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            flops += _dot_flops(eqn)
        elif name == "conv_general_dilated":
            flops += _conv_flops(eqn)
        elif name == "cond":
            # One branch executes; count the worst case.
            branches = eqn.params.get("branches", ())
            flops += max(
                (_count_jaxpr(b.jaxpr) for b in branches), default=0.0
            )
        else:
            # scan/while bodies counted ONCE (the module's convention);
            # everything else recursed structurally.
            for sub in _subjaxprs(eqn.params):
                flops += _count_jaxpr(sub)
    return flops


def analytic_flops(fn: Callable, *args, **kwargs) -> float:
    """Analytic FLOP count of ``fn(*args)``: 2 FLOPs per matmul/conv MAC,
    read off the traced jaxpr's shapes. Elementwise/reduction ops are
    excluded (MXU work dominates every zoo model by orders of magnitude);
    ``lax.scan``/``while`` bodies are counted once — the same convention as
    XLA's ``cost_analysis`` (see module docstring), so the two are directly
    comparable."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return _count_jaxpr(closed.jaxpr)


# Pure shape/metadata primitives: XLA lowers these to layout bookkeeping or
# folds them into neighbouring fusions — they move no HBM bytes of their own
# (counting a scalar broadcast to [clients, ...] as traffic would swamp the
# model with phantom bytes).
_LAYOUT_PRIMS = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims", "transpose",
    "copy", "stop_gradient",
})

# Elementwise primitives XLA reliably folds into loop fusions: a chain of
# these runs as ONE pass over the data, so intermediates between them never
# touch HBM. The byte model groups maximal connected runs (see
# :func:`_bytes_jaxpr`) and charges only tensors crossing group boundaries.
_ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "neg", "abs", "max", "min", "pow",
    "integer_pow", "square", "sqrt", "rsqrt", "exp", "exp2", "log", "log1p",
    "expm1", "tanh", "sin", "cos", "logistic", "erf", "erf_inv", "erfc",
    "sign", "floor", "ceil", "round", "clamp", "rem", "nextafter",
    "select_n", "convert_element_type", "reduce_precision", "eq", "ne",
    "lt", "le", "gt", "ge", "and", "or", "not", "xor", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "is_finite", "add_any",
    "atan2",
})

# Reductions fuse with their PRODUCERS (XLA input fusion: the reduce is the
# fusion root, reading its operand from registers), but their outputs are
# materialization points — consumers start a fresh pass over the data.
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "argmax", "argmin",
})

_FUSIBLE_PRIMS = _LAYOUT_PRIMS | _ELEMENTWISE_PRIMS | _REDUCE_PRIMS


def _aval_bytes(var) -> float:
    if hasattr(var, "val"):  # Literal: a compile-time constant, not traffic
        return 0.0
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0.0
    return float(math.prod(shape)) * dtype.itemsize


def _bytes_jaxpr(jaxpr) -> float:
    """Fusion-aware byte walk of one jaxpr level.

    Greedy producer->consumer fusion grouping over
    :data:`_FUSIBLE_PRIMS`: a maximal connected run of elementwise /
    layout / reduction eqns is ONE pass over the data, charging only the
    tensors that cross its boundary (read once by each consuming group,
    written once by the producer) — intermediates inside a group are
    register traffic, not HBM. Reduction outputs always materialize
    (consumers re-read). Non-fusible ops (conv, dot, gather, rng, ...)
    are singleton groups, i.e. charged per-eqn input+output exactly as
    before. Layout eqns alias their output to their operand, so a
    pure-layout group charges nothing and a broadcast feeding another
    group charges its (small) operand, not the phantom broadcast bytes.
    scan/while bodies counted ONCE (the module's convention —
    comparable with XLA ``cost_analysis``); cond takes the worst branch.
    """
    eqns = jaxpr.eqns
    total = 0.0
    opaque = set()
    for i, eqn in enumerate(eqns):
        if eqn.primitive.name == "cond":
            branches = eqn.params.get("branches", ())
            total += max(
                (_bytes_jaxpr(b.jaxpr) for b in branches), default=0.0
            )
            opaque.add(i)
            continue
        subs = list(_subjaxprs(eqn.params))
        if subs:
            # The container eqn's own full-array operands are NOT added on
            # top: the body's boundary tensors carry the traffic.
            for sub in subs:
                total += _bytes_jaxpr(sub)
            opaque.add(i)

    producer: Dict[Any, int] = {}
    alias: Dict[Any, Any] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            producer[v] = i
        if (
            i not in opaque
            and eqn.primitive.name in _LAYOUT_PRIMS
            and eqn.invars
        ):
            alias[eqn.outvars[0]] = eqn.invars[0]

    def resolve(v):
        while not hasattr(v, "val") and v in alias:
            v = alias[v]
        return v  # a Literal endpoint charges 0 via _aval_bytes

    def fusible(i: int) -> bool:
        return i not in opaque and eqns[i].primitive.name in _FUSIBLE_PRIMS

    parent = list(range(len(eqns)))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]
            i = parent[i]
        return i

    for i, eqn in enumerate(eqns):
        if not fusible(i):
            continue
        for v in eqn.invars:
            if hasattr(v, "val"):  # Literal
                continue
            p = producer.get(v)
            if (
                p is not None
                and fusible(p)
                and eqns[p].primitive.name not in _REDUCE_PRIMS
            ):
                parent[find(i)] = find(p)

    def gid(i: int):
        return ("f", find(i)) if fusible(i) else ("op", i)

    consumers: Dict[Any, list] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not hasattr(v, "val"):
                consumers.setdefault(v, []).append(i)

    out_set = set(v for v in jaxpr.outvars if not hasattr(v, "val"))
    reads: Dict[Any, set] = {}
    writes: Dict[Any, set] = {}
    has_real: Dict[Any, bool] = {}
    for i, eqn in enumerate(eqns):
        if i in opaque:
            continue
        g = gid(i)
        if eqn.primitive.name not in _LAYOUT_PRIMS:
            has_real[g] = True
        for v in eqn.invars:
            if hasattr(v, "val"):
                continue
            p = producer.get(v)
            if p is None or gid(p) != g:
                r = resolve(v)
                if not hasattr(r, "val"):
                    reads.setdefault(g, set()).add(r)
        for v in eqn.outvars:
            cons = consumers.get(v, [])
            ext = (
                v in out_set
                or not cons
                or eqn.primitive.name in _REDUCE_PRIMS
                or any(gid(c) != g for c in cons)
            )
            if ext:
                w = resolve(v)
                if not hasattr(w, "val"):
                    writes.setdefault(g, set()).add(w)
    for g in set(reads) | set(writes):
        if not has_real.get(g):
            continue  # pure-layout group: bookkeeping, no traffic
        total += sum(_aval_bytes(v) for v in reads.get(g, ()))
        total += sum(_aval_bytes(v) for v in writes.get(g, ()))
    return total


def analytic_bytes(fn: Callable, *args, **kwargs) -> float:
    """Analytic HBM-traffic model of ``fn(*args)``: fusion-group boundary
    bytes at the JAXPR avals' stated dtypes, scan/while bodies counted
    once, shape/layout primitives free (see :func:`_bytes_jaxpr`).

    This is deliberately BACKEND-INDEPENDENT — read off the traced jaxpr,
    never the lowered HLO — because it exists to predict the TPU HBM
    effect of dtype/layout levers from a host without the chip: a CPU
    backend's ``cost_analysis`` bytes describe bf16 *emulation* (f32
    upconverts inserted by the CPU lowering), which inverts the very
    signal being measured. Fusion-awareness matters for the same reason:
    an unfused per-eqn count charges the f32 intermediates of e.g. a
    BatchNorm statistics chain at 5x activation size, even though XLA
    folds the whole chain into one pass over the (compute-dtype) input —
    biasing the count AGAINST exactly the dtype lever being measured.
    Greedy elementwise grouping is still a model, not a compiler:
    absolute numbers are approximate; mode-over-mode RATIOS (f32 vs
    bf16_mixed, per-client vs megabatched) are the supported use.
    On-chip, prefer the XLA figure (:func:`xla_cost`), which is measured
    from the optimised HLO."""
    import jax

    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return _bytes_jaxpr(closed.jaxpr)


def xla_cost(compiled) -> Dict[str, float]:
    """``{"flops": ..., "bytes": ...}`` from a compiled executable's
    ``cost_analysis()`` (normalising the list-wrapped form some PJRT
    versions return); zeros when unavailable."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return {"flops": 0.0, "bytes": 0.0}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    return {
        "flops": float(analysis.get("flops", 0.0)),
        "bytes": float(analysis.get("bytes accessed", 0.0)),
    }


def roofline(
    flops: Optional[float],
    bytes_accessed: Optional[float],
    peak_flops: Optional[float],
    peak_bw: Optional[float],
    achieved_flops_per_s: Optional[float] = None,
) -> Dict[str, Any]:
    """Classic roofline classification for one program execution. Returns
    ``arith_intensity_flops_per_byte``, ``ridge_point_flops_per_byte``,
    ``roofline_bound`` ("compute" | "bandwidth") and, when an achieved rate
    is given, ``roofline_utilization`` = achieved / ceiling-at-intensity.
    Keys are present-but-None when an input is missing — schema-stable for
    the ``--mfu-profile`` artifact."""
    out: Dict[str, Any] = {
        "arith_intensity_flops_per_byte": None,
        "ridge_point_flops_per_byte": None,
        "roofline_bound": None,
        "roofline_utilization": None,
    }
    if flops and bytes_accessed:
        out["arith_intensity_flops_per_byte"] = round(
            flops / bytes_accessed, 3
        )
    if peak_flops and peak_bw:
        out["ridge_point_flops_per_byte"] = round(peak_flops / peak_bw, 3)
    ai = out["arith_intensity_flops_per_byte"]
    ridge = out["ridge_point_flops_per_byte"]
    if ai is not None and ridge is not None:
        out["roofline_bound"] = "compute" if ai >= ridge else "bandwidth"
        if achieved_flops_per_s:
            ceiling = (
                peak_flops if ai >= ridge else peak_bw * ai
            )
            if ceiling:
                out["roofline_utilization"] = round(
                    achieved_flops_per_s / ceiling, 6
                )
    return out


# ------------------------------------------------------------- cost model
class CostModel:
    """Per-round FLOP/byte figures for one round program, carrying both the
    analytic count and the XLA cost-analysis one plus their agreement
    ratio. ``flops`` prefers XLA (it sees the post-optimisation HLO);
    analytic is the cross-check and the fallback when AOT compilation is
    unavailable (e.g. shard_map paths on some backends)."""

    def __init__(
        self,
        xla_flops: Optional[float] = None,
        xla_bytes: Optional[float] = None,
        analytic: Optional[float] = None,
        analytic_bytes: Optional[float] = None,
    ):
        self.xla_flops = xla_flops or None
        self.xla_bytes = xla_bytes or None
        self.analytic = analytic or None
        self.analytic_bytes = analytic_bytes or None
        self.flops = self.xla_flops or self.analytic
        self.source = (
            "xla" if self.xla_flops else
            ("analytic" if self.analytic else None)
        )
        self.agreement = (
            round(self.analytic / self.xla_flops, 4)
            if self.analytic and self.xla_flops else None
        )

    def as_dict(self) -> Dict[str, Any]:
        return {
            "flops_per_round": self.flops,
            "bytes_per_round": self.xla_bytes,
            "analytic_flops_per_round": self.analytic,
            "analytic_bytes_per_round": self.analytic_bytes,
            "flops_source": self.source,
            "analytic_vs_xla": self.agreement,
        }


def engine_cost_model(fed, xla_check: bool = True) -> CostModel:
    """Build the per-round :class:`CostModel` for a
    :class:`fedtpu.core.engine.Federation`'s device-data round program:
    analytic jaxpr walk + (with ``xla_check``, best-effort) AOT compile for
    ``cost_analysis``. One-time cost at first use (the AOT compile hits
    the persistent XLA compile cache the engine already enables)."""
    import jax.numpy as jnp

    d_images, d_labels, d_idx, d_mask = fed._ensure_device_data()
    n = fed.cfg.fed.num_clients
    alive = fed._placed(
        jnp.ones((n,), bool), sharded=fed.mesh is not None
    )
    extra = ()
    if fed._attack_seats is not None:
        extra = (jnp.asarray(fed._attack_seats),)
    args = (
        fed.state, d_images, d_labels, d_idx, d_mask, fed.weights, alive,
        fed._data_key, *extra,
    )
    analytic = ab = None
    try:
        import jax

        closed = jax.make_jaxpr(fed._data_step)(*args)
        analytic = _count_jaxpr(closed.jaxpr)
        ab = _bytes_jaxpr(closed.jaxpr)
    except Exception as e:  # pragma: no cover - backend quirks
        log.debug("analytic FLOP/byte model failed: %s", e)
    xf = xb = None
    if xla_check:
        try:
            compiled = fed._data_step.lower(*args).compile()
            cost = xla_cost(compiled)
            xf, xb = cost["flops"], cost["bytes"]
        except Exception as e:  # pragma: no cover - backend quirks
            log.debug("XLA cost analysis unavailable: %s", e)
    return CostModel(
        xla_flops=xf, xla_bytes=xb, analytic=analytic, analytic_bytes=ab
    )


# ---------------------------------------------------------- round profiler
class RoundProfiler:
    """Continuous per-round MFU/step-time accounting through one Telemetry.

    ``observe_round(wall_s, rounds=n)`` after each dispatch sets three
    gauges and returns the derived dict for round-record stamping. All
    per-round work is arithmetic + gauge sets (no device sync, no
    compile); the cost model is attached once via :meth:`set_cost_model`.
    """

    def __init__(
        self,
        telemetry,
        n_devices: int = 1,
        device_kind: str = "",
    ):
        self.telemetry = telemetry
        self.n_devices = max(1, int(n_devices))
        self.device_kind = device_kind
        self.peak_flops, self.peak_bw = device_peaks(device_kind)
        self.cost: Optional[CostModel] = None
        self._last: Dict[str, Any] = {}
        self._rounds = 0

    def set_cost_model(self, cost: CostModel) -> None:
        self.cost = cost

    def observe_round(self, wall_s: float, rounds: int = 1) -> Dict[str, Any]:
        """Account one dispatch of ``rounds`` fused rounds taking ``wall_s``
        seconds; returns ``{step_time_s, achieved_flops_per_s, mfu}``
        (items None when underivable) after updating the gauges."""
        tel = self.telemetry
        step_s = wall_s / max(1, rounds)
        self._rounds += rounds
        out: Dict[str, Any] = {
            "step_time_s": step_s,
            "achieved_flops_per_s": None,
            "mfu": None,
        }
        tel.gauge(
            "fedtpu_step_time_seconds",
            "wall time of the last round dispatch, per round",
        ).set(step_s)
        flops = self.cost.flops if self.cost else None
        if flops and wall_s > 0:
            achieved = flops * rounds / wall_s
            out["achieved_flops_per_s"] = achieved
            tel.gauge(
                "fedtpu_achieved_flops_per_sec",
                "model FLOPs retired per second over the last dispatch "
                "(all devices)",
            ).set(achieved)
            if self.peak_flops:
                mfu = achieved / (self.n_devices * self.peak_flops)
                out["mfu"] = mfu
                tel.gauge(
                    "fedtpu_mfu_ratio",
                    "model FLOPs utilization of the last dispatch vs "
                    "per-chip peak (device_peaks table or FEDTPU_PEAK_FLOPS)",
                ).set(mfu)
        self._last = out
        return out

    def record_fields(self) -> Dict[str, Any]:
        """Rounded stamps for a v1 round record from the last observation
        (empty before any round / when underivable) — the round loops merge
        this into each record they emit."""
        out: Dict[str, Any] = {}
        last = self._last
        if last.get("achieved_flops_per_s"):
            out["achieved_flops_per_s"] = round(
                last["achieved_flops_per_s"], 1
            )
        if last.get("mfu") is not None:
            out["mfu"] = round(last["mfu"], 6)
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The ``/statusz`` perf block: last-round derived figures + the
        static cost model and peaks."""
        snap: Dict[str, Any] = {
            "device_kind": self.device_kind,
            "n_devices": self.n_devices,
            "peak_flops_per_s": self.peak_flops,
            "rounds_observed": self._rounds,
        }
        if self.cost is not None:
            snap.update(self.cost.as_dict())
        snap.update(self._last)
        if self.cost is not None and self._last.get("achieved_flops_per_s"):
            snap.update(roofline(
                self.cost.flops, self.cost.xla_bytes,
                self.peak_flops, self.peak_bw,
                self._last["achieved_flops_per_s"] / self.n_devices,
            ))
        return snap


# ------------------------------------------------------- latency summaries
def latency_summary(
    pairs: Sequence[Tuple[str, float]], top_k: int = 3
) -> Dict[str, Any]:
    """p50/p95/p99 + top-k slowest over ``(client, seconds)`` pairs — the
    straggler-attribution block on server round records and ``/statusz``.
    Empty input yields ``{}`` (rounds with no completed RPCs)."""
    if not pairs:
        return {}
    lats = sorted(v for _, v in pairs)

    def pct(p: float) -> float:
        # Nearest-rank percentile: exact at small n, no interpolation.
        i = min(len(lats) - 1, max(0, math.ceil(p / 100.0 * len(lats)) - 1))
        return round(lats[i], 6)

    slowest = sorted(pairs, key=lambda cv: cv[1], reverse=True)[:top_k]
    return {
        "n": len(pairs),
        "p50_s": pct(50),
        "p95_s": pct(95),
        "p99_s": pct(99),
        "max_s": round(lats[-1], 6),
        "slowest": [[c, round(v, 6)] for c, v in slowest],
    }


# --------------------------------------------------------- compile watcher
_COMPILE_EVENT_SUBSTR = "backend_compile"


class CompileWatcher:
    """Count + time XLA compilations via ``jax.monitoring`` duration events
    (``/jax/core/compile/backend_compile_duration`` fires once per backend
    compile). After :meth:`mark_steady` — the owner's signal that every
    program it intends to run has warmed up — any further compile is a
    *steady-state recompile*: it warns, flight-records, and bumps
    ``fedtpu_xla_recompiles_steady_total``, because a recompile inside the
    round loop silently turns a ~ms round into a multi-second one.

    ``install()``/``uninstall()`` manage the process-global listener; one
    active watcher per process (the registration API has no scoping)."""

    _active: Optional["CompileWatcher"] = None

    def __init__(self, telemetry=None, flight=None):
        self.telemetry = telemetry
        self.flight = flight
        self.compiles = 0
        self.compile_seconds = 0.0
        self.recompiles_after_steady = 0
        self._steady = False
        self._installed = False
        self._lock = threading.Lock()

    # The listener survives uninstall() in jax versions without an
    # unregister API — the _installed gate keeps it inert.
    def _listener(self, event: str, duration: float, **kwargs) -> None:
        if not self._installed or _COMPILE_EVENT_SUBSTR not in event:
            return
        with self._lock:
            self.compiles += 1
            self.compile_seconds += duration
            steady = self._steady
            if steady:
                self.recompiles_after_steady += 1
        tel = self.telemetry
        if tel is not None:
            tel.counter(
                "fedtpu_xla_compiles_total",
                "XLA backend compilations observed by this process",
            ).inc()
            tel.histogram(
                "fedtpu_xla_compile_seconds",
                "XLA backend compile wall time per executable",
            ).observe(duration)
        if steady:
            log.warning(
                "steady-state XLA recompile (%.2fs): a program shape or "
                "constant drifted after warmup — the classic silent round "
                "slowdown (compiles so far: %d)", duration, self.compiles,
            )
            if tel is not None:
                tel.counter(
                    "fedtpu_xla_recompiles_steady_total",
                    "XLA compilations after the owner declared steady "
                    "state (each one is a latent perf bug)",
                ).inc()
            if self.flight is not None:
                self.flight.record(
                    "xla_recompile",
                    duration_s=round(duration, 4),
                    compiles_total=self.compiles,
                )

    def install(self) -> "CompileWatcher":
        if self._installed:
            return self
        if CompileWatcher._active is not None:
            raise RuntimeError(
                "another CompileWatcher is already installed in this "
                "process (jax.monitoring listeners are global)"
            )
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(self._listener)
        self._installed = True
        CompileWatcher._active = self
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False
        if CompileWatcher._active is self:
            CompileWatcher._active = None
        try:  # best-effort: the public API grew unregister late
            from jax._src import monitoring as _m

            _m._unregister_event_duration_listener_by_callback(
                self._listener
            )
        except Exception:
            pass  # inert via the _installed gate

    def mark_steady(self) -> None:
        with self._lock:
            self._steady = True

    @property
    def steady(self) -> bool:
        return self._steady

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "compiles": self.compiles,
                "compile_seconds": round(self.compile_seconds, 4),
                "steady": self._steady,
                "recompiles_after_steady": self.recompiles_after_steady,
            }


# -------------------------------------------------------- capture windows
PROFILE_META = "profile_meta.json"


def parse_round_window(spec: str) -> Tuple[int, int]:
    """Parse ``--profile-rounds N:M`` into a half-open ``[N, M)`` round
    window (``"3:5"`` captures rounds 3 and 4). A bare ``N`` means one
    round ``[N, N+1)``."""
    try:
        if ":" in spec:
            a, b = spec.split(":", 1)
            lo, hi = int(a), int(b)
        else:
            lo = int(spec)
            hi = lo + 1
    except ValueError:
        raise ValueError(
            f"--profile-rounds wants N:M (half-open round window), "
            f"got {spec!r}"
        )
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"--profile-rounds window must satisfy 0 <= N < M, got {spec!r}"
        )
    return lo, hi


def write_profile_meta(
    trace_dir: str, role: str = "", trace_id: Optional[str] = None,
    extra: Optional[dict] = None,
) -> str:
    """Drop the ``profile_meta.json`` sidecar into a profiler output dir:
    ``wall_start`` (wall clock at capture start — device-trace timestamps
    are relative to it) + role/trace_id for lane naming and federation
    stitching. This is what lets ``tools/trace_merge.py`` put device ops on
    the same wall-clock timeline as host spans."""
    meta = {
        "wall_start": time.time(),
        "role": role,
        "trace_id": trace_id,
        "format": "jax.profiler",
    }
    if extra:
        meta.update(extra)
    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, PROFILE_META)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh)
    os.replace(tmp, path)
    return path


class CaptureWindow:
    """Round-windowed ``jax.profiler`` capture for a round loop.

    The loop calls :meth:`maybe_start` with the first round of the block it
    is about to dispatch and :meth:`maybe_stop` with the next round index
    after it completes; the window opens before the first block that
    overlaps ``[lo, hi)`` and closes after the block that reaches ``hi``.
    Fused blocks are captured whole (the profiler cannot cut inside one
    dispatch). ``stop()`` is idempotent and must be called on loop exit so
    a window that spans the tail still flushes."""

    def __init__(
        self, spec: str, trace_dir: str,
        role: str = "", trace_id: Optional[str] = None,
    ):
        self.lo, self.hi = parse_round_window(spec)
        self.trace_dir = trace_dir
        self.role = role
        self.trace_id = trace_id
        self._ctx = None

    @property
    def active(self) -> bool:
        return self._ctx is not None

    def maybe_start(self, first_round: int, last_round: int = None) -> None:
        """Open the window if block ``[first_round, last_round]`` overlaps
        it (``last_round`` defaults to ``first_round``)."""
        if self._ctx is not None:
            return
        last = first_round if last_round is None else last_round
        if first_round >= self.hi or last < self.lo:
            return
        import jax

        write_profile_meta(
            self.trace_dir, role=self.role, trace_id=self.trace_id,
            extra={"round_window": [self.lo, self.hi]},
        )
        self._ctx = jax.profiler.trace(self.trace_dir)
        self._ctx.__enter__()
        log.info(
            "profiler capture window open: rounds [%d, %d) -> %s",
            self.lo, self.hi, self.trace_dir,
        )

    def maybe_stop(self, next_round: int) -> None:
        if self._ctx is not None and next_round >= self.hi:
            self.stop()

    def stop(self) -> None:
        if self._ctx is None:
            return
        ctx, self._ctx = self._ctx, None
        try:
            ctx.__exit__(None, None, None)
        except Exception as e:  # pragma: no cover - profiler teardown
            log.warning("profiler capture stop failed: %s", e)
        else:
            log.info("profiler capture window closed: %s", self.trace_dir)


def find_device_trace(trace_dir: str) -> Optional[str]:
    """Locate the newest ``*.trace.json.gz`` a ``jax.profiler.trace``
    session wrote under ``trace_dir`` (layout:
    ``plugins/profile/<run>/<host>.trace.json.gz``); None if absent."""
    hits: List[str] = []
    for dirpath, _dirs, files in os.walk(trace_dir):
        for f in files:
            if f.endswith(".trace.json.gz") or f.endswith(".trace.json"):
                hits.append(os.path.join(dirpath, f))
    return max(hits, key=os.path.getmtime) if hits else None
