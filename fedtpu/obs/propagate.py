"""Trace-context propagation over the gRPC edge.

PR 3's tracer made each process's spans self-consistent; this module makes
them *federation*-consistent. The coordinator attaches a
``fedtpu-trace-bin`` metadata entry to every outbound RPC (StartTrain,
SendModel, HeartBeat, CheckIfPrimaryUp, FetchModel) carrying:

- ``trace_id``  — the federation-wide run identity (the coordinator
  tracer's random id, adopted by every client that sees it);
- ``span_id``   — the *sender-local* id of the innermost open span on the
  issuing thread (the ``client_rpc`` span for collect workers, 0 when no
  span is open, e.g. heartbeat probes);
- ``role``      — the sender's process identity ("primary", "backup", ...),
  which is how a receiver's ``remote_parent`` id is resolved to the right
  per-process trace file at merge time;
- ``round``     — the coordinator's lineage round counter.

The payload is JSON bytes (gRPC binary metadata — the ``-bin`` suffix is
mandatory for non-ASCII values): a dozen µs of encode+decode per RPC
against multi-ms RPCs (measured: ``bench.py --obs-plane-microbench``,
artifacts/OBS_PLANE_MICROBENCH.json). Injection happens in a client-side
interceptor whose context *source* is injected, so the transport layer
never imports server internals; when the source returns ``None`` (telemetry
below ``trace``) the interceptor forwards the call untouched and costs one
function call.

Receivers (`fedtpu.transport.service.trace_context_of` →
``ClientAgent``/``LocalTrainer``) stamp the extracted fields onto their own
spans as ``trace_id`` / ``remote_parent`` / ``remote_role`` args and adopt
the trace id — the cross-process link ``tools/trace_merge.py`` stitches on.

No jax import; safe for config-only and tools users.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Optional

METADATA_KEY = "fedtpu-trace-bin"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One RPC's propagated trace coordinates (see module docstring)."""

    trace_id: str
    span_id: int = 0
    role: str = ""
    round: int = 0


def encode_context(ctx: TraceContext) -> bytes:
    return json.dumps(
        {
            "trace_id": ctx.trace_id,
            "span_id": int(ctx.span_id or 0),
            "role": ctx.role,
            "round": int(ctx.round),
        },
        separators=(",", ":"),
    ).encode()


def decode_context(data: bytes) -> Optional[TraceContext]:
    """None on any malformed payload — a bad peer must never break an RPC."""
    try:
        obj = json.loads(data.decode())
        return TraceContext(
            trace_id=str(obj["trace_id"]),
            span_id=int(obj.get("span_id", 0)),
            role=str(obj.get("role", "")),
            round=int(obj.get("round", 0)),
        )
    except (ValueError, KeyError, TypeError, AttributeError):
        return None


def from_metadata(metadata) -> Optional[TraceContext]:
    """Extract the context from gRPC invocation metadata (a sequence of
    ``(key, value)`` pairs), or None when absent/malformed."""
    if not metadata:
        return None
    for key, value in metadata:
        if key == METADATA_KEY:
            if isinstance(value, str):
                value = value.encode()
            return decode_context(value)
    return None


def span_args(ctx: Optional[TraceContext]) -> dict:
    """The receiver-side span args a propagated context contributes:
    ``trace_id`` (the coordinator's), ``remote_parent`` + ``remote_role``
    (the cross-process parent link trace_merge resolves), and the
    coordinator's ``coord_round`` (named so it can never collide with a
    receiver's own ``round=`` span arg). Empty when no context arrived, so
    call sites can unconditionally ``**span_args(ctx)``."""
    if ctx is None:
        return {}
    args = {"trace_id": ctx.trace_id, "coord_round": ctx.round}
    if ctx.span_id:
        args["remote_parent"] = ctx.span_id
        args["remote_role"] = ctx.role
    return args


def adopt(tracer, ctx: Optional[TraceContext]) -> None:
    """Adopt the federation trace id on a receiver's tracer (idempotent;
    no-op without a tracer or context)."""
    if tracer is not None and ctx is not None and ctx.trace_id:
        tracer.trace_id = ctx.trace_id


# ------------------------------------------------------------- interceptor
def _build_interceptor_types():
    """Interceptor classes are built lazily so this module imports without
    grpc (config-only users, tools)."""
    import grpc

    class _CallDetails(
        # namedtuple-style replacement: grpc requires a ClientCallDetails
        # instance, attribute-compatible with the one it handed us.
        grpc.ClientCallDetails
    ):
        def __init__(self, base, metadata):
            self.method = base.method
            self.timeout = base.timeout
            self.metadata = metadata
            self.credentials = getattr(base, "credentials", None)
            self.wait_for_ready = getattr(base, "wait_for_ready", None)
            self.compression = getattr(base, "compression", None)

    class TraceContextInterceptor(grpc.UnaryUnaryClientInterceptor):
        """Appends ``fedtpu-trace-bin`` metadata when the injected source
        yields a context; forwards untouched otherwise."""

        def __init__(self, source: Callable[[], Optional[TraceContext]]):
            self._source = source

        def intercept_unary_unary(self, continuation, client_call_details,
                                  request):
            try:
                ctx = self._source()
            except Exception:
                ctx = None
            if ctx is None:
                return continuation(client_call_details, request)
            metadata = list(client_call_details.metadata or ())
            metadata.append((METADATA_KEY, encode_context(ctx)))
            return continuation(
                _CallDetails(client_call_details, metadata), request
            )

    return TraceContextInterceptor


def instrument_channel(channel,
                       source: Callable[[], Optional[TraceContext]]):
    """Wrap ``channel`` so every unary RPC carries the source's current
    trace context. ``source`` runs per RPC on the issuing thread (that is
    what lets the innermost-span id ride along)."""
    import grpc

    return grpc.intercept_channel(channel, _build_interceptor_types()(source))
