"""Span tracer: nested round/client/phase spans with monotonic timing.

Spans are recorded as Chrome trace-event "complete" events (``ph: "X"``) so
a dump loads directly in Perfetto / ``chrome://tracing``. Two nesting
signals are emitted:

- **time containment** per thread track (``tid``) — what the viewers render;
- explicit ``args.span_id`` / ``args.parent_id`` links — what the tests
  (and :mod:`tools.metrics_report`) verify, and the only signal that holds
  across threads: a ``decode`` span running in a collect worker thread is
  parented to the main thread's ``round`` span by id, not by track.

Parentage defaults to the innermost open span **on the same thread**
(a thread-local stack); cross-thread children pass ``parent=`` explicitly
(:meth:`SpanTracer.span` / :meth:`SpanTracer.current_id`).

The jax bridge: with ``bridge_jax=True`` every span also enters a
``jax.profiler.TraceAnnotation`` of the same name, so when a jax profiler
session is active (``fedtpu.utils.progress.profile_rounds`` /
``--profile-dir``) XLA device activity nests under the framework spans in
the XProf timeline. TraceAnnotation is a no-op-cheap TraceMe when no
session is active; the import is lazy and failure-tolerant so the tracer
itself never drags in a backend.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional


class _NullSpan:
    """Shared no-op span: what ``Telemetry.span`` returns below ``trace``
    mode. ``id`` is None so ``parent=span.id`` chains stay valid."""

    __slots__ = ()
    id = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tracer", "name", "args", "id", "parent", "_t0", "_ann")

    def __init__(self, tracer: "SpanTracer", name: str,
                 parent: Optional[int], args: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.args = args
        self.parent = parent
        self.id = None
        self._t0 = 0.0
        self._ann = None

    def __enter__(self) -> "_Span":
        tr = self._tracer
        self.id = next(tr._ids)
        if self.parent is None:
            self.parent = tr.current_id()
        stack = tr._stack()
        stack.append(self.id)
        if tr._annotation is not None:
            try:
                self._ann = tr._annotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.monotonic()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        tr = self._tracer
        stack = tr._stack()
        if stack and stack[-1] == self.id:
            stack.pop()
        args = {"span_id": self.id}
        if self.parent is not None:
            args["parent_id"] = self.parent
        args.update(self.args)
        tr._record({
            "name": self.name,
            "ph": "X",
            "ts": round((self._t0 - tr._t0) * 1e6, 3),
            "dur": round((t1 - self._t0) * 1e6, 3),
            "pid": tr._pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "args": args,
        })


class SpanTracer:
    """Collects spans; thread-safe; export via :func:`write_chrome_trace`."""

    def __init__(self, bridge_jax: bool = False):
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self.wall_start = time.time()
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._pid = os.getpid()
        # Federation-wide trace identity: random per tracer, OVERWRITTEN on
        # remote clients the moment a propagated context arrives
        # (fedtpu.obs.propagate) so every process in one federation run
        # shares the coordinator's id. Span ids stay process-local;
        # tools/trace_merge.py qualifies them by role when stitching.
        self.trace_id: str = os.urandom(8).hex()
        # Optional per-event hook (e.g. the flight recorder's span feed) —
        # called with the finished Chrome event OUTSIDE the tracer lock.
        # Must never raise into the traced code path.
        self.sink = None
        self._annotation = None
        if bridge_jax:
            try:
                from jax.profiler import TraceAnnotation

                self._annotation = TraceAnnotation
            except Exception:
                self._annotation = None

    def _stack(self) -> List[int]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def _record(self, event: dict) -> None:
        with self._lock:
            self._events.append(event)
        sink = self.sink
        if sink is not None:
            try:
                sink(event)
            except Exception:
                pass

    # ------------------------------------------------------------------ api
    def span(self, name: str, parent: Optional[int] = None,
             **args: Any) -> _Span:
        """Context manager for one timed span. ``parent`` overrides the
        thread-local nesting (required when the span runs on a different
        thread than its logical parent)."""
        return _Span(self, name, parent, args)

    def current_id(self) -> Optional[int]:
        """Innermost open span id on THIS thread (None outside any span) —
        capture it before handing work to another thread, then pass it as
        that thread's ``parent=``."""
        stack = getattr(self._local, "stack", None)
        return stack[-1] if stack else None

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()


def write_chrome_trace(events: List[dict], path: str,
                       metadata: Optional[Dict[str, Any]] = None) -> None:
    """Write events as a Perfetto/chrome://tracing-loadable JSON object.

    ``metadata`` (ignored by viewers, read by ``tools/trace_merge.py``)
    carries the process identity a multi-process merge needs: the
    federation ``trace_id``, this process's ``role``/``pid``, and
    ``wall_start`` — the wall-clock time of the tracer's monotonic zero,
    which is how per-process relative timestamps align on one timeline.
    """
    doc = {
        "traceEvents": list(events),
        "displayTimeUnit": "ms",
    }
    if metadata:
        doc["metadata"] = dict(metadata)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(doc, fh)
    os.replace(tmp, path)


def load_chrome_trace(path: str) -> List[dict]:
    """Read back a :func:`write_chrome_trace` dump (accepts the bare-array
    form too — both are valid Chrome trace JSON)."""
    with open(path) as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        return doc
    return doc["traceEvents"]
