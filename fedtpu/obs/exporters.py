"""Exporters: versioned JSONL round records + Prometheus text dumps.

``RoundRecordWriter`` subsumes the old ``fedtpu.utils.metrics.MetricsLogger``
``--metrics`` path: same call shape (``log(step, **fields)``), same field
coercion, same JSONL-append-and-flush behavior — plus a pinned
``schema_version`` on every record so downstream consumers
(``tools/jsontail.py``, ``tools/metrics_report.py``, the watcher) can detect
drift instead of silently misreading a renamed field.

Schema history:
  - (unversioned, "v0"): PR-2-era records — no ``schema_version`` key.
    Readers treat them as version 0.
  - 1: adds ``schema_version``; the payload keys are whatever the producer
    logs (the round-record keys of ``PrimaryServer.round()`` / the engine
    CLIs are documented in docs/OBSERVABILITY.md). Bump this ONLY when an
    existing key changes meaning or is removed — additions are free.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from typing import Any, Dict, List, Optional

from fedtpu.obs.registry import Histogram, MetricsRegistry

SCHEMA_VERSION = 1


class RoundRecordWriter:
    """JSONL round-record sink with a pinned schema version.

    Drop-in for ``MetricsLogger`` (same ``log``/``close``/context-manager
    surface), so every call site that takes a ``logger=`` keeps working.
    """

    def __init__(self, path: Optional[str] = None, echo: bool = True):
        self._path = path
        self._echo = echo
        self._fh = open(path, "a") if path else None
        self._t0 = time.time()

    def log(self, step: int, **fields: Any) -> None:
        rec: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "step": int(step),
            "t": round(time.time() - self._t0, 4),
        }
        for k, v in fields.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = v
        line = json.dumps(rec)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self._echo:
            print(line, file=sys.stderr)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "RoundRecordWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_round_records(path: str) -> List[dict]:
    """Parse a round-record JSONL file. Unparseable lines are skipped (a
    crashed writer can truncate the tail); records without a
    ``schema_version`` are legacy v0 and get ``schema_version: 0`` stamped
    so consumers can branch on one key."""
    records = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            rec.setdefault("schema_version", 0)
            records.append(rec)
    return records


# ------------------------------------------------------------- prometheus
def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Full-precision sample rendering: ``%g``-style formatting silently
    rounds to 6 significant digits, which corrupts large byte counters."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in the Prometheus text exposition format
    (``# HELP`` / ``# TYPE`` + samples; histograms as cumulative
    ``_bucket{le=...}`` series plus ``_sum`` / ``_count``)."""
    lines: List[str] = []
    snap = registry.snapshot()
    for name, entries in snap.items():
        help_line = registry.help_text(name)
        if help_line:
            lines.append(f"# HELP {name} {help_line}")
        lines.append(f"# TYPE {name} {entries[0]['kind']}")
        for entry in entries:
            labels = entry["labels"]
            if entry["kind"] == "histogram":
                for le, cum in entry["buckets"].items():
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(dict(labels, le=repr(float(le))))} {cum}"
                    )
                lines.append(
                    f"{name}_bucket{_fmt_labels(dict(labels, le='+Inf'))} "
                    f"{entry['count']}"
                )
                lines.append(
                    f"{name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(entry['sum'])}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels)} {entry['count']}"
                )
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} "
                    f"{_fmt_value(entry['value'])}"
                )
    return "\n".join(lines) + "\n"


def write_prometheus(registry: MetricsRegistry, path: str) -> None:
    """Atomic file dump of :func:`prometheus_text` — the pull-less stand-in
    for a ``/metrics`` endpoint (point node_exporter's textfile collector,
    or a human, at it)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(prometheus_text(registry))
    os.replace(tmp, path)


_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Parse the exposition format back into
    ``{metric_name: {label_string: value}}`` (label_string is the sorted
    ``k=v,...`` form, ``""`` for no labels). Used by the exporter tests and
    :mod:`tools.metrics_report`; raises ValueError on a malformed sample
    line so a broken dump fails loudly."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed prometheus sample line: {line!r}")
        labels = {}
        if m.group("labels"):
            labels = {k: v for k, v in _LABEL_RE.findall(m.group("labels"))}
        lkey = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
        out.setdefault(m.group("name"), {})[lkey] = float(m.group("value"))
    return out
