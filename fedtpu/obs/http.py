"""Live introspection plane: a tiny stdlib HTTP endpoint per process.

PR 3's exporters only speak at exit; this serves the *running* federation
(``--obs-port`` on the server/run/train CLIs, off by default; loopback
bind by default):

- ``/metrics``  — the cumulative :class:`fedtpu.obs.MetricsRegistry` in
  Prometheus text exposition format, rendered from one
  ``registry.snapshot()`` per request (each scrape is a consistent
  point-in-time view; scraping mid-round is safe and tested);
- ``/healthz``  — 200 ``ok`` while the process is HEALTHY; 503 with a
  one-line reason while it is not (a fenced coordinator pending re-base,
  quorum unmet) — honest enough for an orchestrator probe to act on,
  via an injected ``health_fn`` (no ``health_fn`` keeps the legacy
  unconditional 200);
- ``/statusz``  — JSON from an injected ``status_fn`` (the owning
  component's :meth:`status_snapshot`: current round + phase, client
  liveness, failover role, heartbeat misses, last-round phase timings —
  rendered live by ``tools/statusz.py``);
- ``/flightz``  — the flight recorder's current ring buffer (when one is
  attached): the black box, readable *before* the crash.

Pure stdlib ``http.server`` on daemon threads — no new dependencies, no
cost until a request arrives, and the GIL-bound handler only ever reads
snapshots, so a scrape cannot stall a round.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class StatusBoard:
    """Thread-safe last-write-wins status dict — the producer side of
    ``/statusz``. Round loops ``update(round=..., phase=...)`` as they move
    through phases; ``snapshot()`` is what the endpoint (or any poller)
    reads. One dict merge under a lock per update: sub-µs, cheap enough to
    run unconditionally (measured: ``bench.py --obs-plane-microbench``)."""

    def __init__(self, **initial):
        self._data = dict(initial)
        self._lock = threading.Lock()

    def update(self, **fields) -> None:
        with self._lock:
            self._data.update(fields)
            self._data["updated_at"] = time.time()

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._data)


class _Handler(BaseHTTPRequestHandler):
    # Set by ObsServer on the server object; read via self.server.
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence per-request stderr lines
        return

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                health_fn = self.server.obs_health_fn
                if health_fn is None:
                    self._send(200, b"ok\n", "text/plain; charset=utf-8")
                else:
                    ok, reason = health_fn()
                    self._send(
                        200 if ok else 503,
                        (reason + "\n").encode(),
                        "text/plain; charset=utf-8",
                    )
            elif path == "/metrics":
                registry = self.server.obs_registry
                if registry is None:
                    self._send(404, b"no metrics registry\n", "text/plain")
                    return
                from fedtpu.obs.exporters import prometheus_text

                self._send(
                    200, prometheus_text(registry).encode(),
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            elif path == "/statusz":
                status_fn = self.server.obs_status_fn
                status = status_fn() if status_fn is not None else {}
                self._send(
                    200, (json.dumps(status) + "\n").encode(),
                    "application/json",
                )
            elif path == "/flightz":
                flight = self.server.obs_flight
                if flight is None:
                    self._send(404, b"no flight recorder\n", "text/plain")
                    return
                self._send(
                    200, (json.dumps(flight.snapshot()) + "\n").encode(),
                    "application/json",
                )
            else:
                self._send(404, b"have: /metrics /healthz /statusz "
                                b"/flightz\n", "text/plain")
        except BrokenPipeError:
            pass
        except Exception as exc:  # a scrape must never kill the process
            try:
                self._send(500, f"{exc}\n".encode(), "text/plain")
            except Exception:
                pass


class ObsServer:
    """Owns the listening socket + serve thread. ``port=0`` binds an
    ephemeral port (tests); ``port`` after :meth:`start` is the real one."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        status_fn: Optional[Callable[[], dict]] = None,
        flight=None,
        health_fn: Optional[Callable[[], tuple]] = None,
    ):
        """``health_fn``: () -> (ok, reason) — the owning component's
        honest liveness verdict (e.g. ``PrimaryServer.health``); None
        keeps the legacy unconditional 200."""
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs_registry = registry
        self._httpd.obs_status_fn = status_fn
        self._httpd.obs_flight = flight
        self._httpd.obs_health_fn = health_fn
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def start(self) -> "ObsServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()
