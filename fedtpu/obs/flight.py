"""Crash flight recorder: a bounded black box that survives the failure.

PR 3's exporters write at *orderly* exit — exactly the moment a crash,
watchdog promotion, or SIGKILLed straggler never reaches. The
:class:`FlightRecorder` keeps the last ``capacity`` structured events in a
ring buffer (recent round completions, span completions via the tracer
sink, FT transitions, warning+ log lines) and dumps the whole ring as JSON
the moment something goes wrong:

- **unhandled exception** (``sys.excepthook`` + ``threading.excepthook``,
  chained to the previous hooks),
- **SIGUSR1** (operator-triggered snapshot of a live process — the
  non-destructive "what is it doing" probe, docs/OPERATIONS.md),
- **every failover promote/demote** (wired through
  :class:`fedtpu.ft.FailoverStateMachine`), because the seconds before a
  role flip are precisely the telemetry the dead primary took with it.

Dumps land at ``artifacts/flightrecorder-<role>-<pid>.json`` (atomic
rename; each dump overwrites the previous for that process — the newest
black box is the one that matters). Recording is a deque append under a
lock (~sub-µs); the ring costs memory proportional to ``capacity`` only.

The dump path is best-effort re-entrant: a signal arriving while the
recording lock is held must not deadlock the handler, so ``dump`` takes
the lock with a timeout and falls back to a lock-free copy.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import List, Optional


def _sanitize(role: str) -> str:
    return "".join(c if c.isalnum() or c in "-_." else "-" for c in role)


class _FlightLogHandler(logging.Handler):
    """Feeds warning+ log records (FT transitions, straggler warnings,
    RpcError marks) into the ring."""

    def __init__(self, recorder: "FlightRecorder"):
        super().__init__(level=logging.WARNING)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.record(
                "log",
                logger=record.name,
                level=record.levelname,
                message=record.getMessage(),
            )
        except Exception:
            pass


class FlightRecorder:
    def __init__(
        self,
        capacity: int = 512,
        role: str = "",
        artifacts_dir: str = "artifacts",
    ):
        self.role = role or f"pid{os.getpid()}"
        self.artifacts_dir = artifacts_dir
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._created = time.time()
        self._dump_count = 0
        self._installed = False
        self._log_handler: Optional[_FlightLogHandler] = None
        self._prev_excepthook = None
        self._prev_threading_excepthook = None
        self._prev_signal = None

    # ------------------------------------------------------------ recording
    def record(self, kind: str, **fields) -> None:
        event = {"t": round(time.time(), 6), "kind": kind}
        event.update(fields)
        with self._lock:
            self._events.append(event)

    def record_span(self, chrome_event: dict) -> None:
        """Tracer sink (:attr:`fedtpu.obs.trace.SpanTracer.sink`): keep the
        completed span's name/duration/args, drop the viewer fields."""
        self.record(
            "span",
            name=chrome_event.get("name"),
            dur_us=chrome_event.get("dur"),
            args=chrome_event.get("args", {}),
        )

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    # ------------------------------------------------------------- dumping
    def dump_path(self) -> str:
        return os.path.join(
            self.artifacts_dir,
            f"flightrecorder-{_sanitize(self.role)}-{os.getpid()}.json",
        )

    def dump(self, reason: str, path: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring + context to ``path`` (default
        :meth:`dump_path`); returns the path, or None if even the
        best-effort write failed (a dump must never raise into a crashing
        process)."""
        got_lock = self._lock.acquire(timeout=0.5)
        try:
            try:
                events = list(self._events)
            except RuntimeError:  # mutated during lock-free iteration
                events = []
        finally:
            if got_lock:
                self._lock.release()
        doc = {
            "reason": reason,
            "role": self.role,
            "pid": os.getpid(),
            "dumped_at": time.time(),
            "recorder_started_at": self._created,
            "dump_count": self._dump_count + 1,
            "num_events": len(events),
            "events": events,
        }
        if extra:
            doc.update(extra)
        path = path or self.dump_path()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, default=str)
            os.replace(tmp, path)
        except OSError:
            return None
        self._dump_count += 1
        return path

    # ------------------------------------------------------ process hooks
    def install(
        self,
        signum: Optional[int] = signal.SIGUSR1,
        logger_names=("fedtpu", "fedtpu.ft", "fedtpu.federation"),
    ) -> "FlightRecorder":
        """Arm the process-wide dump triggers (CLI entrypoints call this;
        in-process/library users usually wire components directly):

        - chain ``sys.excepthook`` / ``threading.excepthook`` to dump with
          the traceback before the previous hook runs;
        - ``signum`` (default SIGUSR1, None to skip; silently skipped off
          the main thread where Python forbids signal registration) dumps
          without exiting;
        - attach a warning+ capture handler to ``logger_names``.
        """
        if self._installed:
            return self
        self._installed = True

        self._prev_excepthook = sys.excepthook

        def _excepthook(exc_type, exc, tb):
            self.record(
                "exception",
                type=exc_type.__name__,
                message=str(exc),
                traceback="".join(
                    traceback.format_exception(exc_type, exc, tb)
                )[-4000:],
            )
            self.dump(reason=f"unhandled:{exc_type.__name__}")
            if self._prev_excepthook is not None:
                self._prev_excepthook(exc_type, exc, tb)

        sys.excepthook = _excepthook

        self._prev_threading_excepthook = threading.excepthook

        def _thread_hook(hook_args):
            if hook_args.exc_type is not SystemExit:
                self.record(
                    "exception",
                    type=hook_args.exc_type.__name__,
                    message=str(hook_args.exc_value),
                    thread=getattr(hook_args.thread, "name", "?"),
                )
                self.dump(
                    reason=f"thread-unhandled:{hook_args.exc_type.__name__}"
                )
            if self._prev_threading_excepthook is not None:
                self._prev_threading_excepthook(hook_args)

        threading.excepthook = _thread_hook

        if signum is not None:
            try:
                self._prev_signal = (
                    signum, signal.signal(signum, self._on_signal)
                )
            except ValueError:  # not the main thread
                self._prev_signal = None

        for name in logger_names:
            if self._log_handler is None:
                self._log_handler = _FlightLogHandler(self)
            logging.getLogger(name).addHandler(self._log_handler)
        self._log_loggers = list(logger_names)
        return self

    def _on_signal(self, signum, frame) -> None:
        self.dump(reason=f"signal:{signal.Signals(signum).name}")

    def uninstall(self) -> None:
        """Tests only: restore the hooks this instance installed."""
        if not self._installed:
            return
        self._installed = False
        if self._prev_excepthook is not None:
            sys.excepthook = self._prev_excepthook
        if self._prev_threading_excepthook is not None:
            threading.excepthook = self._prev_threading_excepthook
        if self._prev_signal is not None:
            signum, prev = self._prev_signal
            try:
                signal.signal(signum, prev)
            except ValueError:
                pass
        if self._log_handler is not None:
            for name in getattr(self, "_log_loggers", ()):
                logging.getLogger(name).removeHandler(self._log_handler)
