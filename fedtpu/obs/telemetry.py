"""The Telemetry facade: one object gating tracer + registry on a mode.

``FedConfig.telemetry`` selects how much the framework measures itself:

- ``"off"``   — nothing. ``span()`` returns a shared no-op, metric getters
  return shared no-op instruments. The per-round wire/phase accounting on
  round records stays (it is part of the round() API, and its thread-safe
  counters are a correctness fix, not telemetry).
- ``"basic"`` (default) — the metrics registry is live (counters, gauges,
  histograms; exportable as Prometheus text), no spans. Measured overhead:
  well under 1% of round wall time (``bench.py --telemetry-microbench``,
  artifacts/TELEMETRY_MICROBENCH.json).
- ``"trace"`` — basic plus the span tracer (Chrome-trace export, jax
  TraceAnnotation bridge). Spans cost ~a microsecond each; fine for
  diagnosis runs, off the default path.

Each engine/server owns ONE Telemetry instance (its registry is that
component's metric namespace); the FT helpers receive the owning
component's registry and fall back to the process-global one when
constructed standalone.
"""

from __future__ import annotations

from typing import Optional

from fedtpu.obs.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from fedtpu.obs.trace import NULL_SPAN, SpanTracer

TELEMETRY_MODES = ("off", "basic", "trace")


class _NullCounter:
    __slots__ = ()
    kind = "counter"
    value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        return None


class _NullGauge:
    __slots__ = ()
    kind = "gauge"
    value = 0.0

    def set(self, value: float) -> None:
        return None

    def inc(self, amount: float = 1.0) -> None:
        return None


class _NullHistogram:
    __slots__ = ()
    kind = "histogram"
    count = 0
    sum = 0.0

    def observe(self, value: float) -> None:
        return None


_NULL_COUNTER = _NullCounter()
_NULL_GAUGE = _NullGauge()
_NULL_HISTOGRAM = _NullHistogram()


def validate_telemetry_mode(mode: str) -> str:
    if mode not in TELEMETRY_MODES:
        raise ValueError(
            f"unknown telemetry mode {mode!r}; have off | basic | trace"
        )
    return mode


class Telemetry:
    """Mode-gated bundle of one :class:`MetricsRegistry` and (in ``trace``
    mode) one :class:`SpanTracer`."""

    def __init__(self, mode: str = "basic",
                 registry: Optional[MetricsRegistry] = None,
                 bridge_jax: Optional[bool] = None,
                 role: Optional[str] = None):
        self.mode = validate_telemetry_mode(mode)
        # Process/component identity for multi-process trace stitching and
        # the flight recorder's dump filenames: "primary", "backup",
        # "client:<addr>", "engine", ... Settable post-construction (the
        # components that own a Telemetry stamp it).
        self.role = role
        self.enabled = mode != "off"
        self.tracing = mode == "trace"
        # A registry exists even in off mode (so handing
        # ``telemetry.registry`` to the FT modules is unconditional); the
        # off gate lives in the instrument getters below.
        self.registry = registry if registry is not None else MetricsRegistry()
        # Bridge framework spans to jax.profiler.TraceAnnotation by default
        # whenever we trace at all — TraceAnnotation is a no-op-cheap
        # TraceMe outside an active profiler session.
        if bridge_jax is None:
            bridge_jax = self.tracing
        self.tracer = SpanTracer(bridge_jax=bridge_jax) if self.tracing else None

    # ------------------------------------------------------------- spans
    def span(self, name: str, parent=None, **args):
        if self.tracer is None:
            return NULL_SPAN
        return self.tracer.span(name, parent=parent, **args)

    def trace_events(self):
        return self.tracer.events() if self.tracer is not None else []

    def export_trace(self, path: str) -> None:
        """Write the collected spans as a Perfetto-loadable Chrome trace.
        No-op below ``trace`` mode (nothing was collected). The dump's
        ``metadata`` block (trace id, role, pid, wall_start) is what
        ``tools/trace_merge.py`` keys on when stitching per-process files
        into one federation timeline."""
        if self.tracer is None:
            return
        import os

        from fedtpu.obs.trace import write_chrome_trace

        write_chrome_trace(
            self.tracer.events(), path,
            metadata={
                "trace_id": self.tracer.trace_id,
                "role": self.role or f"pid{os.getpid()}",
                "pid": os.getpid(),
                "wall_start": self.tracer.wall_start,
            },
        )

    # ----------------------------------------------------------- metrics
    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        if not self.enabled:
            return _NULL_COUNTER  # type: ignore[return-value]
        return self.registry.counter(name, help, labels)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        if not self.enabled:
            return _NULL_GAUGE  # type: ignore[return-value]
        return self.registry.gauge(name, help, labels)

    def histogram(self, name: str, help: str = "", labels=None,
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        if not self.enabled:
            return _NULL_HISTOGRAM  # type: ignore[return-value]
        return self.registry.histogram(name, help, labels, buckets=buckets)

    def export_prometheus(self, path: str) -> None:
        from fedtpu.obs.exporters import write_prometheus

        write_prometheus(self.registry, path)


# Shared disabled instance for components whose config has no telemetry
# field (or that predate one) — all calls are no-ops.
NULL_TELEMETRY = Telemetry("off")
