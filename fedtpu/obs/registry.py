"""Thread-safe metrics registry: counters, gauges, histograms.

Replaces the ad-hoc measurement state that used to live scattered across the
round loop — mutable-list byte accumulators shared between thread-pool
workers without a lock (the ``bytes_up = [0]`` pattern the PR-3 tentpole
retires), closure variables in codecs, and silent state flips in the FT
modules — with one typed, lockable home. The shape follows the Prometheus
client-library data model (counter / gauge / histogram, optional label
sets) because that is the schema :func:`prometheus_text` renders, but the
implementation is deliberately dependency-free: plain ``threading.Lock``
per metric, no background threads, no jax import (the FT modules must stay
importable without initialising a backend).

Cost model: one ``inc``/``observe`` is a lock acquire + a float add —
tens of nanoseconds. That is why the per-round *wire accounting* in
:meth:`fedtpu.transport.federation.PrimaryServer.round` uses bare
:class:`Counter` objects unconditionally (correctness under threads is not
a telemetry feature), while the *cumulative* registry is only touched when
``FedConfig.telemetry != "off"``.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Tuple

# Default histogram buckets, in seconds: spans phase timings from sub-ms
# decode work to multi-minute straggler waits. Cumulative ("le") rendering
# happens at export time; observation stores per-bucket counts.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonically increasing, thread-safe float counter.

    Also usable standalone (outside any registry) as the safe replacement
    for the mutable-list accumulator pattern: workers ``inc()`` without
    external locking, the owner reads ``.value`` after the join.
    """

    kind = "counter"

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Thread-safe settable value (last-write-wins)."""

    kind = "gauge"

    def __init__(self) -> None:
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram: per-bucket counts + sum + count + min/max."""

    kind = "histogram"

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        bs = sorted(float(b) for b in buckets)
        if not bs:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets: Tuple[float, ...] = tuple(bs)
        self._counts = [0] * (len(bs) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if value <= b:
                    break
            else:
                i = len(self.buckets)
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            self._min = min(self._min, value)
            self._max = max(self._max, value)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        """{"count", "sum", "min", "max", "buckets": {le: cumulative}}."""
        with self._lock:
            cum, out = 0, {}
            for b, c in zip(self.buckets, self._counts):
                cum += c
                out[b] = cum
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "buckets": out,
            }


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple[Tuple[str, str], ...]:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Get-or-create metric store keyed by ``(name, labels)``.

    Creation is locked; the returned metric objects carry their own locks,
    so hot-path ``inc``/``observe`` calls never contend on the registry.
    A name is bound to ONE kind — asking for ``counter("x")`` after
    ``gauge("x")`` raises instead of silently aliasing.
    """

    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], object] = {}
        self._help: Dict[str, str] = {}
        self._kinds: Dict[str, str] = {}
        self._lock = threading.Lock()

    def _get(self, cls, name: str, help: str, labels, **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            existing_kind = self._kinds.get(name)
            if existing_kind is not None and existing_kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{existing_kind}, cannot re-register as {cls.kind}"
                )
            metric = self._metrics.get(key)
            if metric is None:
                metric = cls(**kwargs)
                self._metrics[key] = metric
                self._kinds[name] = cls.kind
                if help:
                    self._help[name] = help
            return metric

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def snapshot(self) -> dict:
        """Plain-dict dump: {name: [{"labels": {...}, ...metric fields}]}."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, List[dict]] = {}
        for (name, lkey), metric in sorted(items, key=lambda kv: kv[0]):
            entry: dict = {"labels": dict(lkey), "kind": metric.kind}
            if isinstance(metric, Histogram):
                entry.update(metric.snapshot())
            else:
                entry["value"] = metric.value
            out.setdefault(name, []).append(entry)
        return out

    def help_text(self, name: str) -> str:
        with self._lock:
            return self._help.get(name, "")


_GLOBAL = MetricsRegistry()


def get_global_registry() -> MetricsRegistry:
    """Process-wide default registry — the sink for modules that have no
    natural owner to receive one (standalone FT machinery in tests, tools).
    Components with a config (engines, servers) use their own
    :class:`~fedtpu.obs.telemetry.Telemetry` registry instead."""
    return _GLOBAL
