"""Process-level resource observations for the leak gauges.

The long-haul soaks (``tools/chaos_soak.py --churn``) assert a FLAT memory
profile over a thousand rounds of continuous churn — which needs a gauge of
the process's *current* resident set, sampled per round. ``getrusage``'s
``ru_maxrss`` cannot serve: it is a high-water mark, monotone by
definition, so a leak check against it would never see a plateau. On Linux
the authoritative current value is ``VmRSS`` in ``/proc/self/status``;
elsewhere we fall back to the high-water mark (better than nothing, and the
soaks run on Linux).
"""

from __future__ import annotations

import os
import resource
import sys


def process_rss_bytes() -> int:
    """Current resident-set size of this process in bytes (best effort:
    0 when no source is readable)."""
    try:
        with open("/proc/self/status", encoding="ascii", errors="replace") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024  # kB
    except OSError:
        pass
    try:
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # Linux reports kB, macOS bytes — only reached off-Linux.
        return peak if sys.platform == "darwin" else peak * 1024
    except Exception:
        return 0


def process_fd_count() -> int:
    """Open file descriptors (a second leak axis: channels/sockets under
    churn). 0 when /proc is unavailable."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0
