"""SimFederation — the massive-cohort simulation engine.

Runs ``population`` simulated clients through the resident engine's
fixed-size device buffers: each round a seeded sampler
(:mod:`fedtpu.sim.samplers`) draws a ``cohort`` (= ``FedConfig.num_clients``)
from the :class:`~fedtpu.sim.population.Population`, the cohort's
assignment rows are gathered into the engine's ``[cohort, shard_len]``
inputs (:meth:`fedtpu.core.engine.Federation.set_assignment` — a values-only
swap, no recompile), and the round runs through the UNCHANGED jitted
round/fused-scan programs. Device memory is O(cohort): the only
O(population) objects are host numpy tables.

Slot semantics
--------------
A device slot is a *seat*, not a client. When a seat is handed to a
different client than last round, its heavy per-seat state — optimizer
momentum, compressor residuals, PRNG key — is **reset** (jitted, donated:
one fused ``where`` over the seat axis), because a cross-device client
starts each cohort appearance fresh; what persists per *client* lives in
the Population (last-seen loss, availability, sampling bookkeeping). When
``population == cohort`` under the uniform sampler the seat map is the
identity every round, the reset fast-path never fires, and the sim engine
is **bit-identical** to a plain :class:`Federation` with the same config
(the parity pin in ``tests/test_sim.py``).

Fused blocks (:meth:`run_on_device`) sample ONE cohort per block — the
cohort is a program input, so re-sampling mid-scan would mean shipping
``[rounds, cohort, shard_len]`` assignments; per-block sampling keeps the
H2D O(cohort) and matches how cross-device systems amortise cohort setup.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import RoundConfig, validate_sim_config
from fedtpu.core.engine import Federation
from fedtpu.sim import scenario as scenario_lib
from fedtpu.sim.population import Population
from fedtpu.sim.samplers import make_sampler


def _default_scenario(cfg: RoundConfig) -> str:
    """Scenario spec when ``sim.scenario`` is empty: the existing
    DataConfig partitioner, verbatim."""
    if cfg.data.partition == "dirichlet":
        return f"dirichlet:alpha={cfg.data.dirichlet_alpha}"
    return cfg.data.partition  # iid | round_robin


class SimFederation(Federation):
    """Population/cohort-decoupled simulated federation (see module doc)."""

    def __init__(
        self,
        cfg: RoundConfig,
        seed: int = 0,
        compressor=None,
        data: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ):
        validate_sim_config(cfg.fed)
        sim = cfg.fed.sim
        if sim.population <= 0:
            raise ValueError(
                "SimFederation needs FedConfig.sim.population > 0 "
                "(use Federation for the resident path)"
            )
        # The per-round cohort re-gather swaps assignment VALUES into the
        # jitted program; only the gather layout keeps the assignment as a
        # program input (presharded bakes it into the uploaded data rows).
        if cfg.data.device_layout != "gather":
            cfg = dataclasses.replace(
                cfg, data=dataclasses.replace(cfg.data, device_layout="gather")
            )
        if data is None:
            from fedtpu.data import data_source, load

            images, labels = load(
                cfg.data.dataset, "train", seed=cfg.data.seed,
                num=cfg.data.num_examples,
            )
            src = data_source(cfg.data.dataset, "train")
        else:
            images, labels = data
            src = "caller"

        spec = sim.scenario or _default_scenario(cfg)
        pop_idx, pop_mask = scenario_lib.make_partition(
            spec, labels, sim.population, seed=cfg.data.seed,
            batch_size=cfg.data.batch_size,
        )
        # malicious_fraction axis (fedtpu.sim.adversary): the seeded
        # attacker set lives at POPULATION scope — whichever cohort a
        # malicious client lands in, it attacks there. label_flip poisons
        # the attackers' example rows once, host-side (the population
        # partition is a disjoint cover); delta-level kinds get their
        # per-SEAT mask re-derived at every cohort install below.
        self._pop_attackers = None
        if sim.malicious_fraction > 0:
            from fedtpu.sim import adversary

            plan = adversary.parse_attack(sim.attack)
            self._pop_attackers = adversary.attacker_mask(
                sim.population, sim.malicious_fraction,
                cfg.data.seed + sim.seed + plan.seed,
            )
            if plan.kind == "label_flip":
                labels = adversary.flip_labels(
                    labels, pop_idx, pop_mask, self._pop_attackers,
                    plan.label_offset, cfg.num_classes,
                )
        self.population = Population(
            pop_idx, pop_mask, seed=cfg.data.seed + sim.seed,
            availability=sim.availability, churn=sim.churn,
        )
        self.scenario_spec = spec
        self._sampler = make_sampler(
            sim.cohort_sampler, seed=cfg.data.seed + sim.seed,
            prior=None if sim.loss_prior < 0 else sim.loss_prior,
        )
        cohort = cfg.fed.num_clients
        # Seat map BEFORE the first install: the round-0 cohort, drawn now
        # so the engine's initial buffers are built over real rows.
        ids0, alive0 = self._sampler.sample(self.population, 0, cohort)
        super().__init__(
            cfg, seed=seed, compressor=compressor, data=(images, labels),
            assignment=self._cohort_assignment(ids0, alive0),
        )
        self._data_source = src  # not 'caller': we loaded it ourselves
        self.alive = alive0.copy()
        self._cohort_ids = ids0
        self._slot_ids = np.where(alive0, ids0, -1)
        self._cohort_round = 0  # round the current cohort was drawn for
        self.population.mark_sampled(ids0[alive0], 0)
        self._refresh_attack_seats(ids0, alive0)
        self._refresh_fn = None
        self._fresh_key_base = None
        self._hetero = self.population.heterogeneity_index(labels)
        self._set_sim_gauges()

    # ------------------------------------------------------------- installs
    def _cohort_assignment(self, ids, alive):
        """Cohort rows for the engine: padded-dead seats get an empty mask
        (no data -> no steps) on top of the dead ``alive`` flag."""
        idx, mask, _ = self.population.gather(ids)
        return idx, mask & alive[:, None]

    def _refresh_attack_seats(self, ids: np.ndarray, alive: np.ndarray):
        """Per-seat attacker mask for the installed cohort (delta-level
        attack kinds only — label_flip already poisoned the data)."""
        if (self._pop_attackers is None or self._attack_plan is None
                or self._attack_plan.kind == "label_flip"):
            return
        self._attack_seats = (
            self._pop_attackers[ids] & alive
        ).astype(np.float32)

    def _set_sim_gauges(self) -> None:
        tel = self.telemetry
        if self._pop_attackers is not None:
            tel.gauge(
                "fedtpu_sim_malicious_in_cohort",
                "seeded malicious clients live in the current cohort",
            ).set(int(
                (self._pop_attackers[self._cohort_ids] & self.alive).sum()
            ))
        tel.gauge(
            "fedtpu_sim_population",
            "simulated population size (host-resident clients)",
        ).set(self.population.size)
        tel.gauge(
            "fedtpu_sim_cohort_size",
            "live clients in the current cohort (dead-padded seats excluded)",
        ).set(int(self.alive.sum()))
        tel.gauge(
            "fedtpu_sim_heterogeneity_index",
            "mean total-variation distance of client label distributions "
            "from the population's (0 = IID)",
        ).set(self._hetero)
        tel.gauge(
            "fedtpu_sim_never_sampled",
            "population clients never yet drawn into a cohort",
        ).set(self.population.never_sampled())

    def _fresh_keys(self, ids: np.ndarray):
        """Per-CLIENT PRNG keys for fresh seats: ``fold_in(base, client_id)``
        — a client's stream is its identity, independent of which seat it
        lands in (the round step folds the round index on top)."""
        if self._fresh_key_base is None:
            self._fresh_key_base = jax.random.PRNGKey(
                (self.cfg.data.seed + self.cfg.fed.sim.seed) ^ 0x51B0D5
            )
        return jax.vmap(lambda i: jax.random.fold_in(self._fresh_key_base, i))(
            np.asarray(ids, np.uint32)
        )

    def _refresh(self, fresh: np.ndarray, ids: np.ndarray) -> None:
        """Reset the heavy per-seat state of reassigned seats (donated jit:
        one fused where over the seat axis) and install the population's
        last-seen losses as the engine-side observation vector."""
        if self._refresh_fn is None:
            @partial(jax.jit, donate_argnums=(0,))
            def refresh(state, fresh_m, new_rng, new_loss):
                def reset(x):
                    m = fresh_m.reshape((-1,) + (1,) * (x.ndim - 1))
                    return jnp.where(m, jnp.zeros_like(x), x)

                return state._replace(
                    opt_state=jax.tree.map(reset, state.opt_state),
                    comp_state=jax.tree.map(reset, state.comp_state),
                    client_rng=jnp.where(
                        fresh_m[:, None], new_rng, state.client_rng
                    ),
                    last_client_loss=new_loss,
                )

            self._refresh_fn = refresh
        self._state = self._refresh_fn(
            self._state,
            jnp.asarray(fresh),
            self._fresh_keys(ids),
            jnp.asarray(
                self.population.last_seen_loss[ids], jnp.float32
            ),
        )

    def _install_cohort(self, round_idx: int) -> None:
        """Draw + install the cohort for ``round_idx`` (no-op if already
        installed for it — `step` inside `run` calls land here once)."""
        if round_idx == self._cohort_round:
            return
        with self.telemetry.span("cohort_sample", round=round_idx):
            ids, alive = self._sampler.sample(
                self.population, round_idx, self.cfg.fed.num_clients
            )
            self.population.mark_sampled(ids[alive], round_idx)
            slot_ids = np.where(alive, ids, -1)
            fresh = slot_ids != self._slot_ids
            self._cohort_ids, self._cohort_round = ids, round_idx
            self.alive = alive.copy()
            self._refresh_attack_seats(ids, alive)
            if fresh.any():
                idx, mask = self._cohort_assignment(ids, alive)
                _, _, w = self.population.gather(ids)
                self.set_assignment(idx, mask, weights=w * alive)
                self._refresh(fresh, ids)
                self._slot_ids = slot_ids
            # else: identity re-draw — state, assignment and weights are
            # already exactly this cohort's (the population==cohort parity
            # fast path: device state is left byte-for-byte untouched).
        self._set_sim_gauges()

    def _observe_back(self) -> None:
        """Write the block's on-device loss observations into the
        population table (finite values only — dead/padded seats keep
        their previous observation or NaN)."""
        losses = np.asarray(self._state.last_client_loss)
        live = self.alive
        self.population.observe_loss(self._cohort_ids[live], losses[live])

    # --------------------------------------------------------------- rounds
    def step(self, batch=None):
        if batch is None:
            self._install_cohort(self._round_number())
        m = super().step(batch)
        if batch is None:
            self._observe_back()
        return m

    def run_on_device(self, num_rounds: int):
        # ONE cohort per fused block (see module docstring).
        self._install_cohort(self._round_number())
        m = super().run_on_device(num_rounds)
        self._observe_back()
        return m

    # ----------------------------------------------------------------- eval
    def cohort_label_hist(self) -> np.ndarray:
        """Training-label histogram of the current cohort's live shards."""
        idx, mask, _ = self.population.gather(self._cohort_ids)
        mask = mask & self.alive[:, None]
        labels = np.asarray(self.labels)
        picked = labels[idx[mask]] if mask.any() else np.zeros(0, np.int64)
        return np.bincount(picked, minlength=int(labels.max()) + 1)

    def evaluate_cohort(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        num: Optional[int] = None,
        seed: int = 0,
    ):
        """Per-cohort eval slice: evaluate on a test subset whose label
        mixture matches the CURRENT cohort's training mixture
        (:func:`fedtpu.sim.scenario.cohort_eval_indices`) — under label or
        quantity skew this measures the model on the slice of the task this
        cohort represents, which the global test average hides."""
        num = num or min(len(labels), 1000)
        sel = scenario_lib.cohort_eval_indices(
            labels, self.cohort_label_hist(), num,
            seed=self.cfg.data.seed + seed,
        )
        return self.evaluate(
            np.asarray(images)[sel], np.asarray(labels)[sel]
        )

    # ---------------------------------------------------------------- intro
    def status_snapshot(self) -> dict:
        snap = super().status_snapshot()
        snap["sim"] = dict(
            self.population.stats(),
            cohort_round=self._cohort_round,
            cohort_live=int(self.alive.sum()),
            scenario=self.scenario_spec,
            heterogeneity_index=round(self._hetero, 4),
        )
        return snap
