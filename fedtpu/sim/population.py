"""The :class:`Population` — per-client truth for ``population >> cohort``.

The resident engine (`fedtpu.core.engine.Federation`) sizes every per-client
buffer — momentum, error-feedback residuals, PRNG keys, the flat
``[clients, P]`` delta buffer — to ``cfg.fed.num_clients``, so simulating N
clients used to mean N live device states. This module holds what must
survive *between* a client's cohort appearances as lightweight **host**
state instead: the dataset assignment, last-seen training loss,
availability, and sampling bookkeeping — O(population) numpy rows, while
the device keeps O(cohort) (FedJAX's population/cohort split,
arXiv:2108.02117).

What deliberately does NOT persist per population client: optimizer
momentum and compressor residuals. In the cross-device regime a sampled
client starts its local run fresh (it may not reappear for thousands of
rounds); the engine's per-slot heavy state is therefore *reset* whenever a
slot is handed to a different client (`fedtpu.sim.engine.SimFederation`).
When ``population == cohort`` the slot map is the identity, nothing resets,
and the resident-engine semantics (and bits) are preserved exactly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from fedtpu.sim.sampling import round_rng

# Salt for the availability trace's RNG stream (decorrelates it from the
# cohort sampler's draws over the same seed/round).
_AVAIL_SALT = 17


class Population:
    """Host-resident per-client state for a simulated client population.

    ``idx`` / ``mask``: the padded ``[population, shard_len]`` dataset
    assignment from :mod:`fedtpu.data.partition` /
    :mod:`fedtpu.sim.scenario`. Per-client tables (all ``[population]``):

    - ``last_seen_loss`` — f32, NaN until the client first trains; updated
      from the engine's on-device observations after each round/block.
      Feeds the loss-proportional cohort sampler through
      :func:`fedtpu.sim.sampling.loss_weights` (optimistic prior for the
      never-sampled).
    - ``last_sampled_round`` — int64, -1 until first sampled.
    - ``times_sampled`` — int64 draw counter (`never_sampled()` is the
      exploration-debt gauge the obs plane exports).
    - availability — a seeded two-state Markov trace (`available_at`):
      P(up->down) = ``churn`` per round, P(down->up) chosen so the
      stationary up-fraction is ``availability``. ``churn=0`` freezes the
      initial Bernoulli(availability) draw; ``availability=1`` means always
      up. Deterministic in (seed, round): replaying a run replays its
      churn trace.
    """

    def __init__(
        self,
        idx: np.ndarray,
        mask: np.ndarray,
        *,
        seed: int = 0,
        availability: float = 1.0,
        churn: float = 0.0,
    ):
        if not 0.0 < availability <= 1.0:
            raise ValueError(
                f"availability must be in (0, 1], got {availability}"
            )
        if not 0.0 <= churn <= 1.0:
            raise ValueError(f"churn must be in [0, 1], got {churn}")
        self.idx = np.asarray(idx, np.int32)
        self.mask = np.asarray(mask, bool)
        if self.idx.shape != self.mask.shape or self.idx.ndim != 2:
            raise ValueError(
                f"idx/mask must be matching [population, shard_len] arrays, "
                f"got {self.idx.shape} vs {self.mask.shape}"
            )
        self.size = self.idx.shape[0]
        self.sizes = self.mask.sum(axis=1).astype(np.int64)
        self.seed = int(seed)
        self.availability = float(availability)
        self.churn = float(churn)
        n = self.size
        self.last_seen_loss = np.full((n,), np.nan, np.float32)
        self.last_sampled_round = np.full((n,), -1, np.int64)
        self.times_sampled = np.zeros((n,), np.int64)
        # Availability trace state, advanced lazily round-by-round.
        init_rng = round_rng(self.seed, -1, salt=_AVAIL_SALT)
        self._avail = (
            init_rng.random(n) < self.availability
            if self.availability < 1.0
            else np.ones((n,), bool)
        )
        self._avail_round = -1
        # Membership mask: True = current member. The availability trace
        # models TRANSIENT presence (a member that happens to be offline);
        # membership models the roster itself — evicted clients are never
        # sampled however their availability trace rolls, and mid-run
        # admits (`admit`) grow the population without touching the
        # engine's fixed cohort seats (the set_assignment values-only swap
        # maps whatever ids the sampler draws onto them).
        self._member = np.ones((n,), bool)

    # ---------------------------------------------------------- membership
    def admit(self, idx_row: np.ndarray, mask_row: np.ndarray) -> int:
        """Admit a NEW client mid-run: append its padded dataset-assignment
        row (same ``shard_len`` as the population's; shorter rows are
        zero-padded) and fresh bookkeeping. Returns the new client id —
        immediately eligible for cohort sampling."""
        shard_len = self.idx.shape[1]
        idx_row = np.asarray(idx_row, np.int32).reshape(-1)
        mask_row = np.asarray(mask_row, bool).reshape(-1)
        if idx_row.shape != mask_row.shape:
            raise ValueError("admit: idx/mask rows must match")
        if len(idx_row) > shard_len:
            raise ValueError(
                f"admit: shard of {len(idx_row)} exceeds the population's "
                f"shard_len {shard_len}"
            )
        pad = shard_len - len(idx_row)
        if pad:
            idx_row = np.concatenate([idx_row, np.zeros((pad,), np.int32)])
            mask_row = np.concatenate([mask_row, np.zeros((pad,), bool)])
        cid = self.size
        self.idx = np.concatenate([self.idx, idx_row[None]])
        self.mask = np.concatenate([self.mask, mask_row[None]])
        self.sizes = np.concatenate(
            [self.sizes, [int(mask_row.sum())]]
        ).astype(np.int64)
        self.last_seen_loss = np.concatenate(
            [self.last_seen_loss, [np.nan]]
        ).astype(np.float32)
        self.last_sampled_round = np.concatenate(
            [self.last_sampled_round, [-1]]
        ).astype(np.int64)
        self.times_sampled = np.concatenate(
            [self.times_sampled, [0]]
        ).astype(np.int64)
        self._avail = np.concatenate([self._avail, [True]])
        self._member = np.concatenate([self._member, [True]])
        self.size += 1
        return cid

    def evict(self, client_id: int) -> None:
        """Remove a client from the roster (its row and bookkeeping stay,
        so a later :meth:`readmit` returns it stale — with its last-seen
        loss — rather than fresh)."""
        self._member[int(client_id)] = False

    def readmit(self, client_id: int) -> None:
        """A stale rejoin: the client re-enters the roster with the
        bookkeeping it left with."""
        self._member[int(client_id)] = True

    def members(self) -> np.ndarray:
        return self._member.copy()

    # ------------------------------------------------------------ sampling
    def available_at(self, round_idx: int) -> np.ndarray:
        """The ``[population]`` availability mask for a round (advancing the
        Markov trace as needed; rounds may only move forward). Non-members
        are never available, whatever their trace state."""
        if self.churn <= 0.0:
            # No dynamics: the initial draw holds at every round.
            return self._avail & self._member
        if round_idx < self._avail_round:
            raise ValueError(
                f"availability trace cannot rewind: at round "
                f"{self._avail_round}, asked for {round_idx}"
            )
        a, c = self.availability, self.churn
        # Stationarity: up-fraction a is preserved when
        # a * P(up->down) == (1 - a) * P(down->up).
        p_up = min(1.0, c * a / max(1.0 - a, 1e-9)) if a < 1.0 else 1.0
        while self._avail_round < round_idx:
            self._avail_round += 1
            rng = round_rng(self.seed, self._avail_round, salt=_AVAIL_SALT)
            u = rng.random(self.size)
            self._avail = np.where(self._avail, u >= c, u < p_up)
        return self._avail & self._member

    def mark_sampled(self, client_ids: np.ndarray, round_idx: int) -> None:
        ids = np.asarray(client_ids, np.int64)
        self.times_sampled[ids] += 1
        self.last_sampled_round[ids] = round_idx

    def observe_loss(self, client_ids: np.ndarray, losses: np.ndarray) -> None:
        """Record fresh loss observations (non-finite entries are skipped —
        a slot that never actually trained must not write a stale value)."""
        ids = np.asarray(client_ids, np.int64)
        vals = np.asarray(losses, np.float32)
        ok = np.isfinite(vals)
        self.last_seen_loss[ids[ok]] = vals[ok]

    def never_sampled(self) -> int:
        """How many clients have never been in a cohort (exploration debt)."""
        return int(np.sum(self.times_sampled == 0))

    # -------------------------------------------------------------- gather
    def gather(
        self, client_ids: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Cohort-shaped ``(idx, mask, weights)`` rows for the engine's
        fixed-size buffers — the O(cohort) device view of the population."""
        ids = np.asarray(client_ids, np.int64)
        return (
            self.idx[ids],
            self.mask[ids],
            self.sizes[ids].astype(np.float32),
        )

    # ------------------------------------------------------------- metrics
    def heterogeneity_index(self, labels: np.ndarray) -> float:
        """Label-distribution heterogeneity in ``[0, 1]``: the mean total
        variation distance between each (non-empty) client's label
        distribution and the population's. 0 for IID splits, approaching 1
        for pathological single-class shards — the one-number scenario
        summary exported as ``fedtpu_sim_heterogeneity_index``."""
        labels = np.asarray(labels)
        num_classes = int(labels.max()) + 1
        global_hist = np.bincount(labels, minlength=num_classes).astype(
            np.float64
        )
        global_p = global_hist / max(global_hist.sum(), 1.0)
        # Vectorized per-client histograms: one bincount over
        # client*num_classes + label for the valid (client, example) pairs.
        owners = np.repeat(np.arange(self.size), self.idx.shape[1]).reshape(
            self.idx.shape
        )
        own_labels = labels[self.idx]
        flat = (owners * num_classes + own_labels)[self.mask]
        hists = np.bincount(
            flat, minlength=self.size * num_classes
        ).reshape(self.size, num_classes).astype(np.float64)
        totals = hists.sum(axis=1)
        nonempty = totals > 0
        if not nonempty.any():
            return 0.0
        p = hists[nonempty] / totals[nonempty, None]
        tv = 0.5 * np.abs(p - global_p[None, :]).sum(axis=1)
        return float(tv.mean())

    def stats(self) -> dict:
        """Snapshot for status boards / artifacts."""
        return {
            "population": self.size,
            "members": int(self._member.sum()),
            "shard_len": int(self.idx.shape[1]),
            "examples": int(self.sizes.sum()),
            "min_shard": int(self.sizes.min()),
            "max_shard": int(self.sizes.max()),
            "never_sampled": self.never_sampled(),
            "availability": self.availability,
            "churn": self.churn,
        }
