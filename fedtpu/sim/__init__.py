"""fedtpu.sim — massive-cohort simulation (population/cohort decoupling).

The FedJAX-style (arXiv:2108.02117) simulation layer: a host-resident
:class:`Population` of ``N >> cohort`` clients, seeded per-round cohort
samplers, a composable non-IID scenario matrix, and
:class:`SimFederation`, which feeds sampled cohorts through the resident
engine's unchanged fused programs with O(cohort) device memory. See
``docs/SIMULATION.md``.
"""

from fedtpu.sim.engine import SimFederation
from fedtpu.sim.population import Population
from fedtpu.sim.samplers import (
    CohortSampler,
    LossProportionalSampler,
    UniformSampler,
    make_sampler,
)
from fedtpu.sim.sampling import loss_weights
from fedtpu.sim.scenario import (
    cohort_eval_indices,
    make_partition,
    parse_scenario,
)

__all__ = [
    "SimFederation",
    "Population",
    "CohortSampler",
    "UniformSampler",
    "LossProportionalSampler",
    "make_sampler",
    "loss_weights",
    "make_partition",
    "parse_scenario",
    "cohort_eval_indices",
]
