"""Shared sampling math for sparse loss observations.

One implementation of "probability weights from a last-seen-loss table"
serves every loss-proportional sampler in the framework: the engine's
``participation_sampling='loss'`` subset draw (`fedtpu.core.engine.
Federation._alive_for_round`) and the population-scale cohort sampler
(:mod:`fedtpu.sim.samplers`). The table is *sparse by construction* —
clients are observed only in rounds they actually train — so the rule for
missing observations is load-bearing: a never-yet-sampled client must draw
at an **optimistic prior** (the maximum observed loss by default), not at a
stale zero, or a small first cohort permanently starves the rest of the
population.

Numpy-only (host-side sampling decisions); no jax import.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def loss_weights(
    observed: np.ndarray, prior: Optional[float] = None
) -> Optional[np.ndarray]:
    """Normalised sampling probabilities from sparse loss observations.

    ``observed``: last-seen training losses, ``NaN`` where a client has
    never been observed. Returns ``None`` when *nothing* has been observed
    yet (callers fall back to uniform), else a probability vector where
    unobserved entries are filled with ``prior`` (default: the maximum
    observed loss — optimistic exploration) and every entry gets a small
    floor so an observed-at-zero client keeps a nonzero pick probability.

    This is bit-for-bit the fill/floor/normalise rule the engine's
    ``_alive_for_round`` applied inline before the sim subsystem existed,
    so refactored callers draw identical masks for identical inputs.
    """
    obs = np.asarray(observed, np.float64)
    if obs.size == 0 or np.all(np.isnan(obs)):
        return None
    fill = float(np.nanmax(obs)) if prior is None or prior < 0 else float(prior)
    w = np.where(np.isnan(obs), fill, obs)
    w = np.maximum(w, 0.0) + 1e-8
    return w / w.sum()


def round_rng(seed: int, round_idx: int, salt: int = 0) -> np.random.Generator:
    """The framework's seeded per-round generator rule (`seed * 7919 +
    round`), with an optional salt to decorrelate independent consumers
    (e.g. the cohort sampler vs the availability trace) of the same round.
    Centralised so every sampling surface derives draws the same way."""
    return np.random.default_rng((seed + salt * 1_000_003) * 7919 + round_idx)
