"""Scenario matrix: composable non-IID population generators.

A *scenario* is a small spec string that turns one flag into a full
heterogeneity regime (the FedJAX-style ablation surface the ROADMAP names):

    "iid"
    "dirichlet:alpha=0.1"
    "pathological:shards=2"
    "label_skew:classes=3"
    "quantity_skew:power=1.5"
    "dirichlet:alpha=0.5+quantity_skew:power=1.2"

Grammar: ``base[+modifier]...`` where each stage is
``name[:key=value[,key=value]...]``. Bases produce a
``[population, shard_len]`` assignment (built on
:mod:`fedtpu.data.partition` — ``iid`` and ``dirichlet`` ARE the existing
partitioners, so scenario specs compose with, not fork, that module);
modifiers rewrite an existing assignment. ``quantity_skew`` works as both:
as a base it carves the example permutation into power-law-sized shards, as
a modifier it subsamples each client's shard to a power-law size profile —
stacking label skew x quantity skew in one spec.

Everything is seeded and deterministic; all generators return the padded
``(idx, mask)`` convention so downstream static-shape machinery is
untouched.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from fedtpu.data import partition
from fedtpu.data.partition import _owner_to_shards

_BASES = ("iid", "dirichlet", "pathological", "label_skew", "quantity_skew",
          "round_robin")
_MODIFIERS = ("quantity_skew",)


def parse_scenario(spec: str) -> List[Tuple[str, Dict[str, float]]]:
    """``"a:k=v+b:k=v"`` -> ``[("a", {k: v}), ("b", {k: v})]`` (validated)."""
    stages: List[Tuple[str, Dict[str, float]]] = []
    for i, stage in enumerate(spec.strip().split("+")):
        stage = stage.strip()
        if not stage:
            raise ValueError(f"empty stage in scenario spec {spec!r}")
        name, _, argstr = stage.partition(":")
        name = name.strip()
        allowed = _BASES if i == 0 else _MODIFIERS
        if name not in allowed:
            raise ValueError(
                f"unknown scenario {'base' if i == 0 else 'modifier'} "
                f"{name!r} in {spec!r}; have "
                + " | ".join(allowed)
            )
        params: Dict[str, float] = {}
        if argstr:
            for kv in argstr.split(","):
                k, _, v = kv.partition("=")
                if not _ or not k.strip():
                    raise ValueError(
                        f"malformed option {kv!r} in scenario {spec!r} "
                        "(want key=value)"
                    )
                params[k.strip()] = float(v)
        stages.append((name, params))
    return stages


# ------------------------------------------------------------------ bases
def pathological(
    labels: np.ndarray,
    num_clients: int,
    shards_per_client: int = 2,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """The classic FedAvg "pathological non-IID" split: examples sorted by
    label, carved into ``num_clients * shards_per_client`` contiguous
    shards, each client dealt ``shards_per_client`` shards at random — so a
    client sees ~``shards_per_client`` classes (a shard can straddle one
    class boundary)."""
    labels = np.asarray(labels)
    if shards_per_client < 1:
        raise ValueError(f"shards_per_client must be >= 1, got {shards_per_client}")
    rng = np.random.default_rng(seed)
    by_label = np.argsort(labels, kind="stable")
    n_shards = num_clients * shards_per_client
    if n_shards > len(labels):
        raise ValueError(
            f"{n_shards} shards > {len(labels)} examples; lower "
            "shards_per_client or the population"
        )
    shard_of_pos = np.minimum(
        (np.arange(len(labels)) * n_shards) // len(labels), n_shards - 1
    )
    deal = rng.permutation(n_shards)  # shard s -> client deal[s] // spc
    owner = np.empty(len(labels), np.int64)
    owner[by_label] = deal[shard_of_pos] // shards_per_client
    return _owner_to_shards(owner, num_clients)


def label_skew(
    labels: np.ndarray,
    num_clients: int,
    classes_per_client: int = 2,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Each client holds examples from exactly ``classes_per_client``
    classes. Class sets come from a shuffled class deck (so every class has
    at least one holder whenever ``num_clients * classes_per_client >=
    num_classes``); each class's examples split evenly among its holders."""
    labels = np.asarray(labels)
    num_classes = int(labels.max()) + 1
    if not 1 <= classes_per_client <= num_classes:
        raise ValueError(
            f"classes_per_client must be in [1, {num_classes}], "
            f"got {classes_per_client}"
        )
    rng = np.random.default_rng(seed)
    # Deck of class ids, reshuffled per cycle, dealt classes_per_client per
    # client; a client re-draws duplicates from the running deck tail.
    need = num_clients * classes_per_client
    deck: List[int] = []
    while len(deck) < need + num_classes:
        deck.extend(rng.permutation(num_classes).tolist())
    holders: List[List[int]] = [[] for _ in range(num_classes)]
    pos = 0
    for c in range(num_clients):
        mine: List[int] = []
        while len(mine) < classes_per_client:
            k = deck[pos]
            pos += 1
            if k not in mine:
                mine.append(k)
        for k in mine:
            holders[k].append(c)
    owner = np.empty(len(labels), np.int64)
    for k in range(num_classes):
        idx_k = np.flatnonzero(labels == k)
        rng.shuffle(idx_k)
        who = holders[k] or [int(rng.integers(num_clients))]
        for j, part in enumerate(np.array_split(idx_k, len(who))):
            owner[part] = who[j]
    return _owner_to_shards(owner, num_clients)


def _power_profile(
    num_clients: int, power: float, rng: np.random.Generator
) -> np.ndarray:
    """Power-law size profile in (0, 1], randomly assigned to clients:
    client with rank r gets ``(r+1)^-power`` (rank 0 = the heavy head)."""
    if power < 0:
        raise ValueError(f"power must be >= 0, got {power}")
    prof = (np.arange(1, num_clients + 1, dtype=np.float64)) ** (-power)
    return prof[rng.permutation(num_clients)]


def quantity_skew(
    num_examples: int,
    num_clients: int,
    power: float = 1.5,
    min_size: int = 1,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Quantity-skew base: a random example permutation carved into
    power-law-sized shards — client sizes follow ``rank^-power`` (Zipf-ish
    heavy head, long tail of tiny clients), every client keeping at least
    ``min_size`` examples."""
    if num_clients * min_size > num_examples:
        raise ValueError(
            f"min_size={min_size} x {num_clients} clients > "
            f"{num_examples} examples"
        )
    rng = np.random.default_rng(seed)
    prof = _power_profile(num_clients, power, rng)
    spare = num_examples - num_clients * min_size
    extra = np.floor(prof / prof.sum() * spare).astype(np.int64)
    sizes = min_size + extra
    # Distribute the rounding remainder to the largest shares.
    for c in np.argsort(-prof)[: num_examples - int(sizes.sum())]:
        sizes[c] += 1
    perm = rng.permutation(num_examples)
    owner = np.empty(num_examples, np.int64)
    owner[perm] = np.repeat(np.arange(num_clients), sizes)
    return _owner_to_shards(owner, num_clients)


def apply_quantity_skew(
    idx: np.ndarray,
    mask: np.ndarray,
    power: float = 1.5,
    min_size: int = 1,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Quantity-skew modifier: keep each client's label mixture but
    subsample its shard to the power-law profile (client at rank r keeps
    ``~rank^-power`` of its examples, floored at ``min_size``) — composes
    label skew x quantity skew."""
    idx = np.asarray(idx)
    mask = np.asarray(mask, bool)
    rng = np.random.default_rng(seed)
    prof = _power_profile(idx.shape[0], power, rng)
    sizes = mask.sum(axis=1)
    keep = np.maximum(
        np.minimum(sizes, min_size), np.round(sizes * prof).astype(np.int64)
    )
    shards = []
    for c in range(idx.shape[0]):
        own = idx[c][mask[c]]
        if len(own) > keep[c]:
            own = np.sort(rng.choice(own, size=int(keep[c]), replace=False))
        shards.append(own.astype(np.int32))
    return partition._pad_shards(shards)


# ------------------------------------------------------------ entry point
def make_partition(
    spec: str,
    labels: np.ndarray,
    num_clients: int,
    seed: int = 0,
    batch_size: int = 128,
) -> Tuple[np.ndarray, np.ndarray]:
    """Build a population assignment from a scenario spec (see module
    docstring). ``batch_size`` only feeds the ``round_robin`` base."""
    labels = np.asarray(labels)
    stages = parse_scenario(spec)
    name, p = stages[0]
    if name == "iid":
        idx, mask = partition.iid(len(labels), num_clients, seed=seed)
    elif name == "dirichlet":
        idx, mask = partition.dirichlet(
            labels, num_clients, alpha=p.get("alpha", 0.5), seed=seed,
            min_size=int(p.get("min_size", 1)),
        )
    elif name == "pathological":
        idx, mask = pathological(
            labels, num_clients, shards_per_client=int(p.get("shards", 2)),
            seed=seed,
        )
    elif name == "label_skew":
        idx, mask = label_skew(
            labels, num_clients, classes_per_client=int(p.get("classes", 2)),
            seed=seed,
        )
    elif name == "quantity_skew":
        idx, mask = quantity_skew(
            len(labels), num_clients, power=p.get("power", 1.5),
            min_size=int(p.get("min", 1)), seed=seed,
        )
    else:  # round_robin — validated by parse_scenario
        idx, mask = partition.round_robin(len(labels), num_clients, batch_size)
    for name, p in stages[1:]:
        # parse_scenario restricts modifiers to quantity_skew today.
        idx, mask = apply_quantity_skew(
            idx, mask, power=p.get("power", 1.5),
            min_size=int(p.get("min", 1)), seed=seed + 1,
        )
    return idx, mask


# ------------------------------------------------------- per-cohort eval
def cohort_eval_indices(
    eval_labels: np.ndarray,
    label_hist: np.ndarray,
    num: int,
    seed: int = 0,
) -> np.ndarray:
    """Eval-set indices whose label mixture matches a cohort's.

    Under label/quantity skew the global test set no longer reflects what
    any given cohort was trained on; this draws ``num`` test examples (per
    class, without replacement, capped by per-class supply) proportional to
    ``label_hist`` — the cohort's training-label histogram — so
    "per-cohort eval" measures the model on the slice of the task the
    cohort actually represents.
    """
    eval_labels = np.asarray(eval_labels)
    hist = np.asarray(label_hist, np.float64)
    if hist.sum() <= 0:
        raise ValueError("cohort label histogram is empty")
    rng = np.random.default_rng(seed)
    want = np.floor(hist / hist.sum() * num).astype(np.int64)
    # Remainder to the largest classes.
    for k in np.argsort(-hist)[: num - int(want.sum())]:
        want[k] += 1
    picks = []
    for k in np.flatnonzero(want):
        pool = np.flatnonzero(eval_labels == k)
        if len(pool) == 0:
            continue
        take = min(int(want[k]), len(pool))
        picks.append(rng.choice(pool, size=take, replace=False))
    if not picks:
        raise ValueError("eval set holds none of the cohort's classes")
    return np.sort(np.concatenate(picks))
