"""Seeded adversarial participants for the simulation engines.

FL_PyTorch (arXiv:2202.03099) argues the unreliable/adversarial-participant
regime must be a first-class *simulated* scenario, not an afterthought:
fedtpu already ships the defenses (median/trimmed_mean/krum in
:mod:`fedtpu.core.round`, fused screening in :mod:`fedtpu.ops.flat`) but
until this module had no attacker to exercise them. Here the malicious set
becomes one more seeded, replayable scenario axis
(``SimConfig.malicious_fraction`` + ``SimConfig.attack``), exactly like
PR 5 made wire faults one (``fedtpu.ft.chaos``).

Attack kinds (``SimConfig.attack`` spec, ``kind[:key=val,...]``):

- ``sign_flip`` — submit the NEGATED honest delta (gradient ascent on the
  global objective; the classic model-poisoning baseline).
- ``scale:factor=F`` — submit the honest delta boosted by ``F`` (model
  replacement / boosting, Bagdasaryan et al.); ``factor`` may be negative
  to combine boosting with the sign flip.
- ``noise:std=S`` — add Gaussian noise of std ``S`` to the honest delta
  (a Gaussian Byzantine worker, Blanchard et al. 2017's attack model).
- ``label_flip:offset=K`` — a DATA poisoning attack: the attacker's
  training labels are shifted by ``K`` classes (mod num_classes). Applied
  host-side to the attacker-owned example rows at engine construction
  (partitions are disjoint covers, so only attacker shards are touched);
  the jitted round program is unchanged.

Shared options: ``p`` (per-round fire probability, default 1), ``rounds``
(``lo-hi`` half-open lineage-round window), ``collude=1`` (colluding-cohort
mode: the whole malicious set fires on ONE shared draw and — for ``noise``
— submits ONE shared noise vector, the coordinated fake cluster that
defeats distance-based selection like krum when independent noise would
not), and ``seed``.

Determinism contract (same as PR 5 chaos): attacker IDENTITY is a seeded
choice over the population, and every per-round decision is a pure function
of ``(seed, round)`` (via ``jax.random`` inside the jitted round step, via
the same fold host-side for accounting) — the same config replays the same
attack schedule bit-identically, which is what lets the convergence pins
assert exact reproducibility.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

ATTACK_KINDS = ("sign_flip", "scale", "noise", "label_flip")


@dataclasses.dataclass(frozen=True)
class AttackPlan:
    """Parsed ``SimConfig.attack`` spec — static, closed over by the jitted
    round step (only the per-seat attacker mask is a traced input)."""

    kind: str
    p: float = 1.0
    factor: float = 10.0
    std: float = 1.0
    label_offset: int = 1
    collude: bool = False
    rounds: Optional[Tuple[int, int]] = None
    seed: int = 0

    @property
    def coef(self) -> float:
        """Multiplicative coefficient on the honest delta."""
        if self.kind == "sign_flip":
            return -1.0
        if self.kind == "scale":
            return self.factor
        return 1.0

    def validate(self) -> "AttackPlan":
        if self.kind not in ATTACK_KINDS:
            raise ValueError(
                f"unknown attack kind {self.kind!r}; "
                f"have {'|'.join(ATTACK_KINDS)}"
            )
        if not 0.0 < self.p <= 1.0:
            raise ValueError(f"attack p must be in (0, 1], got {self.p}")
        if self.std < 0:
            raise ValueError(f"attack std must be >= 0, got {self.std}")
        if self.kind == "scale" and self.factor == 0.0:
            raise ValueError("attack scale factor must be nonzero")
        if self.kind == "label_flip" and self.label_offset == 0:
            raise ValueError("label_flip offset must be nonzero")
        return self


def parse_attack(spec: str) -> AttackPlan:
    """``kind[:key=val,...]`` -> validated :class:`AttackPlan`.

    Examples: ``sign_flip``, ``scale:factor=20,p=0.5``,
    ``noise:std=2.0,collude=1``, ``label_flip:offset=3,rounds=10-50``.
    """
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty attack spec")
    head, _, opt_str = spec.partition(":")
    fields: dict = {"kind": head.strip()}
    for opt in filter(None, (o.strip() for o in opt_str.split(","))):
        key, eq, val = opt.partition("=")
        if not eq:
            raise ValueError(f"attack option {opt!r} is not key=value")
        key, val = key.strip(), val.strip()
        if key == "p":
            fields["p"] = float(val)
        elif key == "factor":
            fields["factor"] = float(val)
        elif key == "std":
            fields["std"] = float(val)
        elif key == "offset":
            fields["label_offset"] = int(val)
        elif key == "collude":
            fields["collude"] = val not in ("0", "false", "False", "")
        elif key == "seed":
            fields["seed"] = int(val)
        elif key == "rounds":
            lo, dash, hi = val.partition("-")
            fields["rounds"] = (
                (int(lo), int(hi)) if dash else (int(lo), int(lo) + 1)
            )
        else:
            raise ValueError(
                f"unknown attack option {key!r} in {spec!r}; have "
                "p|factor|std|offset|collude|rounds|seed"
            )
    return AttackPlan(**fields).validate()


def choose_attackers(population: int, fraction: float, seed: int) -> np.ndarray:
    """The seeded malicious subset: ``floor(fraction * population)`` client
    ids drawn without replacement. Pure function of (population, fraction,
    seed) — the identity of the adversaries replays exactly."""
    k = int(np.floor(fraction * population))
    if k <= 0:
        return np.zeros((0,), np.int64)
    rng = np.random.default_rng(seed * 9973 + 0xBAD)
    return np.sort(rng.choice(population, size=k, replace=False)).astype(
        np.int64
    )


def attacker_mask(population: int, fraction: float, seed: int) -> np.ndarray:
    """``[population]`` bool mask over client ids (True = malicious)."""
    mask = np.zeros((population,), bool)
    mask[choose_attackers(population, fraction, seed)] = True
    return mask


def flip_labels(
    labels: np.ndarray,
    idx: np.ndarray,
    mask: np.ndarray,
    attackers: np.ndarray,
    offset: int,
    num_classes: int,
) -> np.ndarray:
    """Label-flip poisoning applied to the attacker-owned example rows.

    ``idx``/``mask``: the ``[clients, shard_len]`` partition (a disjoint
    cover, so only attacker shards change); ``attackers``: ``[clients]``
    bool. Returns a COPY of ``labels`` with the attackers' examples shifted
    by ``offset`` classes — the attackers then *train honestly on poisoned
    data*, the cheapest realistic data-poisoning adversary.
    """
    out = np.asarray(labels).copy()
    for c in np.flatnonzero(np.asarray(attackers, bool)):
        own = idx[c][mask[c]]
        if len(own):
            out[own] = (out[own] + offset) % num_classes
    return out


def attack_fire_mask(plan: AttackPlan, attack_seats, round_idx, n: int):
    """Traced per-seat fire decision for one round: attacker seat AND
    round window AND the seeded per-round Bernoulli draw (one shared draw
    in colluding mode). Pure function of (plan, round_idx, seats) — the
    jitted twin of :func:`fires_this_round`."""
    import jax
    import jax.numpy as jnp

    fire = attack_seats.astype(jnp.float32) > 0
    if plan.rounds is not None:
        lo, hi = plan.rounds
        fire = fire & (round_idx >= lo) & (round_idx < hi)
    if plan.p < 1.0:
        key = jax.random.fold_in(
            jax.random.PRNGKey(plan.seed ^ 0xAD5A17), round_idx
        )
        if plan.collude:
            fire = fire & (jax.random.uniform(key, ()) < plan.p)
        else:
            fire = fire & (jax.random.uniform(key, (n,)) < plan.p)
    return fire


def fires_this_round(
    plan: AttackPlan, attack_seats: np.ndarray, round_idx: int
) -> np.ndarray:
    """Host-side mirror of :func:`attack_fire_mask` (identical jax.random
    draws, forced to CPU-independent semantics by jax's deterministic PRNG)
    — used for per-round accounting (``fedtpu_attack_injected_total``)
    without reading anything back from the device."""
    import jax
    import jax.numpy as jnp

    return np.asarray(
        attack_fire_mask(
            plan,
            jnp.asarray(np.asarray(attack_seats, np.float32)),
            jnp.asarray(round_idx, jnp.int32),
            len(attack_seats),
        )
    )
