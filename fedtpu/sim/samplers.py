"""Per-round cohort samplers over a :class:`~fedtpu.sim.population.Population`.

A sampler answers one question each round: *which ``cohort`` of the
population trains now?* All samplers:

- draw **without replacement** (a client trains at most once per round);
- respect the population's availability/churn trace (an offline client is
  never drawn — the unreliable-participant regime of arXiv:2202.03099);
- return **sorted** client ids. Sorting is load-bearing: when
  ``population == cohort`` with everyone available, every round's cohort is
  the identity map ``[0..n)``, the engine's per-slot state never needs a
  reset, and the sim path reproduces the resident engine bit-for-bit (the
  parity pin in ``tests/test_sim.py``);
- degrade gracefully when fewer clients are available than the cohort has
  slots: the spare slots are padded with id 0 and masked dead via the
  returned ``alive`` vector (the engine's existing dead-client handling —
  padded slots do no work and are excluded from the aggregate).

Seeded per (sampler seed, round): the same config replays the same cohort
sequence on any host.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from fedtpu.sim.population import Population
from fedtpu.sim.sampling import loss_weights, round_rng


class CohortSampler:
    """Base: common availability handling + pad-to-cohort machinery."""

    name = "base"

    def __init__(self, seed: int = 0):
        self.seed = int(seed)

    def _probabilities(
        self, pop: Population, candidates: np.ndarray
    ) -> Optional[np.ndarray]:
        """Pick probabilities over the available candidates (None = uniform)."""
        return None

    def sample(
        self, pop: Population, round_idx: int, cohort: int
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Draw one round's cohort: ``(client_ids[cohort], alive[cohort])``,
        ids sorted ascending, ``alive`` False only for padded slots."""
        if cohort < 1 or cohort > pop.size:
            raise ValueError(
                f"cohort must be in [1, population={pop.size}], got {cohort}"
            )
        candidates = np.flatnonzero(pop.available_at(round_idx))
        rng = round_rng(self.seed, round_idx)
        if len(candidates) <= cohort:
            chosen = candidates
        else:
            p = self._probabilities(pop, candidates)
            chosen = rng.choice(candidates, size=cohort, replace=False, p=p)
        chosen = np.sort(chosen.astype(np.int64))
        alive = np.ones((cohort,), bool)
        if len(chosen) < cohort:
            pad = cohort - len(chosen)
            alive[len(chosen):] = False
            chosen = np.concatenate([chosen, np.zeros((pad,), np.int64)])
        return chosen, alive


class UniformSampler(CohortSampler):
    """Uniform without-replacement over the available population."""

    name = "uniform"


class LossProportionalSampler(CohortSampler):
    """Importance sampling proportional to each client's *last-seen*
    training loss (arXiv:2306.03240 flavor), routed through the population's
    sparse observation table: never-yet-sampled clients draw at the
    optimistic prior (``prior``; default the max observed loss) instead of a
    stale zero, so the worst-served clients are revisited *and* the
    never-visited are explored. Uniform until the first observation lands.
    """

    name = "loss"

    def __init__(self, seed: int = 0, prior: Optional[float] = None):
        super().__init__(seed)
        self.prior = prior

    def _probabilities(self, pop, candidates):
        return loss_weights(pop.last_seen_loss[candidates], prior=self.prior)


def make_sampler(
    name: str, seed: int = 0, prior: Optional[float] = None
) -> CohortSampler:
    """Sampler factory for ``SimConfig.cohort_sampler``."""
    if name == "uniform":
        return UniformSampler(seed)
    if name == "loss":
        return LossProportionalSampler(seed, prior=prior)
    raise ValueError(f"unknown cohort sampler {name!r}; have uniform | loss")
