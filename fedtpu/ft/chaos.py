"""Deterministic fault injection (chaos) for the gRPC federation edge.

The FT machinery of this package — heartbeat revival, retry/backoff
(:mod:`fedtpu.transport.retry`), round quorum, primary/backup failover —
exists for faults, yet nothing in the repo could *produce* a fault against
the live transport short of manually killing processes (the reference's
only drill, SURVEY §4). This module is the missing half: a seeded,
scriptable :class:`FaultSchedule` of :class:`FaultRule` entries applied via
gRPC client-channel and server interceptors, so multi-process soaks
(``tools/chaos_soak.py``) replay bit-identically from a spec string.

Fault kinds:

- ``delay``   — sleep ``delay_s`` before the call proceeds (straggler /
  congested-edge simulation; composes with round deadlines).
- ``drop``    — sleep ``delay_s``, then fail with DEADLINE_EXCEEDED
  (a blackholed packet, time-compressed so soaks stay fast).
- ``error``   — fail immediately with status ``code`` (default
  UNAVAILABLE — the classic transient).
- ``corrupt`` — deliver the RPC but flip the last byte of its payload
  (``TrainReply.message`` / ``SendModelRequest.model``), exercising the
  wire-CRC reject-and-retry path.
- ``kill``    — SIGKILL the *current process* (use ``max=1`` for the
  one-shot mid-round primary kill of the failover drills).

Network-partition faults (``NET_KINDS``: ``partition`` | ``flaky``) ride
the same wire interceptors but model LINK failures rather than peer
failures: ``partition`` is a total link cut (immediate UNAVAILABLE, no
time spent — the TCP RST of a severed path), group-keyed via ``peer=a|b``
so one rule severs a whole side of the federation, and windowed either by
``rounds=`` or the new wall-clock ``window=lo-hi`` (seconds since the
schedule was armed — partitions must also cut paths, like the backup
watchdog's, that never learn a round number); ``flaky`` is the gray link —
a seeded intermittent burst that *delays* ``delay_s`` and then fails with
``code``, the flapping half-failure that exercises watchdog hysteresis.
Asymmetric cuts fall out of placement: arm ``partition`` only on one
side's schedule and the reverse direction stays up.

Model-level Byzantine attacks (``ATTACK_KINDS``: ``sign_flip`` |
``scale:factor=F`` | ``noise:std=S[,collude=1]`` | ``label_flip:offset=K``)
ride the same schedule/DSL but are a separate fault CLASS: they are
consulted by :class:`fedtpu.transport.federation.LocalTrainer` via
:meth:`FaultSchedule.decide_attack` (pseudo-RPC ``Attack``, peer = the
client's own address) and executed against the model update itself, never
by the wire interceptors; they count into
``fedtpu_attack_injected_total{kind}``. See docs/FAULT_TOLERANCE.md
§Threat model.

Disk faults (``DISK_KINDS``: ``ckpt_fail`` | ``ckpt_torn`` | ``ckpt_rot``)
are a third class, keyed on the pseudo-RPC ``Disk`` and consulted once per
:meth:`fedtpu.checkpoint.Checkpointer.save` — the chaos surface of the
durability stack (write failures, torn writes, silent bit rot; see
docs/FAULT_TOLERANCE.md §Durability and ``tools/chaos_soak.py
--disaster``). Like attacks, they never fire from wire interceptors and
wildcard wire rules never fire on the disk consult.

Determinism: each (rule, rpc, peer) triple keeps its own draw counter, and
the n-th draw fires iff ``crc32(f"{seed}|{rule}|{rpc}|{peer}|{n}") / 2^32 <
p``. The decision therefore depends only on the seed and on that peer's own
call sequence for that RPC — not on cross-peer thread interleaving — so a
re-run with the same spec injects the same faults at the same points.

Spec format (``--chaos-spec`` on all four CLIs): either a JSON object
``{"seed": 7, "rules": [{"kind": "error", "rpc": "StartTrain", "p": 0.3}]}``
or the mini-DSL ``kind@rpc:key=val,...`` with rules joined by ``;`` —
e.g. ``error@StartTrain:p=0.3,seed=7;delay@SendModel:p=0.1,delay=0.5``.
Keys: ``p`` (probability), ``peer``, ``delay`` (seconds), ``code``
(grpc status name), ``rounds`` (``lo-hi`` half-open window or a single
round), ``window`` (``lo-hi`` half-open wall-clock window in seconds since
the schedule was armed — the time-domain sibling of ``rounds`` for paths
with no round counter), ``max`` (total injection cap), ``consec`` (max
consecutive fires
per stream — what makes a rule transient BY CONSTRUCTION; pair
``consec < retry attempts`` with unbounded ``p`` faults), ``seed``
(schedule-wide).

Every injected fault increments ``fedtpu_chaos_injected_total{kind,rpc}``
and lands in the flight recorder, so a post-mortem dump shows exactly
which faults preceded a failure. The engine CLIs (``run``/``train``) have
no RPC edge; there the schedule's :meth:`FaultSchedule.tick_round` applies
``delay``/``kill`` rules keyed on the pseudo-RPC ``Round``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("fedtpu.chaos")

WIRE_KINDS = ("delay", "drop", "error", "corrupt", "kill")
# Seeded disk faults against the checkpoint store (the durability fault
# class): consulted by fedtpu.checkpoint.Checkpointer.save via the
# pseudo-RPC "Disk" — never by the wire interceptors. ckpt_fail raises
# ENOSPC at write time (the non-fatal-save path: counted, training
# continues); ckpt_torn truncates the WRITTEN generation to half and
# ckpt_rot flips a byte in it AFTER the writer verified — both model a
# disk that acknowledged the write and lost/flipped bits later, so only
# restore-time manifest verification (and the multi-generation fallback)
# can catch them. The disaster soak (tools/chaos_soak.py --disaster) is
# built on these.
DISK_KINDS = ("ckpt_fail", "ckpt_torn", "ckpt_rot")
# Model-level Byzantine attacks (the well-formed-but-malicious fault
# class): executed inside LocalTrainer against the update itself, never by
# the wire interceptors. Keyed on the pseudo-RPC "Attack" with peer = the
# client's own serving address; consulted once per training round via
# decide_attack(). sign_flip negates the honest delta, scale boosts it by
# `factor`, noise adds Gaussian noise of std `std` (a shared draw when
# collude=1 — the coordinated fake cluster), label_flip shifts the round's
# training labels by `offset` classes. The simulated twin is
# fedtpu.sim.adversary (SimConfig.malicious_fraction).
ATTACK_KINDS = ("sign_flip", "scale", "noise", "label_flip")
# Link-level network faults (the partition/gray-failure class): fired by
# the SAME wire interceptors as WIRE_KINDS but modeling the link, not the
# peer. "partition" severs the path instantly (UNAVAILABLE with no sleep);
# "flaky" stalls delay_s then fails with `code` — the gray link that flaps
# watchdogs. Group-keyed peers (peer=a|b) and wall-clock windows
# (window=lo-hi seconds) let one rule cut a whole side of the federation
# for a bounded interval. The partition-heal soak
# (tools/chaos_soak.py --partition) is built on these.
NET_KINDS = ("partition", "flaky")
KINDS = WIRE_KINDS + NET_KINDS + ATTACK_KINDS + DISK_KINDS
# The service's RPC surface plus the engine loops' pseudo-RPC, the
# model-level attack consult, and the checkpoint store's disk consult.
RPC_NAMES = (
    "StartTrain", "SendModel", "SubmitPartial", "HeartBeat",
    "CheckIfPrimaryUp", "FetchModel", "Round", "Attack", "Disk", "*",
)


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """One scriptable fault: WHAT to inject (``kind`` + parameters) and
    WHERE (rpc name, peer, round window, probability, total cap)."""

    kind: str
    rpc: str = "*"
    peer: str = "*"
    p: float = 1.0
    delay_s: float = 0.25
    code: str = "UNAVAILABLE"
    # Half-open [lo, hi) coordinator-round window; None = every round.
    # Only consulted where a round is known (the coordinator sets it).
    rounds: Optional[Tuple[int, int]] = None
    # Half-open [lo, hi) WALL-CLOCK window in seconds since the schedule
    # was constructed; None = always. The time-domain sibling of rounds=,
    # for paths that never learn a round number (the backup's watchdog
    # probes, a partitioned primary whose round counter stalls) — a healed
    # partition is "the window closed".
    window: Optional[Tuple[float, float]] = None
    # Total injections this rule may ever perform (None = unbounded);
    # max=1 is the one-shot process kill.
    max_injections: Optional[int] = None
    # Cap on CONSECUTIVE fires per (rule, rpc, peer) stream: after this
    # many in a row the rule passes until one of its draws passes
    # naturally (only a drawn pass re-arms the streak). This is what makes
    # a rule *transient by construction* — an unbounded Bernoulli stream
    # eventually produces an outage longer than any retry budget, which is
    # a different fault class. A soak that must prove "zero clients die of
    # transients" pairs consec < retry attempts. None = unbounded
    # (outage-style rules).
    max_consecutive: Optional[int] = None
    # Attack-kind parameters (ATTACK_KINDS only; ignored by wire kinds).
    factor: float = 10.0      # scale: boost on the honest delta
    noise_std: float = 1.0    # noise: Gaussian std
    label_offset: int = 1     # label_flip: class shift (mod num_classes)
    # Colluding-cohort mode: every attacker consulting this rule shares ONE
    # per-round draw (and one noise vector) instead of independent ones —
    # a consistent fake cluster, the shape that defeats distance-based
    # selection (krum) where independent noise would not.
    collude: bool = False

    @property
    def is_attack(self) -> bool:
        return self.kind in ATTACK_KINDS

    @property
    def is_disk(self) -> bool:
        return self.kind in DISK_KINDS

    @property
    def is_net(self) -> bool:
        return self.kind in NET_KINDS

    def validate(self) -> "FaultRule":
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; have {'|'.join(KINDS)}"
            )
        if self.rpc not in RPC_NAMES:
            raise ValueError(
                f"unknown rpc {self.rpc!r}; have {'|'.join(RPC_NAMES)}"
            )
        if self.is_attack and self.rpc not in ("Attack", "*"):
            raise ValueError(
                f"attack kind {self.kind!r} applies to the model update, "
                "not an RPC — leave rpc unset (it keys on the pseudo-RPC "
                "'Attack')"
            )
        if self.is_disk and self.rpc not in ("Disk", "*"):
            raise ValueError(
                f"disk kind {self.kind!r} applies to the checkpoint "
                "store, not an RPC — leave rpc unset (it keys on the "
                "pseudo-RPC 'Disk')"
            )
        if (self.kind in WIRE_KINDS + NET_KINDS
                and self.rpc in ("Attack", "Disk")):
            raise ValueError(
                f"wire kind {self.kind!r} cannot target the pseudo-RPC "
                f"{self.rpc!r} (kind classes never cross)"
            )
        if self.is_net and self.rpc == "Round":
            raise ValueError(
                f"net kind {self.kind!r} models a LINK fault — it needs a "
                "wire RPC, not the engine-loop pseudo-RPC 'Round'"
            )
        if self.window is not None:
            lo, hi = self.window
            if lo < 0 or hi <= lo:
                raise ValueError(
                    f"fault window must satisfy 0 <= lo < hi, got "
                    f"{lo}-{hi}"
                )
        if self.kind == "scale" and self.factor == 0.0:
            raise ValueError("scale attack factor must be nonzero")
        if self.noise_std < 0:
            raise ValueError(
                f"noise std must be >= 0, got {self.noise_std}"
            )
        if self.kind == "label_flip" and self.label_offset == 0:
            raise ValueError("label_flip offset must be nonzero")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1], got {self.p}")
        if self.delay_s < 0:
            raise ValueError(f"fault delay must be >= 0, got {self.delay_s}")
        if self.max_injections is not None and self.max_injections < 1:
            raise ValueError("fault max must be >= 1")
        if self.max_consecutive is not None and self.max_consecutive < 1:
            raise ValueError("fault consec must be >= 1")
        return self


class FaultSchedule:
    """Seeded schedule of fault rules, consulted per RPC by the
    interceptors. Thread-safe; one instance is shared by every channel and
    server of a process."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = [r.validate() for r in rules]
        self.seed = int(seed)
        self._counts: Dict[Tuple[int, str, str], int] = {}
        # Consecutive-fire run length per (rule, rpc, peer) stream, for
        # max_consecutive enforcement.
        self._streak: Dict[Tuple[int, str, str], int] = {}
        self._fired = [0] * len(self.rules)
        self._round: Optional[int] = None
        # Arm time: origin of the window= wall-clock axis.
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._metrics = None
        self._flight = None

    # ------------------------------------------------------------ wiring
    def attach(self, metrics=None, flight=None) -> "FaultSchedule":
        """Hook the owning component's metrics registry / flight recorder
        (later attach calls with None keep earlier hooks)."""
        if metrics is not None:
            self._metrics = metrics
        if flight is not None:
            self._flight = flight
        return self

    def set_round(self, round_idx: int) -> None:
        """The coordinator advertises its current round so ``rounds=``
        windows can key on it (peers without a round match any window)."""
        self._round = int(round_idx)

    # ---------------------------------------------------------- decision
    def _matches(self, rule: FaultRule, rpc: str, peer: str) -> bool:
        # Kind classes never cross: a wildcard wire rule (error@*) must not
        # fire on the model-update or disk consults, and an attack/disk
        # rule must never inject into a wire interceptor.
        if rule.is_attack != (rpc == "Attack"):
            return False
        if rule.is_disk != (rpc == "Disk"):
            return False
        if rule.rpc != "*" and rule.rpc != rpc:
            return False
        # peer may be a |-joined GROUP (partition rules cut whole sides of
        # the federation with one rule); a single peer is a group of one.
        if rule.peer != "*" and peer not in rule.peer.split("|"):
            return False
        if rule.rounds is not None and self._round is not None:
            lo, hi = rule.rounds
            if not lo <= self._round < hi:
                return False
        if rule.window is not None:
            lo, hi = rule.window
            if not lo <= time.monotonic() - self._t0 < hi:
                return False
        return True

    def decide(self, rpc: str, peer: str = "*") -> Optional[FaultRule]:
        """First rule that fires for this call, advancing the deterministic
        draw counters; None = the call proceeds untouched. Counting happens
        here (not at apply time) so the decision itself is the injection
        event of record."""
        fired = None
        with self._lock:
            for i, rule in enumerate(self.rules):
                if not self._matches(rule, rpc, peer):
                    continue
                if (rule.max_injections is not None
                        and self._fired[i] >= rule.max_injections):
                    continue
                key = (i, rpc, peer)
                n = self._counts.get(key, 0)
                self._counts[key] = n + 1
                draw = f"{self.seed}|{i}|{rpc}|{peer}|{n}".encode()
                u = (zlib.crc32(draw) & 0xFFFFFFFF) / 2**32
                capped = (
                    rule.max_consecutive is not None
                    and self._streak.get(key, 0) >= rule.max_consecutive
                )
                if u < rule.p and not capped:
                    self._streak[key] = self._streak.get(key, 0) + 1
                    self._fired[i] += 1
                    fired = rule
                    break
                if u >= rule.p:
                    # Only a DRAWN pass re-arms a capped stream (a forced
                    # pass leaves the streak at the cap): a capped rule
                    # stays silent while its draws keep firing, so a
                    # multi-rule schedule cannot alternate its resets into
                    # an unbounded outage — each rule fires at most
                    # max_consecutive times between drawn passes.
                    self._streak[key] = 0
        if fired is not None:
            self._record(fired, rpc, peer)
        return fired

    def _record(self, rule: FaultRule, rpc: str, peer: str) -> None:
        log.warning(
            "chaos: injecting %s on %s%s (round=%s)",
            rule.kind, rpc, f" -> {peer}" if peer != "*" else "", self._round,
        )
        if self._metrics is not None:
            if rule.is_attack:
                # Byzantine attacks are their own fault class — folding
                # them into the wire-chaos counter would hide the regime a
                # soak is actually in (satellite of the Byzantine PR).
                self._metrics.counter(
                    "fedtpu_attack_injected_total",
                    "model/data-level attacks executed by seeded "
                    "adversarial clients, by kind",
                    labels={"kind": rule.kind},
                ).inc()
            else:
                self._metrics.counter(
                    "fedtpu_chaos_injected_total",
                    "faults injected by the chaos schedule, by kind and rpc",
                    labels={"kind": rule.kind, "rpc": rpc},
                ).inc()
        if self._flight is not None:
            self._flight.record(
                "attack" if rule.is_attack else "chaos",
                fault=rule.kind, rpc=rpc, peer=peer,
                round=self._round,
            )

    def injected_total(self) -> int:
        with self._lock:
            return sum(self._fired)

    def describe(self) -> str:
        """Startup-log line: the armed rules, compactly."""
        parts = []
        for r in self.rules:
            opts = [f"p={r.p:g}"]
            if r.peer != "*":
                opts.append(f"peer={r.peer}")
            if r.rounds is not None:
                opts.append(f"rounds={r.rounds[0]}-{r.rounds[1]}")
            if r.window is not None:
                opts.append(f"window={r.window[0]:g}-{r.window[1]:g}")
            if r.max_injections is not None:
                opts.append(f"max={r.max_injections}")
            if r.max_consecutive is not None:
                opts.append(f"consec={r.max_consecutive}")
            if r.kind == "scale":
                opts.append(f"factor={r.factor:g}")
            elif r.kind == "noise":
                opts.append(f"std={r.noise_std:g}")
            elif r.kind == "label_flip":
                opts.append(f"offset={r.label_offset}")
            if r.collude:
                opts.append("collude=1")
            parts.append(f"{r.kind}@{r.rpc}:{','.join(opts)}")
        return f"seed={self.seed} " + "; ".join(parts)

    # ------------------------------------------------------- application
    def _kill(self, rpc: str) -> None:
        # Flush the flight recorder synchronously first: SIGKILL leaves no
        # exit path, and the dump is the whole point of the drill.
        log.warning("chaos: SIGKILL of pid %d (rule on %s)", os.getpid(), rpc)
        if self._flight is not None:
            try:
                self._flight.dump(reason="chaos:kill")
            except Exception:
                pass
        os.kill(os.getpid(), signal.SIGKILL)

    def apply_precall(self, rule: FaultRule, rpc: str) -> None:
        """Client-side pre-call application of a fired rule (``corrupt`` is
        applied to the response instead)."""
        import grpc

        if rule.kind == "delay":
            time.sleep(rule.delay_s)
        elif rule.kind == "drop":
            time.sleep(rule.delay_s)
            raise ChaosRpcError(grpc.StatusCode.DEADLINE_EXCEEDED,
                                "chaos: dropped request")
        elif rule.kind == "error":
            raise ChaosRpcError(getattr(grpc.StatusCode, rule.code),
                                "chaos: injected error")
        elif rule.kind == "partition":
            # A severed link fails FAST (connection refused / RST), unlike
            # drop's time-compressed blackhole — no sleep.
            raise ChaosRpcError(grpc.StatusCode.UNAVAILABLE,
                                "chaos: partitioned link")
        elif rule.kind == "flaky":
            # Gray link: a stall long enough to flap watchdogs, then a
            # failure with the configured code.
            time.sleep(rule.delay_s)
            raise ChaosRpcError(getattr(grpc.StatusCode, rule.code),
                                "chaos: flaky link")
        elif rule.kind == "kill":
            self._kill(rpc)

    def decide_attack(self, client: str, round_idx: Optional[int] = None):
        """Model-level attack consult: the first ATTACK_KINDS rule that
        fires for this client's training round (None = train honestly).
        Called by :class:`fedtpu.transport.federation.LocalTrainer` once
        per StartTrain, with ``client`` = its own serving address and
        ``round_idx`` = its local round (keys ``rounds=`` windows). Same
        deterministic draw counters as :meth:`decide` — an attack schedule
        replays bit-identically from its seed."""
        if round_idx is not None:
            self.set_round(round_idx)
        return self.decide("Attack", client)

    def apply_attack_delta(self, rule: FaultRule, delta, peer: str,
                           round_idx: int):
        """Transform a host-side delta pytree per a fired delta-level
        attack rule (sign_flip | scale | noise). Noise draws are seeded
        from (schedule seed, peer, round) — or (schedule seed, round) in
        colluding mode, so every colluder submits the SAME noise vector —
        making the attacked payload a pure function of the spec."""
        import jax
        import numpy as np

        coef = {"sign_flip": -1.0, "scale": rule.factor}.get(rule.kind, 1.0)
        if coef != 1.0:
            delta = jax.tree.map(
                lambda x: (np.asarray(x, np.float32) * coef).astype(
                    np.asarray(x).dtype
                ),
                delta,
            )
        if rule.kind == "noise":
            who = "*" if rule.collude else peer
            seed = zlib.crc32(
                f"{self.seed}|attack-noise|{who}|{round_idx}".encode()
            )
            rng = np.random.default_rng(seed)
            delta = jax.tree.map(
                lambda x: (
                    np.asarray(x, np.float32)
                    + rng.normal(0.0, rule.noise_std, np.shape(x)).astype(
                        np.float32
                    )
                ).astype(np.asarray(x).dtype),
                delta,
            )
        return delta

    def tick_round(self, round_idx: int) -> None:
        """Engine-loop hook for the RPC-less CLIs (``run``/``train``): one
        consult of the pseudo-RPC ``Round`` per round/epoch. Only
        ``delay`` and ``kill`` are meaningful without a wire; other kinds
        are counted but ignored (parse-time warning)."""
        self.set_round(round_idx)
        rule = self.decide("Round")
        if rule is None:
            return
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
        elif rule.kind == "kill":
            self._kill("Round")

    # ------------------------------------------------------ interceptors
    def client_interceptor(self, peer: str):
        """A ``grpc.UnaryUnaryClientInterceptor`` injecting this schedule's
        faults on every RPC issued over one channel to ``peer``."""
        import grpc

        schedule = self

        class _CorruptedCall:
            """Wraps the continuation's call so ``result()`` hands back a
            payload-corrupted response; everything else delegates."""

            def __init__(self, inner):
                self._inner = inner

            def result(self, timeout=None):
                return _corrupt_message(self._inner.result())

            def __getattr__(self, name):
                return getattr(self._inner, name)

        class _ChaosClientInterceptor(grpc.UnaryUnaryClientInterceptor):
            def intercept_unary_unary(self, continuation,
                                      client_call_details, request):
                rpc = client_call_details.method.rsplit("/", 1)[-1]
                rule = schedule.decide(rpc, peer)
                if rule is not None and rule.kind != "corrupt":
                    schedule.apply_precall(rule, rpc)
                call = continuation(client_call_details, request)
                if rule is not None and rule.kind == "corrupt":
                    return _CorruptedCall(call)
                return call

        return _ChaosClientInterceptor()

    def server_interceptor(self):
        """A ``grpc.ServerInterceptor`` injecting this schedule's faults on
        every inbound unary RPC (peer is unknown server-side: ``"*"``)."""
        import grpc

        schedule = self

        class _ChaosServerInterceptor(grpc.ServerInterceptor):
            def intercept_service(self, continuation, handler_call_details):
                handler = continuation(handler_call_details)
                if handler is None or handler.unary_unary is None:
                    return handler
                rpc = handler_call_details.method.rsplit("/", 1)[-1]
                inner = handler.unary_unary

                def behavior(request, context):
                    rule = schedule.decide(rpc)
                    if rule is not None:
                        if rule.kind in ("delay", "drop"):
                            time.sleep(rule.delay_s)
                            if rule.kind == "drop":
                                context.abort(
                                    grpc.StatusCode.DEADLINE_EXCEEDED,
                                    "chaos: dropped reply",
                                )
                        elif rule.kind == "error":
                            context.abort(
                                getattr(grpc.StatusCode, rule.code),
                                "chaos: injected error",
                            )
                        elif rule.kind == "partition":
                            context.abort(
                                grpc.StatusCode.UNAVAILABLE,
                                "chaos: partitioned link",
                            )
                        elif rule.kind == "flaky":
                            time.sleep(rule.delay_s)
                            context.abort(
                                getattr(grpc.StatusCode, rule.code),
                                "chaos: flaky link",
                            )
                        elif rule.kind == "kill":
                            schedule._kill(rpc)
                    response = inner(request, context)
                    if rule is not None and rule.kind == "corrupt":
                        response = _corrupt_message(response)
                    return response

                return grpc.unary_unary_rpc_method_handler(
                    behavior,
                    request_deserializer=handler.request_deserializer,
                    response_serializer=handler.response_serializer,
                )

        return _ChaosServerInterceptor()


_CHAOS_ERROR_TYPE = None


def ChaosRpcError(code, details: str):
    """An injected RPC failure: a real ``grpc.RpcError`` subclass (built
    lazily so this module imports without grpc), so every existing
    ``except grpc.RpcError`` — and the retry classifier — handles injected
    faults exactly like wire-originated ones."""
    global _CHAOS_ERROR_TYPE
    if _CHAOS_ERROR_TYPE is None:
        import grpc

        class _ChaosRpcError(grpc.RpcError):
            def __init__(self, code, details):
                super().__init__(f"chaos: {code} ({details})")
                self._code = code
                self._details = details

            def code(self):
                return self._code

            def details(self):
                return self._details

        _CHAOS_ERROR_TYPE = _ChaosRpcError
    return _CHAOS_ERROR_TYPE(code, details)


def _corrupt_message(msg):
    """Flip the last byte of the message's (largest) bytes payload — past
    the wire header, so the CRC (not the magic check) catches it. Messages
    without a non-empty bytes field pass through untouched."""
    target, size = None, 0
    for field in getattr(msg, "__dataclass_fields__", {}):
        value = getattr(msg, field)
        if isinstance(value, (bytes, bytearray)) and len(value) > size:
            target, size = field, len(value)
    if target is None:
        return msg
    raw = bytearray(getattr(msg, target))
    raw[-1] ^= 0xFF
    setattr(msg, target, bytes(raw))
    return msg


# ------------------------------------------------------------------ parsing
def parse_spec(spec: Optional[str]) -> Optional[FaultSchedule]:
    """``--chaos-spec`` string -> armed :class:`FaultSchedule` (None for
    empty/absent). JSON when the string starts with ``{``; the mini-DSL
    otherwise. Raises ValueError with the offending fragment on bad input.
    """
    if spec is None or not spec.strip():
        return None
    spec = spec.strip()
    if spec.startswith("{"):
        return _parse_json(spec)
    return _parse_dsl(spec)


def _parse_json(spec: str) -> FaultSchedule:
    try:
        obj = json.loads(spec)
    except json.JSONDecodeError as exc:
        raise ValueError(f"chaos spec is not valid JSON: {exc}") from exc
    rules = []
    for raw in obj.get("rules", []):
        rules.append(_rule_from(dict(raw)))
    if not rules:
        raise ValueError("chaos spec has no rules")
    return FaultSchedule(rules, seed=int(obj.get("seed", 0)))


def _parse_dsl(spec: str) -> FaultSchedule:
    rules, seed = [], 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, opt_str = part.partition(":")
        kind, _, rpc = head.partition("@")
        fields: dict = {"kind": kind.strip(), "rpc": rpc.strip() or "*"}
        for opt in filter(None, (o.strip() for o in opt_str.split(","))):
            key, eq, val = opt.partition("=")
            if not eq:
                raise ValueError(f"chaos option {opt!r} is not key=value")
            key = key.strip()
            val = val.strip()
            if key == "seed":
                seed = int(val)
            elif key in ("p", "peer", "code", "rounds", "window"):
                fields[key] = val
            elif key == "delay":
                fields["delay_s"] = val
            elif key == "max":
                fields["max_injections"] = val
            elif key == "consec":
                fields["max_consecutive"] = val
            elif key == "factor":
                fields["factor"] = val
            elif key == "std":
                fields["noise_std"] = val
            elif key == "offset":
                fields["label_offset"] = val
            elif key == "collude":
                fields["collude"] = val not in ("0", "false", "False", "")
            else:
                raise ValueError(
                    f"unknown chaos option {key!r} in {part!r}; have "
                    "p|peer|delay|code|rounds|window|max|consec|seed|"
                    "factor|std|offset|collude"
                )
        rules.append(_rule_from(fields))
    if not rules:
        raise ValueError("chaos spec has no rules")
    return FaultSchedule(rules, seed=seed)


def _rule_from(fields: dict) -> FaultRule:
    # Attack kinds key on the pseudo-RPC "Attack" and disk kinds on
    # "Disk"; a bare `sign_flip:p=1` / `ckpt_rot:p=1` spec normalizes
    # there so authors never have to spell it.
    if fields.get("kind") in ATTACK_KINDS and fields.get("rpc", "*") == "*":
        fields["rpc"] = "Attack"
    if fields.get("kind") in DISK_KINDS and fields.get("rpc", "*") == "*":
        fields["rpc"] = "Disk"
    if "rounds" in fields and not isinstance(fields["rounds"], (tuple, list)):
        lo, dash, hi = str(fields["rounds"]).partition("-")
        fields["rounds"] = (int(lo), int(hi)) if dash else (
            int(lo), int(lo) + 1
        )
    if "rounds" in fields and fields["rounds"] is not None:
        fields["rounds"] = tuple(int(x) for x in fields["rounds"])
    if "window" in fields and not isinstance(fields["window"],
                                             (tuple, list)):
        lo, dash, hi = str(fields["window"]).partition("-")
        if not dash:
            raise ValueError(
                f"chaos window must be lo-hi seconds, got "
                f"{fields['window']!r}"
            )
        fields["window"] = (float(lo), float(hi))
    if "window" in fields and fields["window"] is not None:
        fields["window"] = tuple(float(x) for x in fields["window"])
    for key in ("p", "delay_s", "factor", "noise_std"):
        if key in fields:
            fields[key] = float(fields[key])
    for key in ("max_injections", "max_consecutive", "label_offset"):
        if key in fields and fields[key] is not None:
            fields[key] = int(fields[key])
    if "collude" in fields:
        fields["collude"] = bool(fields["collude"])
    unknown = set(fields) - {
        f.name for f in dataclasses.fields(FaultRule)
    }
    if unknown:
        raise ValueError(f"unknown chaos rule fields {sorted(unknown)}")
    return FaultRule(**fields)
