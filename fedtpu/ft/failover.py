"""Primary/backup failover state machine.

Reference semantics (``src/server.py:183-264``): the primary pings the backup
1x/s with ``CheckIfPrimaryUp(req=str(recovering))``; the backup's watchdog
promotes itself (via SIGUSR1) if no ping lands within a ~10 s window; when
the real primary returns (first ping carries ``req=="1"``) the acting
primary demotes back to backup. The global model survives failover because
the primary replicates it to the backup every round via SendModel
(``src/server.py:141-142,236-242``).

This module reimplements that protocol as a *pure, event-driven* state
machine — ``on_ping`` / ``check_watchdog`` transitions with an injected
clock, promotion/demotion as callbacks — instead of signal handlers and
un-killable threads. (The reference's demotion path calls
``threading.Thread.terminate()``, which does not exist, so its demotion
would crash with AttributeError — ``src/server.py:230``; a known reference
bug we do not replicate.)
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("fedtpu.ft")


class Role(enum.Enum):
    PRIMARY = "primary"
    BACKUP = "backup"
    ACTING_PRIMARY = "acting_primary"


class FailoverStateMachine:
    """Backup-side protocol logic.

    Events:
      - :meth:`on_ping`   — a CheckIfPrimaryUp arrived from the primary.
      - :meth:`check_watchdog` — periodic liveness check.

    Transitions:
      - BACKUP --[watchdog expiry]--> ACTING_PRIMARY  (on_promote)
      - ACTING_PRIMARY --[ping with recovering=True]--> BACKUP  (on_demote)

    Every transition is a structured event: ``log.warning`` with the
    from/to roles plus (when ``metrics`` — a
    :class:`fedtpu.obs.MetricsRegistry` — is attached) a
    ``fedtpu_ft_failover_transitions_total{to=...}`` increment. The
    machine used to change role silently unless the callbacks logged.
    """

    def __init__(
        self,
        timeout: float = 10.0,
        on_promote: Optional[Callable[[], None]] = None,
        on_demote: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        arm_without_ping: bool = False,
        metrics: Optional[object] = None,
        flight: Optional[object] = None,
    ):
        """``flight``: a :class:`fedtpu.obs.FlightRecorder` — every role
        transition is recorded into it AND triggers a dump, because the
        moments before a promote/demote are exactly the telemetry the lost
        primary's exit-time exporters never wrote."""
        self.timeout = timeout
        self.on_promote = on_promote
        self.on_demote = on_demote
        self.clock = clock
        self._metrics = metrics
        self._flight = flight
        self.role = Role.BACKUP
        # The watchdog arms only once a primary has been heard at least once
        # (deliberate divergence: the reference self-promotes ~10 s after
        # boot even if no primary ever existed, src/server.py:254-264 —
        # promoting with no replicated model serves clients a random init).
        # ``arm_without_ping=True`` restores the reference behavior.
        self._last_ping: Optional[float] = clock() if arm_without_ping else None
        self._lock = threading.Lock()

    def _transition_event(self, src: Role, dst: Role, why: str) -> None:
        log.warning("failover: %s -> %s (%s)", src.value, dst.value, why)
        if self._metrics is not None:
            self._metrics.counter(
                "fedtpu_ft_failover_transitions_total",
                "role transitions by destination role",
                labels={"to": dst.value},
            ).inc()
        if self._flight is not None:
            self._flight.record(
                "failover", src=src.value, dst=dst.value, why=why
            )
            self._flight.dump(reason=f"failover:{dst.value}")

    def on_ping(self, recovering: bool) -> int:
        """Handle one CheckIfPrimaryUp; returns the PingResponse value
        (1 = "I am acting primary and will now demote", matching the
        reference's servicer reply, ``src/server.py:244-252``)."""
        demote = False
        with self._lock:
            self._last_ping = self.clock()
            # The returning primary announces itself with recovering=True;
            # an acting primary yields control back.
            if recovering and self.role is Role.ACTING_PRIMARY:
                self.role = Role.BACKUP
                demote = True
        if demote:
            self._transition_event(
                Role.ACTING_PRIMARY, Role.BACKUP, "primary recovered"
            )
            if self.on_demote is not None:
                self.on_demote()
            return 1
        return 0

    def check_watchdog(self) -> bool:
        """Promote if the primary has been silent past the timeout. Returns
        True when a promotion happened on this call."""
        promote = False
        with self._lock:
            if (
                self.role is Role.BACKUP
                and self._last_ping is not None
                and self.clock() - self._last_ping > self.timeout
            ):
                self.role = Role.ACTING_PRIMARY
                promote = True
        if promote:
            self._transition_event(
                Role.BACKUP, Role.ACTING_PRIMARY,
                f"no primary ping for > {self.timeout:.1f}s",
            )
            if self.on_promote is not None:
                self.on_promote()
        return promote

    def seconds_since_ping(self) -> float:
        """Seconds since the last primary ping; +inf if never pinged."""
        with self._lock:
            if self._last_ping is None:
                return float("inf")
            return self.clock() - self._last_ping


class PrimaryPinger:
    """Primary-side 1 Hz pinger (parity: ``pingBackupServer``,
    ``src/server.py:188-200``): sends ``recovering`` on the first ping after
    (re)start, clears it once delivered. ``send(recovering) -> Optional[int]``
    is injected (None = backup unreachable, which the primary tolerates)."""

    def __init__(
        self,
        send: Callable[[bool], Optional[int]],
        period: float = 1.0,
        recovering: bool = True,
        metrics: Optional[object] = None,
    ):
        self.send = send
        self.period = period
        self.recovering = recovering
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self) -> Optional[int]:
        # Timed like the heartbeat probes (fedtpu_ft_rpc_seconds): the
        # backup-ping RTT trend is the primary's view of control-plane
        # health, and it previously went unmeasured.
        t0 = time.perf_counter()
        result = self.send(self.recovering)
        if self._metrics is not None:
            self._metrics.histogram(
                "fedtpu_ft_rpc_seconds",
                "FT control-plane RPC round-trip seconds by rpc",
                labels={"rpc": "CheckIfPrimaryUp"},
            ).observe(time.perf_counter() - t0)
        if result is not None:
            # Delivered: the backup has seen our recovering flag.
            self.recovering = False
        return result

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class WatchdogRunner:
    """Drives ``FailoverStateMachine.check_watchdog`` on a period — the
    thread-shaped replacement for the reference's ``CheckingIfPrimaryServerUp``
    loop + SIGUSR1 self-kill (``src/server.py:254-264``)."""

    def __init__(self, machine: FailoverStateMachine, period: float = 1.0):
        self.machine = machine
        self.period = period
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.machine.check_watchdog()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
