"""Dynamic, versioned federation membership.

The reference — and fedtpu's own :class:`~fedtpu.ft.heartbeat.ClientRegistry`
until this module — freezes the client set at startup: a dead client can be
*revived* but a new one can never be *admitted*, and a departed one never
removed (reference registry: ``src/server.py:31,281-282``). Production
federations churn: clients join mid-run, vanish silently, return stale, and
the roster an operator sees must be the roster the round loop samples from.

:class:`MembershipTable` makes membership a first-class, mutable, versioned
state:

- **Seats.** Every member holds a stable integer *seat* — its rank, i.e.
  the data shard it trains (``fedtpu.transport.federation.LocalTrainer._shard``)
  and its row in alive masks and round records. Seats of evicted members are
  freed and handed to later joiners (lowest free seat first), so
  :meth:`capacity` — the ``world`` every client partitions against — holds
  steady under steady churn and only grows when the federation genuinely
  outgrows it. This is the transport twin of the sim engine's fixed device
  seats (:mod:`fedtpu.sim.engine`: dynamic client ids mapped onto a
  fixed-size cohort via the values-only ``set_assignment`` swap).
- **Epochs.** Every roster transition (admit / evict) bumps :meth:`version`,
  the membership epoch. The epoch rides the replica payload to the backup
  (:meth:`fedtpu.transport.federation.PrimaryServer.replica_bytes`), so a
  promoted backup inherits the *current* roster, not the startup list.
- **Events.** Transitions are structured: logged, and counted into
  ``metrics`` (``fedtpu_membership_joins_total``,
  ``fedtpu_membership_evictions_total{reason}``) with live
  ``fedtpu_membership_size`` / ``fedtpu_membership_version`` gauges, like
  the existing death/recovery counters.
- **Tolerance.** ``mark_failed`` / ``mark_alive`` / ``is_alive`` on an id
  that is not (or no longer) a member log-and-ignore instead of raising:
  under dynamic membership a late RPC completion from an evicted client is
  ordinary, and a bare ``KeyError`` would kill the collect worker thread
  that reports it.
"""

from __future__ import annotations

import heapq
import logging
import threading
from typing import Dict, Iterable, List, Optional

import numpy as np

log = logging.getLogger("fedtpu.ft")


class MembershipTable:
    """Thread-safe, versioned, seat-stable membership roster.

    ``clients`` seeds the initial members (all alive, seats in list order)
    without logging or counting — construction is not churn. Later
    :meth:`admit` calls add members *dead*: a joiner must be resynced with
    the current global model before it may receive a StartTrain (the same
    resync-before-revive order the heartbeat monitor enforces).
    """

    def __init__(self, clients: Iterable[str] = (),
                 metrics: Optional[object] = None):
        self._seat: Dict[str, int] = {}
        self._alive: Dict[str, bool] = {}
        self._free: List[int] = []  # freed seats, min-heap
        self._capacity = 0
        self._version = 0
        self._lock = threading.Lock()
        self._metrics = metrics
        # Reputation (Byzantine screening, docs/FAULT_TOLERANCE.md):
        # per-client suspicion score — an EWMA of screening verdicts fed by
        # the coordinator (observe_screening) — and, for quarantined
        # members, the count of consecutive quarantined rounds (absent =
        # not quarantined). Both replicate with the roster so a promoted
        # backup inherits who is suspect, not just who is a member.
        self._suspicion: Dict[str, float] = {}
        self._quarantined: Dict[str, int] = {}
        for c in clients:
            if c in self._seat:
                raise ValueError(f"duplicate client id {c!r}")
            self._seat[c] = self._capacity
            self._alive[c] = True
            self._capacity += 1

    # ------------------------------------------------------------ metrics
    def _count(self, name: str, help: str, labels=None) -> None:
        if self._metrics is not None:
            self._metrics.counter(name, help, labels=labels).inc()

    def _gauges(self) -> None:
        """Refresh the size/version gauges (call OUTSIDE the lock)."""
        if self._metrics is None:
            return
        self._metrics.gauge(
            "fedtpu_membership_size",
            "current federation members (alive + dead, evicted excluded)",
        ).set(self.size)
        self._metrics.gauge(
            "fedtpu_membership_version",
            "membership epoch: bumped by every admit/evict transition",
        ).set(self.version)
        with self._lock:
            n_quarantined = len(self._quarantined)
        self._metrics.gauge(
            "fedtpu_membership_quarantined",
            "members currently quarantined (served but updates ignored)",
        ).set(n_quarantined)

    def _unknown(self, op: str, client: str) -> None:
        log.info("membership: %s for non-member %s ignored", op, client)
        self._count(
            "fedtpu_membership_unknown_total",
            "registry operations for non-members, ignored (late RPCs from "
            "evicted clients)",
            labels={"op": op},
        )

    # ------------------------------------------------------ introspection
    @property
    def clients(self) -> List[str]:
        """Current members in seat order (the rank/mask ordering)."""
        with self._lock:
            return sorted(self._seat, key=self._seat.__getitem__)

    @property
    def size(self) -> int:
        with self._lock:
            return len(self._seat)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def capacity(self) -> int:
        """The ``world`` clients partition against: seats ever allocated
        (free seats included — they will be reused before it grows)."""
        with self._lock:
            return self._capacity

    def is_member(self, client: str) -> bool:
        with self._lock:
            return client in self._seat

    def seat_of(self, client: str) -> Optional[int]:
        with self._lock:
            return self._seat.get(client)

    def seat_map(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._seat)

    def active_clients(self) -> List[str]:
        """Live members in seat order; each client's rank (data shard) is
        its stable SEAT, never its position among the currently-live (the
        reference renumbers ranks every round, ``src/server.py:126-129``,
        silently moving shards whenever a peer dies)."""
        with self._lock:
            return sorted(
                (c for c, a in self._alive.items() if a),
                key=self._seat.__getitem__,
            )

    def dead_clients(self) -> List[str]:
        with self._lock:
            return sorted(
                (c for c, a in self._alive.items() if not a),
                key=self._seat.__getitem__,
            )

    def alive_mask(self) -> np.ndarray:
        """Alive flags over the current members in seat order."""
        with self._lock:
            order = sorted(self._seat, key=self._seat.__getitem__)
            return np.array([self._alive[c] for c in order], bool)

    # -------------------------------------------------------- transitions
    def admit(self, client: str) -> int:
        """Admit ``client`` (idempotent: an existing member keeps its seat).
        New members start DEAD — the caller resyncs, then
        :meth:`mark_alive` — and take the lowest free seat, growing
        capacity only when none is free. Returns the member's seat."""
        with self._lock:
            seat = self._seat.get(client)
            if seat is not None:
                return seat
            if self._free:
                seat = heapq.heappop(self._free)
            else:
                seat = self._capacity
                self._capacity += 1
            self._seat[client] = seat
            self._alive[client] = False
            self._version += 1
            version = self._version
        log.info(
            "membership v%d: admitted %s at seat %d (unsynced)",
            version, client, seat,
        )
        self._count(
            "fedtpu_membership_joins_total",
            "members admitted into the federation (join RPCs + rejoins "
            "after eviction; the startup roster is not counted)",
        )
        self._gauges()
        return seat

    def evict(self, client: str, reason: str = "leave") -> bool:
        """Remove ``client`` from the roster, freeing its seat for reuse.
        Returns False (logged, counted as unknown) for a non-member."""
        with self._lock:
            seat = self._seat.pop(client, None)
            if seat is not None:
                del self._alive[client]
                self._suspicion.pop(client, None)
                self._quarantined.pop(client, None)
                heapq.heappush(self._free, seat)
                self._version += 1
                version = self._version
        if seat is None:
            self._unknown("evict", client)
            return False
        log.info(
            "membership v%d: evicted %s from seat %d (%s)",
            version, client, seat, reason,
        )
        self._count(
            "fedtpu_membership_evictions_total",
            "members removed from the federation, by reason",
            labels={"reason": reason},
        )
        self._gauges()
        return True

    def mark_failed(self, client: str) -> None:
        with self._lock:
            was_alive = self._alive.get(client)
            if was_alive is not None:
                self._alive[client] = False
        if was_alive is None:
            self._unknown("mark_failed", client)
            return
        if was_alive:
            log.warning("client %s marked dead", client)
            self._count(
                "fedtpu_ft_client_deaths_total",
                "alive -> dead client transitions",
            )

    def mark_alive(self, client: str) -> None:
        with self._lock:
            was_alive = self._alive.get(client)
            if was_alive is not None:
                self._alive[client] = True
        if was_alive is None:
            self._unknown("mark_alive", client)
            return
        if not was_alive:
            log.info("client %s recovered", client)
            self._count(
                "fedtpu_ft_client_recoveries_total",
                "dead -> alive client transitions",
            )

    def is_alive(self, client: str) -> bool:
        """False for non-members: a late probe of an evicted client reads
        as dead, never as a crash."""
        with self._lock:
            return self._alive.get(client, False)

    # --------------------------------------------------------- reputation
    def observe_screening(self, client: str, flagged: bool,
                          ewma: float = 0.5) -> float:
        """Fold one screening verdict into the member's suspicion EWMA
        (``s' = (1-ewma)*s + ewma*flagged``) and return the new score.
        Non-members log-and-ignore (a late verdict for an evicted client
        is ordinary under churn) and read as 0."""
        with self._lock:
            if client not in self._seat:
                member = False
            else:
                member = True
                s = self._suspicion.get(client, 0.0)
                s = (1.0 - ewma) * s + ewma * (1.0 if flagged else 0.0)
                self._suspicion[client] = s
        if not member:
            self._unknown("observe_screening", client)
            return 0.0
        return s

    def suspicion(self, client: str) -> float:
        with self._lock:
            return self._suspicion.get(client, 0.0)

    def suspicion_map(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._suspicion)

    def quarantine(self, client: str) -> bool:
        """Quarantine a member: still served (broadcasts, StartTrain — it
        keeps generating screening evidence and can redeem itself) but its
        updates are ignored unconditionally by the round loop. Returns
        False for non-members or already-quarantined members."""
        with self._lock:
            if client not in self._seat or client in self._quarantined:
                fresh = False
            else:
                fresh = True
                self._quarantined[client] = 0
        if not fresh:
            if not self.is_member(client):
                self._unknown("quarantine", client)
            return False
        log.warning("membership: client %s QUARANTINED (suspicion %.3f)",
                    client, self.suspicion(client))
        self._count(
            "fedtpu_membership_quarantine_total",
            "members placed in quarantine by the screening reputation "
            "escalation (dedicated counter — not a transient failure)",
        )
        self._gauges()
        return True

    def release(self, client: str) -> bool:
        """Release a quarantined member (suspicion decayed below the
        release threshold). Returns False if it was not quarantined."""
        with self._lock:
            present = self._quarantined.pop(client, None) is not None
        if present:
            log.info("membership: client %s released from quarantine "
                     "(suspicion %.3f)", client, self.suspicion(client))
            self._gauges()
        return present

    def is_quarantined(self, client: str) -> bool:
        with self._lock:
            return client in self._quarantined

    def quarantined_clients(self) -> List[str]:
        with self._lock:
            return sorted(self._quarantined, key=self._seat.__getitem__)

    def tick_quarantine(self, client: str) -> int:
        """Advance a quarantined member's round count; returns the new
        count (0 if not quarantined) — the escalation clock for
        quarantine -> evict."""
        with self._lock:
            if client not in self._quarantined:
                return 0
            self._quarantined[client] += 1
            return self._quarantined[client]

    # -------------------------------------------------------- replication
    def snapshot(self) -> dict:
        """JSON-able roster state for the replica payload / checkpoints.
        Member rows carry the reputation state too (suspicion EWMA +
        quarantined-round count, -1 = not quarantined): a promoted backup
        must inherit who is suspect, or a quarantined attacker would get a
        clean slate from every failover."""
        with self._lock:
            return {
                "version": self._version,
                "capacity": self._capacity,
                "members": [
                    [
                        c, self._seat[c], bool(self._alive[c]),
                        round(self._suspicion.get(c, 0.0), 6),
                        self._quarantined.get(c, -1),
                    ]
                    for c in sorted(self._seat, key=self._seat.__getitem__)
                ],
            }

    def restore(self, snap: dict) -> None:
        """Adopt a replicated :meth:`snapshot` wholesale — the promoted
        backup's roster IS the primary's last replicated roster (alive
        flags included, so a silently-departed client is not re-probed as
        if it were fresh). The local version never goes backwards."""
        members = snap["members"]
        seats = [int(row[1]) for row in members]
        if len(set(seats)) != len(seats):
            raise ValueError("membership snapshot has duplicate seats")
        capacity = max([int(snap["capacity"])] + [s + 1 for s in seats])
        with self._lock:
            self._seat = {str(row[0]): int(row[1]) for row in members}
            self._alive = {str(row[0]): bool(row[2]) for row in members}
            # Pre-reputation snapshots (3-element rows) restore with a
            # clean slate; 5-element rows carry suspicion + quarantine.
            self._suspicion = {
                str(row[0]): float(row[3])
                for row in members if len(row) >= 5 and float(row[3]) > 0
            }
            self._quarantined = {
                str(row[0]): int(row[4])
                for row in members if len(row) >= 5 and int(row[4]) >= 0
            }
            self._capacity = capacity
            taken = set(self._seat.values())
            self._free = [s for s in range(capacity) if s not in taken]
            heapq.heapify(self._free)
            self._version = max(self._version, int(snap["version"]))
            version = self._version
        log.info(
            "membership v%d: restored roster (%d members, capacity %d)",
            version, len(members), capacity,
        )
        self._gauges()

    def status(self) -> dict:
        """The ``/statusz`` membership block."""
        with self._lock:
            order = sorted(self._seat, key=self._seat.__getitem__)
            return {
                "version": self._version,
                "size": len(self._seat),
                "capacity": self._capacity,
                "alive": [c for c in order if self._alive[c]],
                "dead": [c for c in order if not self._alive[c]],
                # The reputation audit surface: who is quarantined, and
                # every nonzero suspicion score (operators watch a rising
                # score rounds before the quarantine flips).
                "quarantined": [c for c in order if c in self._quarantined],
                "suspicion": {
                    c: round(s, 4)
                    for c, s in sorted(self._suspicion.items())
                    if s > 0
                },
            }
