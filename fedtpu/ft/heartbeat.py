"""Client failure detection + recovery.

Reference semantics (``src/server.py:51-101``): any RpcError during
StartTrain/SendModel marks a client inactive; a 1 Hz daemon re-probes
inactive clients with HeartBeat and, on success, restores the channel and
re-pushes the current global model so the client rejoins the next round.

Here that is a :class:`ClientRegistry` (the alive-mask authority — the jitted
engine consumes its mask as ``RoundBatch.alive``) plus a
:class:`HeartbeatMonitor` whose probe/recover/clock hooks are injected, so
the whole recovery loop is testable in-process with fake clients and a fake
clock (the reference's only test was manually killing processes, SURVEY §4).

Since the elastic-membership work the registry is a thin alias over
:class:`fedtpu.ft.membership.MembershipTable` — the mutable, versioned
roster that additionally supports admit/evict (dynamic join/leave) and
tolerates operations on evicted members. A fixed-fleet deployment behaves
exactly as before.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from fedtpu.ft.membership import MembershipTable

log = logging.getLogger("fedtpu.ft")


class ClientRegistry(MembershipTable):
    """Thread-safe alive/dead registry keyed by client id.

    The reference keeps this as a bare dict mutated from three threads with
    no lock (``src/server.py:31,59-62,95-99``); we lock. Alive-state
    *transitions* (not redundant re-marks) are structured events: logged,
    and counted into ``metrics`` (a :class:`fedtpu.obs.MetricsRegistry`)
    when one is attached.

    This is the fixed-roster name for :class:`MembershipTable` — everything
    (including dynamic admit/evict and the log-and-ignore handling of ids
    that are not, or are no longer, members) lives in the base class. Each
    client's rank (data shard) is its stable SEAT — a deliberate divergence
    from the reference, which renumbers ranks among the currently-active
    clients every round (``src/server.py:126-129``) and therefore silently
    moves a client's shard whenever any peer dies.
    """


class HeartbeatMonitor:
    """Re-probe dead clients; resync + revive on heartbeat success.

    ``probe(client) -> bool`` and ``resync(client) -> None`` are injected
    (in production: a HeartBeat RPC and a SendModel push of the current
    global model — exactly the reference's ``checkClientStatus``,
    ``src/server.py:78-101``).

    Probes of MULTIPLE dead clients run concurrently, each on its own
    (daemon) thread, bounded by ``probe_deadline_s`` of wall clock per
    tick: the old sequential pass let one hung probe — a blackholed peer
    whose RPC only fails at its deadline — starve recovery of every other
    dead client for ``deadline * retries`` per victim. A probe that
    overruns the tick budget keeps running in the background and still
    revives its client when it completes; it just stops blocking everyone
    else's recovery. A single dead client is probed inline (no thread), so
    fake-clock tests and the common one-victim case stay synchronous.
    """

    def __init__(
        self,
        registry: ClientRegistry,
        probe: Callable[[str], bool],
        resync: Callable[[str], None],
        period: float = 1.0,
        metrics: Optional[object] = None,
        probe_deadline_s: Optional[float] = None,
    ):
        self.registry = registry
        self.probe = probe
        self.resync = resync
        self.period = period
        self.probe_deadline_s = probe_deadline_s
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _count(self, name: str, help: str) -> None:
        if self._metrics is not None:
            self._metrics.counter(name, help).inc()

    def _probe_one(self, client: str, recovered: List[str],
                   lock: threading.Lock) -> None:
        """One probe + (on success) resync + revive. Resync happens
        *before* the client is marked alive so a revived client never
        receives a StartTrain ahead of the current global model (the
        reference does the same: sendOptimizedModel, then
        ``clients[client] = True``, ``src/server.py:95-99``)."""
        # Time the probe round-trip: these control-plane RPCs used to
        # count misses but never their latency, and probe RTT inflation
        # is the early-warning signal for a congested/flapping edge.
        t0 = time.perf_counter()
        up = self.probe(client)
        if self._metrics is not None:
            self._metrics.histogram(
                "fedtpu_ft_rpc_seconds",
                "FT control-plane RPC round-trip seconds by rpc",
                labels={"rpc": "HeartBeat"},
            ).observe(time.perf_counter() - t0)
        if up:
            try:
                self.resync(client)
            except Exception:
                # Still unreachable; retry next tick.
                self._count(
                    "fedtpu_ft_resync_failures_total",
                    "heartbeat succeeded but the resync push failed",
                )
                return
            self.registry.mark_alive(client)
            with lock:
                recovered.append(client)
        else:
            self._count(
                "fedtpu_ft_heartbeat_misses_total",
                "heartbeat probes of dead clients that stayed dead",
            )

    def tick(self) -> List[str]:
        """One probe pass; returns the clients recovered within the pass
        (seat order). With more than one dead client the probes run
        concurrently and the pass is bounded by ``probe_deadline_s``."""
        dead = self.registry.dead_clients()
        recovered: List[str] = []
        lock = threading.Lock()
        if not dead:
            return recovered
        if len(dead) == 1:
            self._probe_one(dead[0], recovered, lock)
            return recovered
        threads = [
            threading.Thread(
                target=self._probe_one, args=(c, recovered, lock),
                daemon=True,
            )
            for c in dead
        ]
        for t in threads:
            t.start()
        deadline = (
            None if self.probe_deadline_s is None
            else time.monotonic() + self.probe_deadline_s
        )
        for t in threads:
            t.join(
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
        with lock:
            done = list(recovered)
        seat = {c: i for i, c in enumerate(self.registry.clients)}
        return sorted(done, key=lambda c: seat.get(c, len(seat)))

    # ------------------------------------------------------- thread runner
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
