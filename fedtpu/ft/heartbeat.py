"""Client failure detection + recovery.

Reference semantics (``src/server.py:51-101``): any RpcError during
StartTrain/SendModel marks a client inactive; a 1 Hz daemon re-probes
inactive clients with HeartBeat and, on success, restores the channel and
re-pushes the current global model so the client rejoins the next round.

Here that is a :class:`ClientRegistry` (the alive-mask authority — the jitted
engine consumes its mask as ``RoundBatch.alive``) plus a
:class:`HeartbeatMonitor` whose probe/recover/clock hooks are injected, so
the whole recovery loop is testable in-process with fake clients and a fake
clock (the reference's only test was manually killing processes, SURVEY §4).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

log = logging.getLogger("fedtpu.ft")


class ClientRegistry:
    """Thread-safe alive/dead registry keyed by client id.

    The reference keeps this as a bare dict mutated from three threads with
    no lock (``src/server.py:31,59-62,95-99``); we lock. Alive-state
    *transitions* (not redundant re-marks) are structured events: logged,
    and counted into ``metrics`` (a :class:`fedtpu.obs.MetricsRegistry`)
    when one is attached — previously a client death changed state silently
    and only surfaced if the caller happened to log around the call.
    """

    def __init__(self, clients: List[str],
                 metrics: Optional[object] = None):
        self._order = list(clients)
        self._alive: Dict[str, bool] = {c: True for c in clients}
        self._lock = threading.Lock()
        self._metrics = metrics

    @property
    def clients(self) -> List[str]:
        return list(self._order)

    def mark_failed(self, client: str) -> None:
        with self._lock:
            was_alive = self._alive[client]
            self._alive[client] = False
        if was_alive:
            log.warning("client %s marked dead", client)
            if self._metrics is not None:
                self._metrics.counter(
                    "fedtpu_ft_client_deaths_total",
                    "alive -> dead client transitions",
                ).inc()

    def mark_alive(self, client: str) -> None:
        with self._lock:
            was_alive = self._alive[client]
            self._alive[client] = True
        if not was_alive:
            log.info("client %s recovered", client)
            if self._metrics is not None:
                self._metrics.counter(
                    "fedtpu_ft_client_recoveries_total",
                    "dead -> alive client transitions",
                ).inc()

    def is_alive(self, client: str) -> bool:
        with self._lock:
            return self._alive[client]

    def dead_clients(self) -> List[str]:
        with self._lock:
            return [c for c in self._order if not self._alive[c]]

    def active_clients(self) -> List[str]:
        """Clients that participate this round, in registry order. Each
        client's rank (data shard) is its stable REGISTRY index — a
        deliberate divergence from the reference, which renumbers ranks
        among the currently-active clients every round
        (``src/server.py:126-129``) and therefore silently moves a client's
        shard whenever any peer dies. Stable ranks match the simulated
        engine's alive-mask semantics; ``world`` stays the total client
        count in both designs."""
        with self._lock:
            return [c for c in self._order if self._alive[c]]

    def alive_mask(self) -> np.ndarray:
        with self._lock:
            return np.array([self._alive[c] for c in self._order], bool)


class HeartbeatMonitor:
    """Re-probe dead clients; resync + revive on heartbeat success.

    ``probe(client) -> bool`` and ``resync(client) -> None`` are injected
    (in production: a HeartBeat RPC and a SendModel push of the current
    global model — exactly the reference's ``checkClientStatus``,
    ``src/server.py:78-101``).
    """

    def __init__(
        self,
        registry: ClientRegistry,
        probe: Callable[[str], bool],
        resync: Callable[[str], None],
        period: float = 1.0,
        metrics: Optional[object] = None,
    ):
        self.registry = registry
        self.probe = probe
        self.resync = resync
        self.period = period
        self._metrics = metrics
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _count(self, name: str, help: str) -> None:
        if self._metrics is not None:
            self._metrics.counter(name, help).inc()

    def tick(self) -> List[str]:
        """One probe pass; returns the clients recovered this pass.

        Resync happens *before* the client is marked alive so a revived
        client never receives a StartTrain ahead of the current global model
        (the reference does the same: sendOptimizedModel, then
        ``clients[client] = True``, ``src/server.py:95-99``).
        """
        recovered = []
        for client in self.registry.dead_clients():
            # Time the probe round-trip: these control-plane RPCs used to
            # count misses but never their latency, and probe RTT inflation
            # is the early-warning signal for a congested/flapping edge.
            t0 = time.perf_counter()
            up = self.probe(client)
            if self._metrics is not None:
                self._metrics.histogram(
                    "fedtpu_ft_rpc_seconds",
                    "FT control-plane RPC round-trip seconds by rpc",
                    labels={"rpc": "HeartBeat"},
                ).observe(time.perf_counter() - t0)
            if up:
                try:
                    self.resync(client)
                except Exception:
                    # Still unreachable; retry next tick.
                    self._count(
                        "fedtpu_ft_resync_failures_total",
                        "heartbeat succeeded but the resync push failed",
                    )
                    continue
                self.registry.mark_alive(client)
                recovered.append(client)
            else:
                self._count(
                    "fedtpu_ft_heartbeat_misses_total",
                    "heartbeat probes of dead clients that stayed dead",
                )
        return recovered

    # ------------------------------------------------------- thread runner
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period):
            self.tick()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
