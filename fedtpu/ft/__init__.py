"""Fault tolerance: client failure detection + primary/backup failover.

Two distinct mechanisms, as in the reference (SURVEY §5):
- client failure — :mod:`fedtpu.ft.heartbeat`: RpcError marks dead, 1 Hz
  probe revives + resyncs (reference ``src/server.py:51-101``); the registry's
  alive mask feeds the jitted engine's ``RoundBatch.alive``.
- server failure — :mod:`fedtpu.ft.failover`: CheckIfPrimaryUp pings, 10 s
  watchdog, promote/demote state machine with per-round model replication
  (reference ``src/server.py:183-264``), rebuilt event-driven and
  fake-clock-testable.
"""

from fedtpu.ft.heartbeat import ClientRegistry, HeartbeatMonitor
from fedtpu.ft.failover import (
    FailoverStateMachine,
    PrimaryPinger,
    Role,
    WatchdogRunner,
)

__all__ = [
    "ClientRegistry",
    "HeartbeatMonitor",
    "FailoverStateMachine",
    "PrimaryPinger",
    "Role",
    "WatchdogRunner",
]
