"""Fault tolerance: client failure detection + primary/backup failover.

Two distinct mechanisms, as in the reference (SURVEY §5):
- client failure — :mod:`fedtpu.ft.heartbeat`: RpcError marks dead, 1 Hz
  probe revives + resyncs (reference ``src/server.py:51-101``); the registry's
  alive mask feeds the jitted engine's ``RoundBatch.alive``.
- server failure — :mod:`fedtpu.ft.failover`: CheckIfPrimaryUp pings, 10 s
  watchdog, promote/demote state machine with per-round model replication
  (reference ``src/server.py:183-264``), rebuilt event-driven and
  fake-clock-testable.

Plus the machinery that *proves* both under real gRPC:
- fault injection — :mod:`fedtpu.ft.chaos`: a seeded, scriptable
  :class:`FaultSchedule` (delay/drop/error/corrupt/kill) applied via
  channel and server interceptors, armed by ``--chaos-spec`` on the CLIs
  (docs/FAULT_TOLERANCE.md; driven end-to-end by ``tools/chaos_soak.py``).
"""

from fedtpu.ft.heartbeat import ClientRegistry, HeartbeatMonitor
from fedtpu.ft.membership import MembershipTable
from fedtpu.ft.failover import (
    FailoverStateMachine,
    PrimaryPinger,
    Role,
    WatchdogRunner,
)
from fedtpu.ft.chaos import FaultRule, FaultSchedule, parse_spec as parse_chaos_spec

__all__ = [
    "ClientRegistry",
    "HeartbeatMonitor",
    "MembershipTable",
    "FailoverStateMachine",
    "PrimaryPinger",
    "Role",
    "WatchdogRunner",
    "FaultRule",
    "FaultSchedule",
    "parse_chaos_spec",
]
