"""On-device data augmentation.

The reference augments on the host via torchvision transforms — random 32x32
crop with padding 4 plus horizontal flip (``src/main.py:37-42``). fedtpu runs
the same augmentation *inside* the jitted step as pure jnp ops, so it fuses
into the training program and costs no host round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def augment_batch(rng: jax.Array, x: jnp.ndarray, pad: int = 4) -> jnp.ndarray:
    """Random crop (zero-pad) + horizontal flip for an NHWC batch.

    Divergence note: torchvision pads raw pixel 0 *before* normalisation
    (reference transform order, ``src/main.py:37-42``); here the pad is 0 in
    normalised space (≈ the mean pixel) — immaterial for accuracy parity.
    """
    n, h, w, c = x.shape
    crop_rng, flip_rng = jax.random.split(rng)
    padded = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant")

    offs = jax.random.randint(crop_rng, (n, 2), 0, 2 * pad + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    cropped = jax.vmap(crop_one)(padded, offs)

    flip = jax.random.bernoulli(flip_rng, 0.5, (n,))
    flipped = jnp.where(flip[:, None, None, None], cropped[:, :, ::-1, :], cropped)
    return flipped
