"""On-device data augmentation.

The reference augments on the host via torchvision transforms — random 32x32
crop with padding 4 plus horizontal flip (``src/main.py:37-42``). fedtpu runs
the same augmentation *inside* the jitted step as pure jnp ops, so it fuses
into the training program and costs no host round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def augment_batch(
    rng: jax.Array, x: jnp.ndarray, pad: int = 4, crop: bool = True
) -> jnp.ndarray:
    """Random crop (zero-pad) + horizontal flip for an NHWC batch.

    ``crop=False`` (``DataConfig.augment_crop``) skips the crop entirely and
    applies the flip alone. The rng split structure is shared between both
    modes, so the flip decisions are bit-identical whether the crop is on or
    off — the two modes differ ONLY by the crop (test-pinned).

    Divergence note: torchvision pads raw pixel 0 *before* normalisation
    (reference transform order, ``src/main.py:37-42``); here the pad is 0 in
    normalised space (≈ the mean pixel) — immaterial for accuracy parity.

    Implementation is VPU-shaped on purpose. A per-example
    ``vmap(dynamic_slice)`` crop lowers on XLA:TPU to a SERIAL per-example
    slice loop — measured as ~250k ~2 us ops and the single largest consumer
    of the fused-round dispatch on a real v5e chip
    (``artifacts/MFU_PROFILE_r04_presharded.json``; the round-4 trace's
    ``bitcast_dynamic-update-slice_fusion`` at n=248728). One-hot
    selection-MATMULS are no better: a batch of 8192 tiny ``32x40 @ 40x120``
    dots serializes the same way (measured 6x WORSE than the slice loop).
    What vectorizes is shift-accumulate: a crop offset has only ``2*pad+1``
    possible values per axis, so the crop is a weighted sum of the
    ``2*pad+1`` STATIC slices of the padded tensor per axis — unrolled
    elementwise FMAs with per-example one-hot weights, no gathers, no
    matmuls, nothing data-dependent in the op graph. Output is bit-identical
    to the slice formulation: exactly one term per sum has weight 1.0, the
    rest contribute f32 ``0.0 * pixel = 0.0``, and adding zeros preserves
    the value bit-for-bit.

    Precondition: inputs must be FINITE. The zero-weight identity breaks on
    non-finite pixels (``0.0 * inf = nan``), so a NaN/Inf anywhere in a
    padded row window would corrupt neighboring outputs where the
    dynamic-slice formulation would not. Normalised image data is always
    finite, so this is a documented invariant rather than a runtime check.
    """
    n, h, w, c = x.shape
    nshift = 2 * pad + 1
    crop_rng, flip_rng = jax.random.split(rng)
    if crop:
        padded = jnp.pad(
            x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="constant"
        )

        offs = jax.random.randint(crop_rng, (n, 2), 0, nshift)
        w_h = jax.nn.one_hot(offs[:, 0], nshift, dtype=x.dtype)  # [n, nshift]
        w_w = jax.nn.one_hot(offs[:, 1], nshift, dtype=x.dtype)

        rows = sum(
            w_h[:, s, None, None, None] * padded[:, s:s + h, :, :]
            for s in range(nshift)
        )
        cropped = sum(
            w_w[:, s, None, None, None] * rows[:, :, s:s + w, :]
            for s in range(nshift)
        )
    else:
        cropped = x

    flip = jax.random.bernoulli(flip_rng, 0.5, (n,))
    return jnp.where(flip[:, None, None, None], cropped[:, :, ::-1, :], cropped)
