"""Dataset loading.

The reference pulls CIFAR-10 via torchvision with download-on-import
(``src/main.py:48-56``). This environment has no network egress and no
torchvision, so fedtpu reads the standard on-disk formats directly when
present (CIFAR python pickles, MNIST idx files) and otherwise synthesises a
deterministic, class-structured surrogate with the same shapes/statistics —
sufficient for throughput benchmarks and for learning-dynamics tests (the
synthetic task is genuinely learnable: class-conditional means + noise).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import warnings
from typing import Optional, Tuple

import numpy as np

# What the most recent load of each (dataset, split) actually used: "disk" or
# "synthetic". Keyed per split because the loaders find per-split files — a
# disk-backed test split must not relabel a synthetic-fallback train split.
# Consumers (engine metrics, bench_parity) tag their output with this so a
# synthetic-fallback run can never masquerade as a real-data result.
_SOURCE: dict = {}
_WARNED: set = set()


def data_source(dataset: str, split: str = "train") -> str:
    """'disk' | 'synthetic' | 'unknown' — source of the last
    ``load(dataset, split)``."""
    return _SOURCE.get((dataset, split), "unknown")


def _record_source(dataset: str, source: str, split: str) -> None:
    _SOURCE[(dataset, split)] = source
    # *_hard tasks and the plain "synthetic" name are synthetic BY DESIGN
    # (benchmark tasks), not a fallback for missing files — no warning.
    deliberate = dataset == "synthetic" or dataset.endswith("_hard")
    if source == "synthetic" and not deliberate and dataset not in _WARNED:
        _WARNED.add(dataset)
        warnings.warn(
            f"dataset '{dataset}' not found on disk (searched "
            f"{list(_search_dirs())}); falling back to the "
            "deterministic SYNTHETIC surrogate. Throughput numbers are valid; "
            "accuracy numbers are NOT comparable to real-data runs.",
            stacklevel=3,
        )

# Normalisation constants used by the reference transform (src/main.py:39-47).
CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2023, 0.1994, 0.2010], np.float32)
MNIST_MEAN, MNIST_STD = 0.1307, 0.3081

def _search_dirs() -> Tuple[str, ...]:
    # Evaluated per lookup (not at import) so FEDTPU_DATA_DIR set or changed
    # after import — including test monkeypatching — takes effect. An
    # explicitly-set FEDTPU_DATA_DIR is authoritative: the defaults are then
    # NOT searched, so callers can guarantee which copy (or absence) is used.
    explicit = os.environ.get("FEDTPU_DATA_DIR", "")
    if explicit:
        return (explicit,)
    return ("./data", os.path.expanduser("~/data"), "/data")


def _find(*names: str) -> Optional[str]:
    for d in _search_dirs():
        for n in names:
            p = os.path.join(d, n)
            if os.path.exists(p):
                return p
    return None


def _synthetic(
    num: int, shape: Tuple[int, ...], num_classes: int, seed: int, split: str = "train"
) -> Tuple[np.ndarray, np.ndarray]:
    """Class-conditional Gaussian images: learnable, deterministic, no IO.

    The class prototypes depend only on ``seed`` (the dataset identity), so
    train and test splits come from the *same* task; only labels/noise differ
    per split.
    """
    proto_rng = np.random.default_rng(seed)
    protos = proto_rng.normal(0.0, 1.0, size=(num_classes,) + shape).astype(np.float32)
    rng = np.random.default_rng(seed + (1_000_003 if split == "test" else 0) + 1)
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    x = protos[labels] + 0.5 * rng.normal(0.0, 1.0, size=(num,) + shape).astype(
        np.float32
    )
    return x, labels


def _synthetic_hard(
    num: int,
    shape: Tuple[int, ...],
    num_classes: int,
    seed: int,
    split: str = "train",
    informative_dims: int = 64,
    proto_scale: float = 0.3,
    label_noise: float = 0.1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Deliberately NON-saturating synthetic task (VERDICT r3 weak #4).

    The plain ``_synthetic`` task is trivially separable in 3072 dimensions —
    every model saturates at test-acc 1.00 within a round, so accuracy-parity
    columns carry no information. This variant makes the comparison mean
    something, three levers at once:

      * class signal lives only in a LOW-dimensional subspace at small
        scale (``proto_scale``) under unit per-pixel noise — for image
        shapes a spatially-structured coarse grid (see below), otherwise a
        random ``informative_dims``-dimensional flat subspace — so the
        discriminative directions must be *estimated* from limited data and
        accuracy climbs over rounds instead of jumping to the ceiling;
      * ``label_noise`` of the labels are resampled uniformly (train AND
        test, independent draws), capping achievable test accuracy at
        roughly ``(1 - p) + p / num_classes`` — no system can saturate;
      * the signal subspace and prototypes depend only on ``seed``, so train
        and test pose the same task, and torch (bench_reference.py) and
        fedtpu consume byte-identical arrays via the same loader.
    """
    proto_rng = np.random.default_rng(seed)
    if len(shape) == 3 and shape[0] % 4 == 0 and shape[1] % 4 == 0:
        # Spatially-STRUCTURED low-dimensional signal: class prototypes are
        # coarse (H/4 x W/4) random fields nearest-upsampled to full
        # resolution. A purely random flat subspace is invisible to conv
        # models (3x3 locality + pooling average unstructured per-pixel
        # patterns away — measured: smallcnn flatlines at chance on it);
        # block-smooth patterns are learnable by convs AND mlps, while the
        # coarse grid keeps the informative dimensionality low so the
        # discriminative directions must still be estimated from data.
        ch, cw = shape[0] // 4, shape[1] // 4
        coarse = proto_rng.normal(
            0.0, 1.0, size=(num_classes, ch, cw, shape[2])
        ).astype(np.float32)
        protos = proto_scale * coarse.repeat(4, axis=1).repeat(4, axis=2)
    else:
        dim = int(np.prod(shape))
        basis = proto_rng.normal(
            0.0, 1.0, size=(informative_dims, dim)
        ).astype(np.float32)
        basis /= np.linalg.norm(basis, axis=1, keepdims=True)
        coords = proto_rng.normal(
            0.0, 1.0, size=(num_classes, informative_dims)
        ).astype(np.float32)
        protos = (proto_scale * coords @ basis).reshape(
            (num_classes,) + shape
        )
    rng = np.random.default_rng(seed + (1_000_003 if split == "test" else 0) + 1)
    labels = rng.integers(0, num_classes, size=num).astype(np.int32)
    x = protos[labels] + rng.normal(0.0, 1.0, size=(num,) + shape).astype(
        np.float32
    )
    flip = rng.random(num) < label_noise
    noisy = rng.integers(0, num_classes, size=num).astype(np.int32)
    labels = np.where(flip, noisy, labels)
    return x, labels


# The *_hard loaders memoise per (name, split, seed): the parity benches
# call load() repeatedly (train+test, twice per system) and regenerating the
# arrays each time wastes seconds of RNG and transient allocation. Canonical
# sizes are 8192 train / 4096 test — benchmark tasks, not dataset stand-ins,
# and num-invariance holds for any truncation below that (load() slices a
# fixed stream).
_HARD_CACHE: dict = {}


def _hard_cached(name, shape, classes, seed, split):
    n = 8192 if split == "train" else 4096
    key = (name, split, seed)
    if key not in _HARD_CACHE:
        _HARD_CACHE[key] = _synthetic_hard(n, shape, classes, seed, split)
    return _HARD_CACHE[key]


def load_cifar10_hard(split: str = "train", seed: int = 0):
    """Non-saturating 10-class surrogate at CIFAR-10 shapes — ALWAYS
    synthetic (it is a benchmark task, not a stand-in for missing files)."""
    _record_source("cifar10_hard", "synthetic", split)
    return _hard_cached("cifar10_hard", (32, 32, 3), 10, seed + 40, split)


def load_cifar100_hard(split: str = "train", seed: int = 0):
    """Non-saturating 100-class surrogate at CIFAR-100 shapes."""
    _record_source("cifar100_hard", "synthetic", split)
    return _hard_cached("cifar100_hard", (32, 32, 3), 100, seed + 50, split)


def load_cifar10(split: str = "train", seed: int = 0):
    """CIFAR-10 as float32 NHWC in [-2.5, 2.5] (normalised), labels int32."""
    root = _find("cifar-10-batches-py")
    n = 50000 if split == "train" else 10000
    if root is None:
        _record_source("cifar10", "synthetic", split)
        return _synthetic(n, (32, 32, 3), 10, seed, split)
    _record_source("cifar10", "disk", split)
    files = (
        [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
    )
    xs, ys = [], []
    for f in files:
        with open(os.path.join(root, f), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(d[b"data"])
        ys.extend(d[b"labels"])
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x = (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    return x, np.asarray(ys, np.int32)


def load_cifar100(split: str = "train", seed: int = 0):
    root = _find("cifar-100-python")
    n = 50000 if split == "train" else 10000
    if root is None:
        _record_source("cifar100", "synthetic", split)
        return _synthetic(n, (32, 32, 3), 100, seed + 10, split)
    _record_source("cifar100", "disk", split)
    with open(os.path.join(root, split if split != "train" else "train"), "rb") as fh:
        d = pickle.load(fh, encoding="bytes")
    x = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    x = (x.astype(np.float32) / 255.0 - CIFAR10_MEAN) / CIFAR10_STD
    return x, np.asarray(d[b"fine_labels"], np.int32)


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fh:
        magic = struct.unpack(">I", fh.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, fh.read(4 * ndim))
        return np.frombuffer(fh.read(), np.uint8).reshape(dims)


def load_mnist(split: str = "train", seed: int = 0):
    """MNIST as float32 [N, 28, 28, 1] normalised, labels int32."""
    prefix = "train" if split == "train" else "t10k"
    img = _find(f"{prefix}-images-idx3-ubyte", f"{prefix}-images-idx3-ubyte.gz",
                f"MNIST/raw/{prefix}-images-idx3-ubyte")
    lbl = _find(f"{prefix}-labels-idx1-ubyte", f"{prefix}-labels-idx1-ubyte.gz",
                f"MNIST/raw/{prefix}-labels-idx1-ubyte")
    n = 60000 if split == "train" else 10000
    if img is None or lbl is None:
        _record_source("mnist", "synthetic", split)
        x, y = _synthetic(n, (28, 28, 1), 10, seed + 20, split)
        return x, y
    _record_source("mnist", "disk", split)
    x = _read_idx(img).astype(np.float32)[..., None]
    x = (x / 255.0 - MNIST_MEAN) / MNIST_STD
    return x, _read_idx(lbl).astype(np.int32)


_LOADERS = {
    "cifar10": (load_cifar10, (32, 32, 3), 10),
    "cifar100": (load_cifar100, (32, 32, 3), 100),
    "cifar10_hard": (load_cifar10_hard, (32, 32, 3), 10),
    "cifar100_hard": (load_cifar100_hard, (32, 32, 3), 100),
    "mnist": (load_mnist, (28, 28, 1), 10),
    "synthetic": (None, (32, 32, 3), 10),
}


def load(dataset: str, split: str = "train", seed: int = 0, num: Optional[int] = None):
    """Load ``(images, labels)`` for a named dataset; optionally truncate."""
    if dataset not in _LOADERS:
        raise KeyError(f"unknown dataset '{dataset}'; have {sorted(_LOADERS)}")
    loader, shape, classes = _LOADERS[dataset]
    if loader is None:
        _record_source(dataset, "synthetic", split)
        x, y = _synthetic(num or 8192, shape, classes, seed, split)
    else:
        x, y = loader(split, seed)
    if num is not None:
        x, y = x[:num], y[:num]
    return x, y


def dataset_info(dataset: str) -> Tuple[Tuple[int, ...], int]:
    """(input_shape, num_classes) for a named dataset."""
    _, shape, classes = _LOADERS[dataset]
    return shape, classes
