"""Client data partitioners.

The reference shards work by batch index round-robin inside each client's
local epoch: after ``count = (count + 1) % world``, rank ``r`` keeps batch
``i`` iff ``(i + 1) % world == r`` — note the pre-increment, so rank 0 takes
the wraparound batches (reference: ``src/main.py:141-144``). fedtpu implements
that exact rule as ``round_robin`` (for bit-level shard parity) plus the two
partitioners needed by the BASELINE parity configs: ``iid`` and
``dirichlet(alpha)`` label-skew.

All partitioners return a dense integer assignment matrix
``[num_clients, shard_len]`` of example indices plus a validity mask, so the
downstream pipeline keeps static shapes (ragged shards are padded and masked,
never dynamically sized — XLA requires static shapes under jit).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _pad_shards(shards, pad_value=0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a list of 1-D index arrays to equal length; return (idx, mask)."""
    n = max(len(s) for s in shards)
    idx = np.full((len(shards), n), pad_value, dtype=np.int32)
    mask = np.zeros((len(shards), n), dtype=bool)
    for c, s in enumerate(shards):
        idx[c, : len(s)] = s
        mask[c, : len(s)] = True
    return idx, mask


def round_robin(
    num_examples: int, num_clients: int, batch_size: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Reference-exact batch-level round-robin shard.

    Batch ``i`` (of ``floor(num_examples / batch_size)`` full batches — the
    reference's DataLoader drops nothing but its final ragged batch is rarely
    hit; we drop the remainder for static shapes) goes to client
    ``(i + 1) % num_clients``, reproducing ``src/main.py:141-144`` including
    the pre-increment shift.
    """
    num_batches = num_examples // batch_size
    shards = [[] for _ in range(num_clients)]
    for i in range(num_batches):
        r = (i + 1) % num_clients
        shards[r].extend(range(i * batch_size, (i + 1) * batch_size))
    return _pad_shards([np.asarray(s, dtype=np.int32) for s in shards])


def iid(
    num_examples: int, num_clients: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Uniform random equal split."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_examples).astype(np.int32)
    shards = np.array_split(perm, num_clients)
    return _pad_shards(shards)


def dirichlet(
    labels: np.ndarray,
    num_clients: int,
    alpha: float = 0.5,
    seed: int = 0,
    min_size: int = 1,
) -> Tuple[np.ndarray, np.ndarray]:
    """Label-skew non-IID split: per class, proportions ~ Dirichlet(alpha).

    Standard federated-learning benchmark partitioner (BASELINE config 2:
    "non-IID Dirichlet(0.5)"). Resamples until every client holds at least
    ``min_size`` examples.
    """
    labels = np.asarray(labels)
    num_classes = int(labels.max()) + 1
    rng = np.random.default_rng(seed)
    for _ in range(100):
        shards = [[] for _ in range(num_clients)]
        for k in range(num_classes):
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            props = rng.dirichlet([alpha] * num_clients)
            cuts = (np.cumsum(props) * len(idx_k)).astype(int)[:-1]
            for c, part in enumerate(np.split(idx_k, cuts)):
                shards[c].extend(part.tolist())
        if min(len(s) for s in shards) >= min_size:
            break
    shards = [np.asarray(sorted(s), dtype=np.int32) for s in shards]
    return _pad_shards(shards)


def make_client_batches(
    images: np.ndarray,
    labels: np.ndarray,
    idx: np.ndarray,
    mask: np.ndarray,
    batch_size: int,
    steps_per_round: int,
    seed: int = 0,
    shuffle: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Materialise per-client batch tensors with static shapes.

    Returns ``(x, y, step_mask)`` shaped ``[clients, steps, batch, ...]``,
    ``[clients, steps, batch]`` and ``[clients, steps]``. Shards shorter than
    ``steps_per_round * batch_size`` wrap around (sampling with replacement at
    the tail), so every client sees full batches and the mask only kills steps
    for clients with no data at all. The reference iterates an *unshuffled*
    loader in federated mode (``src/main.py:140``); ``shuffle=False`` matches.
    """
    num_clients = idx.shape[0]
    need = steps_per_round * batch_size
    xs, ys, ms = [], [], []
    rng = np.random.default_rng(seed)
    for c in range(num_clients):
        own = idx[c][mask[c]]
        if shuffle and len(own):
            own = rng.permutation(own)
        if len(own) == 0:
            xs.append(np.zeros((need,) + images.shape[1:], images.dtype))
            ys.append(np.zeros((need,), labels.dtype))
            ms.append(np.zeros((steps_per_round,), bool))
            continue
        reps = int(np.ceil(need / len(own)))
        take = np.tile(own, reps)[:need]
        xs.append(images[take])
        ys.append(labels[take])
        ms.append(np.ones((steps_per_round,), bool))
    x = np.stack(xs).reshape((num_clients, steps_per_round, batch_size) + images.shape[1:])
    y = np.stack(ys).reshape((num_clients, steps_per_round, batch_size))
    step_mask = np.stack(ms)
    return x, y, step_mask


def shard_sizes(mask: np.ndarray) -> np.ndarray:
    """Per-client example counts (the weights for weighted FedAvg)."""
    return mask.sum(axis=1).astype(np.float32)
