"""Client data partitioners.

The reference shards work by batch index round-robin inside each client's
local epoch: after ``count = (count + 1) % world``, rank ``r`` keeps batch
``i`` iff ``(i + 1) % world == r`` — note the pre-increment, so rank 0 takes
the wraparound batches (reference: ``src/main.py:141-144``). fedtpu implements
that exact rule as ``round_robin`` (for bit-level shard parity) plus the two
partitioners needed by the BASELINE parity configs: ``iid`` and
``dirichlet(alpha)`` label-skew.

All partitioners return a dense integer assignment matrix
``[num_clients, shard_len]`` of example indices plus a validity mask, so the
downstream pipeline keeps static shapes (ragged shards are padded and masked,
never dynamically sized — XLA requires static shapes under jit).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _pad_shards(shards, pad_value=0) -> Tuple[np.ndarray, np.ndarray]:
    """Pad a list of 1-D index arrays to equal length; return (idx, mask)."""
    n = max(len(s) for s in shards)
    idx = np.full((len(shards), n), pad_value, dtype=np.int32)
    mask = np.zeros((len(shards), n), dtype=bool)
    for c, s in enumerate(shards):
        idx[c, : len(s)] = s
        mask[c, : len(s)] = True
    return idx, mask


def round_robin(
    num_examples: int, num_clients: int, batch_size: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Reference-exact batch-level round-robin shard.

    Batch ``i`` (of ``floor(num_examples / batch_size)`` full batches — the
    reference's DataLoader drops nothing but its final ragged batch is rarely
    hit; we drop the remainder for static shapes) goes to client
    ``(i + 1) % num_clients``, reproducing ``src/main.py:141-144`` including
    the pre-increment shift.
    """
    num_batches = num_examples // batch_size
    shards = [[] for _ in range(num_clients)]
    for i in range(num_batches):
        r = (i + 1) % num_clients
        shards[r].extend(range(i * batch_size, (i + 1) * batch_size))
    return _pad_shards([np.asarray(s, dtype=np.int32) for s in shards])


def iid(
    num_examples: int, num_clients: int, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Uniform random equal split."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(num_examples).astype(np.int32)
    shards = np.array_split(perm, num_clients)
    return _pad_shards(shards)


def _owner_to_shards(owner: np.ndarray, num_clients: int) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized shard build from an ``owner[example] = client`` map.

    Replaces the per-class Python-list ``shards[c].extend(...)`` construction
    (O(num_examples) list appends — measured seconds at a 10k-client
    population) with one stable argsort + one scatter. Each client's row is
    its example ids in ascending order, matching the ``sorted(s)``
    normalisation of the list-based build bit-for-bit.
    """
    owner = np.asarray(owner, np.int64)
    counts = np.bincount(owner, minlength=num_clients)
    # Stable sort over example ids (which are already ascending) groups by
    # client while keeping each group's ids ascending.
    order = np.argsort(owner, kind="stable")
    L = max(int(counts.max()), 1)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(owner)) - np.repeat(starts, counts)
    idx = np.zeros((num_clients, L), dtype=np.int32)
    mask = np.zeros((num_clients, L), dtype=bool)
    idx[owner[order], pos] = order.astype(np.int32)
    mask[owner[order], pos] = True
    return idx, mask


def dirichlet(
    labels: np.ndarray,
    num_clients: int,
    alpha: float = 0.5,
    seed: int = 0,
    min_size: int = 1,
    min_size_action: str = "topup",
) -> Tuple[np.ndarray, np.ndarray]:
    """Label-skew non-IID split: per class, proportions ~ Dirichlet(alpha).

    Standard federated-learning benchmark partitioner (BASELINE config 2:
    "non-IID Dirichlet(0.5)"). Resamples until every client holds at least
    ``min_size`` examples — and, unlike the original implementation (which
    silently returned under-``min_size`` clients after 100 failed resamples),
    a persistent deficit is now *signalled*: with
    ``min_size_action='topup'`` the deficient clients are deterministically
    topped up from the largest clients (highest example ids move first) under
    a ``warnings.warn``; ``'raise'`` raises instead. Draws that satisfy
    ``min_size`` are bit-identical to the historical output (same RNG call
    sequence, same assignment rule, ascending ids per client).
    """
    if min_size_action not in ("topup", "raise"):
        raise ValueError(
            f"unknown min_size_action {min_size_action!r}; have topup | raise"
        )
    labels = np.asarray(labels)
    num_classes = int(labels.max()) + 1
    rng = np.random.default_rng(seed)
    owner = np.empty(len(labels), np.int64)
    for _ in range(100):
        for k in range(num_classes):
            idx_k = np.where(labels == k)[0]
            rng.shuffle(idx_k)
            props = rng.dirichlet([alpha] * num_clients)
            cuts = (np.cumsum(props) * len(idx_k)).astype(int)[:-1]
            # np.split(idx_k, cuts) gives client c the positions in
            # [cuts[c-1], cuts[c]) — i.e. the count of cuts <= position.
            owner[idx_k] = np.searchsorted(
                cuts, np.arange(len(idx_k)), side="right"
            )
        counts = np.bincount(owner, minlength=num_clients)
        if counts.min() >= min_size:
            break
    counts = np.bincount(owner, minlength=num_clients)
    if counts.min() < min_size:
        deficit = int(np.sum(np.maximum(min_size - counts, 0)))
        if min_size_action == "raise":
            raise ValueError(
                f"dirichlet(alpha={alpha}) could not satisfy "
                f"min_size={min_size} after 100 resamples "
                f"({int((counts < min_size).sum())} clients short by "
                f"{deficit} examples total)"
            )
        import warnings

        warnings.warn(
            f"dirichlet(alpha={alpha}) left {int((counts < min_size).sum())} "
            f"client(s) below min_size={min_size} after 100 resamples; "
            f"deterministically topping up {deficit} example(s) from the "
            "largest client(s)",
            stacklevel=2,
        )
        for c in np.flatnonzero(counts < min_size):
            while counts[c] < min_size:
                donor = int(np.argmax(counts))
                # Deterministic rule: the donor's highest example id moves.
                moved = np.flatnonzero(owner == donor)[-1]
                owner[moved] = c
                counts[donor] -= 1
                counts[c] += 1
    return _owner_to_shards(owner, num_clients)


def make_client_batches(
    images: np.ndarray,
    labels: np.ndarray,
    idx: np.ndarray,
    mask: np.ndarray,
    batch_size: int,
    steps_per_round: int,
    seed: int = 0,
    shuffle: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Materialise per-client batch tensors with static shapes.

    Returns ``(x, y, step_mask)`` shaped ``[clients, steps, batch, ...]``,
    ``[clients, steps, batch]`` and ``[clients, steps]``. Shards shorter than
    ``steps_per_round * batch_size`` wrap around (sampling with replacement at
    the tail), so every client sees full batches and the mask only kills steps
    for clients with no data at all. The reference iterates an *unshuffled*
    loader in federated mode (``src/main.py:140``); ``shuffle=False`` matches.
    """
    num_clients = idx.shape[0]
    need = steps_per_round * batch_size
    xs, ys, ms = [], [], []
    rng = np.random.default_rng(seed)
    for c in range(num_clients):
        own = idx[c][mask[c]]
        if shuffle and len(own):
            own = rng.permutation(own)
        if len(own) == 0:
            xs.append(np.zeros((need,) + images.shape[1:], images.dtype))
            ys.append(np.zeros((need,), labels.dtype))
            ms.append(np.zeros((steps_per_round,), bool))
            continue
        reps = int(np.ceil(need / len(own)))
        take = np.tile(own, reps)[:need]
        xs.append(images[take])
        ys.append(labels[take])
        ms.append(np.ones((steps_per_round,), bool))
    x = np.stack(xs).reshape((num_clients, steps_per_round, batch_size) + images.shape[1:])
    y = np.stack(ys).reshape((num_clients, steps_per_round, batch_size))
    step_mask = np.stack(ms)
    return x, y, step_mask


def shard_sizes(mask: np.ndarray) -> np.ndarray:
    """Per-client example counts (the weights for weighted FedAvg)."""
    return mask.sum(axis=1).astype(np.float32)
