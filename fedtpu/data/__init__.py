from fedtpu.data import partition
from fedtpu.data.datasets import dataset_info, load
from fedtpu.data.augment import augment_batch

__all__ = ["partition", "load", "dataset_info", "augment_batch"]
