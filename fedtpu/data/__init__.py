from fedtpu.data import partition
from fedtpu.data.datasets import data_source, dataset_info, load
from fedtpu.data.augment import augment_batch

__all__ = ["partition", "load", "dataset_info", "data_source", "augment_batch"]
