"""Device-resident data pipeline.

The dataset and the client-assignment matrix are uploaded to HBM ONCE; each
round's static-shape batch tensors are then gathered on device *inside* the
jitted round program. This replaces a per-round host rebuild (~600 MB of
numpy fancy-indexing + H2D transfer at the 64-client CIFAR bench config) with
a fused XLA gather, keeping the steady-state round compute-bound.

The reference's analogue is its torch DataLoader re-iterated every epoch on
the host (``src/main.py:140-144``); there is deliberately no counterpart to
this module there — it exists because the TPU round loop must not block on
host data preparation.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from fedtpu.config import RoundConfig
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    make_round_step,
)


def round_take_indices(
    idx: jnp.ndarray,
    mask: jnp.ndarray,
    need: int,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """Per-client gather indices for one round, entirely on device.

    ``idx``/``mask``: the padded ``[clients, shard_len]`` assignment from
    :mod:`fedtpu.data.partition`. Returns ``take: [clients, need]`` where each
    client's row cycles through its own shard (in random order when ``rng`` is
    given, else in shard order — the reference iterates an *unshuffled* loader
    in federated mode, ``src/main.py:140``). Shards shorter than ``need`` wrap
    around, exactly like the host-side ``make_client_batches``. Clients with
    empty shards return index 0 rows; callers mask their steps out.
    """
    shard_len = idx.shape[1]
    lengths = jnp.maximum(mask.sum(axis=1), 1)  # [clients]
    if rng is None:
        ordered = idx
    else:
        # Random order with invalid slots sorted last: uniform keys, +inf on
        # padding, argsort. One independent permutation per client per round.
        keys = jax.random.uniform(rng, idx.shape)
        keys = jnp.where(mask, keys, jnp.inf)
        order = jnp.argsort(keys, axis=1)
        ordered = jnp.take_along_axis(idx, order, axis=1)
    pos = jnp.arange(need, dtype=jnp.int32)[None, :] % lengths[:, None]
    return jnp.take_along_axis(ordered, pos.astype(jnp.int32), axis=1)


def make_data_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    compressor=None,
    shuffle: bool = True,
    axis_name: Optional[str] = None,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
) -> Callable[..., Tuple[FederatedState, RoundMetrics]]:
    """Round step that gathers its own batches from the device-resident
    dataset: ``step(state, images, labels, idx, mask, weights, alive,
    data_key)``. The gather + reshape fuse into the same XLA program as the
    local training scan and the FedAvg aggregation, so the host contributes
    nothing per round beyond the (tiny) ``alive`` mask.

    With ``axis_name`` set this is the per-shard body for ``shard_map`` over
    a clients mesh (see :func:`make_sharded_data_round_step`): ``idx``,
    ``mask``, ``weights`` and ``alive`` are then the LOCAL client rows while
    ``images``/``labels`` are replicated, so each device gathers only its own
    clients' batches and aggregation psums over the mesh.

    ``stream`` (default: ``cfg.remat``, since both matter for the same
    big-model configs): gather each step's batch INSIDE the training scan
    instead of materialising all ``[clients, steps, batch, ...]`` up front —
    the full tensor never exists in HBM, only per-step batches. Numerically
    identical; the default stays off for small models where one big fused
    gather is faster.
    """
    if stream is None:
        stream = cfg.remat
    shape = tuple(image_shape or cfg.image_size)
    base = make_round_step(
        model, cfg, compressor, axis_name=axis_name, stream=stream,
        image_shape=shape,
    )
    batch_size = cfg.data.batch_size
    need = steps * batch_size

    def step(
        state: FederatedState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
    ) -> Tuple[FederatedState, RoundMetrics]:
        n = idx.shape[0]
        rng = None
        if shuffle:
            rng = jax.random.fold_in(data_key, state.round_idx)
            if axis_name is not None:
                # Decorrelate shuffles across mesh shards (the body sees only
                # its local client rows; without this every device would draw
                # the same per-row permutation pattern).
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        take = round_take_indices(idx, mask, need, rng)
        has_data = mask.any(axis=1)
        step_mask = jnp.broadcast_to(has_data[:, None], (n, steps))
        if stream:
            takes = take.reshape((n, steps, batch_size))
            batch = RoundBatch(
                x=takes, y=takes, step_mask=step_mask, weights=weights,
                alive=alive,
            )
            return base(state, batch, images, labels)
        # Dataset may be stored flat ([N, H*W*C] — the TPU-friendly layout,
        # reshaped back via image_shape) or as images (shape from the array).
        tail = shape if images.ndim == 2 else tuple(images.shape[1:])
        x = images[take].reshape((n, steps, batch_size) + tail)
        y = labels[take].reshape((n, steps, batch_size))
        batch = RoundBatch(
            x=x, y=y, step_mask=step_mask, weights=weights, alive=alive
        )
        return base(state, batch)

    return step


def make_multi_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    num_rounds: int,
    compressor=None,
    shuffle: bool = True,
    axis_name: Optional[str] = None,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
) -> Callable[..., Tuple[FederatedState, RoundMetrics]]:
    """``num_rounds`` federated rounds as ONE XLA program (``lax.scan``).

    The reference pays a full host round-trip per round — thread fan-out,
    blocking RPCs, checkpoint files (``src/server.py:120-153``). The jitted
    single-round step already collapses that to one dispatch per round, but
    on a remote/tunneled device even dispatch+sync latency dominates small
    rounds. Scanning the round body keeps the WHOLE multi-round run on
    device: per-round batches are still gathered fresh inside each scan
    iteration (``round_take_indices`` folds ``round_idx`` into the shuffle
    key, so round r's batches are identical to the sequential path's), and
    per-round metrics come back stacked ``[num_rounds, ...]``.

    Signature matches :func:`make_data_round_step` except ``alive`` is
    ``[num_rounds, clients]`` — one participation mask per round, so
    heartbeat deaths / client subsampling still vary per round inside the
    fused program. Returns ``(final_state, metrics_stacked)``.
    """
    body = make_data_round_step(
        model, cfg, steps, compressor, shuffle=shuffle, axis_name=axis_name,
        stream=stream, image_shape=image_shape,
    )

    def multi(
        state: FederatedState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
    ) -> Tuple[FederatedState, RoundMetrics]:
        def scan_body(st, alive_r):
            return body(st, images, labels, idx, mask, weights, alive_r,
                        data_key)

        return jax.lax.scan(scan_body, state, alive, length=num_rounds)

    return multi


def _shard_wrap(body, cfg: RoundConfig, mesh, alive_ndim: int, donate: bool):
    """Common shard_map+jit wrapper for the data-round bodies.

    Per-client state/assignment shard on the clients axis; the dataset is
    replicated to every device (CIFAR-scale data fits HBM many times over,
    and replication keeps the gather local — no cross-chip data motion);
    FedAvg psums over ICI. ``alive_ndim`` is 1 for a single-round body
    (``[clients]``) or 2 for the multi-round scan (``[rounds, clients]``,
    client axis sharded).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from fedtpu.parallel.sharded import state_specs

    axis = cfg.mesh_axis
    if cfg.fed.num_clients % mesh.devices.size:
        raise ValueError(
            f"num_clients={cfg.fed.num_clients} not divisible by mesh size "
            f"{mesh.devices.size}"
        )
    sharded = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(
            state_specs(axis),  # state
            P(),                # images (replicated)
            P(),                # labels (replicated)
            P(axis),            # idx
            P(axis),            # mask
            P(axis),            # weights
            P(axis) if alive_ndim == 1 else P(None, axis),  # alive
            P(),                # data_key
        ),
        out_specs=(
            state_specs(axis),
            # Scalar metrics replicate; per_client_loss shards on its client
            # axis — axis 0 for one round, axis 1 when the scan stacks [R, n].
            RoundMetrics(
                P(), P(), P(), P(),
                P(axis) if alive_ndim == 1 else P(None, axis),
            ),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_sharded_multi_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    num_rounds: int,
    mesh,
    compressor=None,
    shuffle: bool = True,
    donate: bool = True,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
):
    """Mesh-parallel form of :func:`make_multi_round_step`: the scan runs
    inside ``shard_map``, so a whole multi-round run is one program with one
    psum per round over ICI and zero host involvement between rounds.
    ``alive`` is ``[num_rounds, clients]``, sharded on its client axis."""
    body = make_multi_round_step(
        model, cfg, steps, num_rounds, compressor, shuffle=shuffle,
        axis_name=cfg.mesh_axis, stream=stream, image_shape=image_shape,
    )
    return _shard_wrap(body, cfg, mesh, alive_ndim=2, donate=donate)


def make_sharded_data_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    mesh,
    compressor=None,
    shuffle: bool = True,
    donate: bool = True,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
):
    """Mesh-parallel round step with the on-device gather inside each shard.

    Call signature matches :func:`make_data_round_step`; inputs must be
    placed with :func:`shard_data_arrays` / :func:`fedtpu.parallel.shard_state`.
    Sharding layout: see :func:`_shard_wrap`.
    """
    body = make_data_round_step(
        model, cfg, steps, compressor, shuffle=shuffle, axis_name=cfg.mesh_axis,
        stream=stream, image_shape=image_shape,
    )
    return _shard_wrap(body, cfg, mesh, alive_ndim=1, donate=donate)
