"""Device-resident data pipeline.

The dataset and the client-assignment matrix are uploaded to HBM ONCE; each
round's static-shape batch tensors are then produced on device *inside* the
jitted round program. This replaces a per-round host rebuild (~600 MB of
numpy fancy-indexing + H2D transfer at the 64-client CIFAR bench config).

Two HBM layouts (``DataConfig.device_layout``):

* ``"presharded"`` (default): the dataset is reorganised ONCE at upload into
  ``[clients, 2*shard_len, features]`` (:func:`preshard_arrays`), so each
  round's batches are ONE contiguous ``dynamic_slice`` at a per-round
  rotation offset. XLA:TPU lowers a computed-index row-gather into a serial
  ~2 us dynamic-slice loop per row, so the layout converts per-round data
  extraction from O(rows) serial ops to one DMA. Attribution honesty
  (round-4 trace history): the first trace blamed the batch gather for ~80%
  of the fused dispatch, but re-measuring after this layout shipped moved
  the bench only 246→250 client-epochs/s/chip — the dominant serial loop
  was actually the per-example augmentation crop + CE label gather (fixed
  in ``fedtpu/data/augment.py`` / ``fedtpu/ops/losses.py``; see
  ``artifacts/MFU_PROFILE_r04*.json`` and BASELINE.md). Presharded remains
  the default for the DMA-shaped extraction, the per-client sharding under
  ``shard_map``, and the bf16 residency it composes with.
* ``"gather"``: dataset stays ``[N, features]``; per-round index gather.
  Exact per-round permutation shuffling and no 2x data HBM, at the measured
  gather cost. This is the exact semantics of the rounds-1-3 artifacts.
  It is also what the massive-cohort simulation layer (:mod:`fedtpu.sim`)
  requires: the assignment matrix stays a *program input* of static shape,
  so swapping which population clients the cohort's device slots represent
  is a values-only ``idx``/``mask`` replacement per round
  (:meth:`fedtpu.core.engine.Federation.set_assignment`) — no recompile,
  no re-upload of the dataset. Presharding would bake the assignment into
  the uploaded per-client rows, costing an O(cohort·shard·features)
  re-preshard every cohort change.

The reference's analogue is its torch DataLoader re-iterated every epoch on
the host (``src/main.py:140-144``); there is deliberately no counterpart to
this module there — it exists because the TPU round loop must not block on
host data preparation.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from fedtpu.utils.platform import shard_map
from fedtpu.config import RoundConfig
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    make_round_step,
)


def round_take_indices(
    idx: jnp.ndarray,
    mask: jnp.ndarray,
    need: int,
    rng: Optional[jax.Array] = None,
) -> jnp.ndarray:
    """Per-client gather indices for one round, entirely on device.

    ``idx``/``mask``: the padded ``[clients, shard_len]`` assignment from
    :mod:`fedtpu.data.partition`. Returns ``take: [clients, need]`` where each
    client's row cycles through its own shard (in random order when ``rng`` is
    given, else in shard order — the reference iterates an *unshuffled* loader
    in federated mode, ``src/main.py:140``). Shards shorter than ``need`` wrap
    around, exactly like the host-side ``make_client_batches``. Clients with
    empty shards return index 0 rows; callers mask their steps out.
    """
    shard_len = idx.shape[1]
    lengths = jnp.maximum(mask.sum(axis=1), 1)  # [clients]
    if rng is None:
        ordered = idx
    else:
        # Random order with invalid slots sorted last: uniform keys, +inf on
        # padding, argsort. One independent permutation per client per round.
        keys = jax.random.uniform(rng, idx.shape)
        keys = jnp.where(mask, keys, jnp.inf)
        order = jnp.argsort(keys, axis=1)
        ordered = jnp.take_along_axis(idx, order, axis=1)
    pos = jnp.arange(need, dtype=jnp.int32)[None, :] % lengths[:, None]
    return jnp.take_along_axis(ordered, pos.astype(jnp.int32), axis=1)


def preshard_arrays(images, labels, idx, mask):
    """Reorganise the dataset into the per-client contiguous layout, ONCE.

    Returns ``(xs_c, ys_c)`` with ``xs_c: [clients, 2*L, features]`` float32
    and ``ys_c: [clients, 2*L]`` int32, where ``L = idx.shape[1]`` (the
    padded shard length). Each client's row is its own shard CYCLED to fill
    ``L`` (a shard of ``k`` examples repeats every ``k`` slots — the same
    wraparound rule as :func:`round_take_indices`'s ``pos % length``), then
    stored twice along the shard axis so any rotated window of length
    ``<= L`` is one contiguous slice. Images are flattened to rows
    (``[*, H*W*C]``): flat rows tile exactly under TPU tiled layouts where
    NHWC tensors pad ~4x. Clients with empty shards get zero rows; callers
    mask them out via ``mask.any(axis=1)`` exactly as in the gather layout.

    Cost: ``clients * 2L * features`` floats — 2x the dataset when shards
    are balanced (L ~= N/clients), but L is the padded MAX shard length, so
    a skewed non-iid partition (low-alpha dirichlet) pays
    ``clients * 2 * max_shard`` instead. The engine falls back to the gather
    layout automatically when this footprint is disproportionate
    (:meth:`fedtpu.core.engine.Federation._ensure_device_data` docs). Under
    ``shard_map`` the rows shard by CLIENT, so each device stores only its
    own clients' data (the gather layout replicates the full dataset to
    every device).
    """
    import numpy as np

    images = np.asarray(images, np.float32).reshape(len(images), -1)
    labels = np.asarray(labels, np.int32)
    idx = np.asarray(idx)
    mask = np.asarray(mask, bool)
    n, L = idx.shape
    xs = np.zeros((n, L, images.shape[1]), np.float32)
    ys = np.zeros((n, L), np.int32)
    for c in range(n):
        own = idx[c][mask[c]]
        if len(own):
            cyc = own[np.arange(L) % len(own)]
            xs[c] = images[cyc]
            ys[c] = labels[cyc]
    return (
        np.concatenate([xs, xs], axis=1),
        np.concatenate([ys, ys], axis=1),
    )


def _round_offset(labels, shuffle, rng):
    """Per-round rotation offset into the doubled presharded axis, shared
    across clients (and across mesh shards — no ``axis_index`` fold, so the
    sharded program is bit-identical to the single-program one). Unshuffled
    mode starts every round at the shard head, matching the reference's
    restart-per-epoch unshuffled loader (``src/main.py:140``) and the gather
    layout's ``shuffle=False`` prefix rule bit-for-bit."""
    L = labels.shape[1] // 2
    if rng is None or not shuffle:
        return jnp.zeros((), jnp.int32), L
    return jax.random.randint(rng, (), 0, L, dtype=jnp.int32), L


def make_data_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    compressor=None,
    shuffle: bool = True,
    axis_name: Optional[str] = None,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
) -> Callable[..., Tuple[FederatedState, RoundMetrics]]:
    """Round step that extracts its own batches from the device-resident
    dataset: ``step(state, images, labels, idx, mask, weights, alive,
    data_key)``. The extraction + reshape fuse into the same XLA program as
    the local training scan and the FedAvg aggregation, so the host
    contributes nothing per round beyond the (tiny) ``alive`` mask.

    ``layout`` selects the HBM layout (see module docstring): with
    ``"presharded"``, ``images``/``labels`` are the ``[clients, 2L, ...]``
    outputs of :func:`preshard_arrays` and the per-round batch tensor is one
    contiguous rotated slice; ``idx`` is ignored (``mask`` still provides
    the has-data/weight masking). With ``"gather"`` they are the flat
    ``[N, ...]`` dataset and batches come from a per-round index gather.
    Shuffling semantics differ deliberately: gather reshuffles each client's
    shard into fresh batches every round (a true per-round permutation);
    presharded rotates the fixed shard order by a shared random offset each
    round ("shuffle once, rotate per round" — the standard trade for making
    the extraction a contiguous DMA). With ``shuffle=False`` the two layouts
    are bit-identical.

    With ``axis_name`` set this is the per-shard body for ``shard_map`` over
    a clients mesh (see :func:`make_sharded_data_round_step`): ``idx``,
    ``mask``, ``weights`` and ``alive`` are then the LOCAL client rows while
    ``images``/``labels`` are replicated, so each device gathers only its own
    clients' batches and aggregation psums over the mesh.

    ``stream`` (default: ``cfg.remat``, since both matter for the same
    big-model configs): gather each step's batch INSIDE the training scan
    instead of materialising all ``[clients, steps, batch, ...]`` up front —
    the full tensor never exists in HBM, only per-step batches. Numerically
    identical; the default stays off for small models where one big fused
    gather is faster.
    """
    if stream is None:
        stream = cfg.remat
    if layout not in ("presharded", "gather"):
        raise ValueError(
            f"unknown device_layout {layout!r}; have presharded | gather"
        )
    shape = tuple(image_shape or cfg.image_size)
    base = make_round_step(
        model, cfg, compressor, axis_name=axis_name,
        stream=(layout if stream else False), image_shape=shape,
    )
    batch_size = cfg.data.batch_size
    need = steps * batch_size

    def gather_step(
        state: FederatedState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
        attack_seats=None,
    ) -> Tuple[FederatedState, RoundMetrics]:
        n = idx.shape[0]
        atk = () if attack_seats is None else attack_seats
        rng = None
        if shuffle:
            rng = jax.random.fold_in(data_key, state.round_idx)
            if axis_name is not None:
                # Decorrelate shuffles across mesh shards (the body sees only
                # its local client rows; without this every device would draw
                # the same per-row permutation pattern).
                rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        take = round_take_indices(idx, mask, need, rng)
        has_data = mask.any(axis=1)
        step_mask = jnp.broadcast_to(has_data[:, None], (n, steps))
        if stream:
            takes = take.reshape((n, steps, batch_size))
            batch = RoundBatch(
                x=takes, y=takes, step_mask=step_mask, weights=weights,
                alive=alive, attack_seats=atk,
            )
            return base(state, batch, images, labels)
        # Dataset may be stored flat ([N, H*W*C] — the TPU-friendly layout,
        # reshaped back via image_shape) or as images (shape from the array).
        tail = shape if images.ndim == 2 else tuple(images.shape[1:])
        x = images[take].reshape((n, steps, batch_size) + tail)
        y = labels[take].reshape((n, steps, batch_size))
        batch = RoundBatch(
            x=x, y=y, step_mask=step_mask, weights=weights, alive=alive,
            attack_seats=atk,
        )
        return base(state, batch)

    def presharded_step(
        state: FederatedState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
        attack_seats=None,
    ) -> Tuple[FederatedState, RoundMetrics]:
        n = mask.shape[0]
        atk = () if attack_seats is None else attack_seats
        rng = (
            jax.random.fold_in(data_key, state.round_idx) if shuffle else None
        )
        off, shard_len = _round_offset(labels, shuffle, rng)
        has_data = mask.any(axis=1)
        step_mask = jnp.broadcast_to(has_data[:, None], (n, steps))
        x, y = presharded_window(
            images, labels, off, steps, batch_size, shape, stream=stream
        )
        batch = RoundBatch(
            x=x, y=y, step_mask=step_mask, weights=weights, alive=alive,
            attack_seats=atk,
        )
        if stream:
            return base(state, batch, images, labels)
        return base(state, batch)

    return presharded_step if layout == "presharded" else gather_step


def presharded_window(images, labels, off, steps, batch_size, shape,
                      stream=False):
    """Extract one round's batch tensors from the presharded layout.

    ``images: [n, 2L, F]`` / ``labels: [n, 2L]`` (:func:`preshard_arrays`),
    ``off``: scalar rotation offset in ``[0, L)``. Non-stream returns
    ``(x: [n, steps, batch, *shape], y: [n, steps, batch])`` — ONE
    contiguous ``dynamic_slice`` when the window fits in an epoch, or an
    epoch slice tiled to length when ``steps*batch > L`` (multi-local-epoch
    cycling, the ``pos % length`` rule). Stream mode returns per-step
    offsets ``[n, steps]`` instead; the slicing then happens inside the
    training scan (:mod:`fedtpu.core.client`), so nothing
    ``[n, steps, batch, ...]``-sized is ever materialised.
    """
    n, L2 = labels.shape
    L = L2 // 2
    need = steps * batch_size
    if stream:
        if batch_size > L:
            raise ValueError(
                f"presharded stream mode needs batch_size <= shard length "
                f"({batch_size} > {L}); use device_layout='gather'"
            )
        offs = (off + jnp.arange(steps, dtype=jnp.int32) * batch_size) % L
        offs = jnp.broadcast_to(offs[None, :], (n, steps))
        return offs, offs
    f_tail = tuple(images.shape[2:])
    if need <= L:
        x = jax.lax.dynamic_slice(
            images, (0, off) + (0,) * len(f_tail), (n, need) + f_tail
        )
        y = jax.lax.dynamic_slice(labels, (0, off), (n, need))
    else:
        reps = -(-need // L)
        xw = jax.lax.dynamic_slice(
            images, (0, off) + (0,) * len(f_tail), (n, L) + f_tail
        )
        yw = jax.lax.dynamic_slice(labels, (0, off), (n, L))
        x = jnp.tile(xw, (1, reps) + (1,) * len(f_tail))[:, :need]
        y = jnp.tile(yw, (1, reps))[:, :need]
    tail = shape if len(f_tail) == 1 else f_tail
    x = x.reshape((n, steps, batch_size) + tail)
    y = y.reshape((n, steps, batch_size))
    return x, y


def make_multi_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    num_rounds: int,
    compressor=None,
    shuffle: bool = True,
    axis_name: Optional[str] = None,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
) -> Callable[..., Tuple[FederatedState, RoundMetrics]]:
    """``num_rounds`` federated rounds as ONE XLA program (``lax.scan``).

    The reference pays a full host round-trip per round — thread fan-out,
    blocking RPCs, checkpoint files (``src/server.py:120-153``). The jitted
    single-round step already collapses that to one dispatch per round, but
    on a remote/tunneled device even dispatch+sync latency dominates small
    rounds. Scanning the round body keeps the WHOLE multi-round run on
    device: per-round batches are still gathered fresh inside each scan
    iteration (``round_take_indices`` folds ``round_idx`` into the shuffle
    key, so round r's batches are identical to the sequential path's), and
    per-round metrics come back stacked ``[num_rounds, ...]``.

    Signature matches :func:`make_data_round_step` except ``alive`` is
    ``[num_rounds, clients]`` — one participation mask per round, so
    heartbeat deaths / client subsampling still vary per round inside the
    fused program. Returns ``(final_state, metrics_stacked)``.
    """
    body = make_data_round_step(
        model, cfg, steps, compressor, shuffle=shuffle, axis_name=axis_name,
        stream=stream, image_shape=image_shape, layout=layout,
    )

    def multi(
        state: FederatedState,
        images: jnp.ndarray,
        labels: jnp.ndarray,
        idx: jnp.ndarray,
        mask: jnp.ndarray,
        weights: jnp.ndarray,
        alive: jnp.ndarray,
        data_key: jax.Array,
        attack_seats=None,
    ) -> Tuple[FederatedState, RoundMetrics]:
        # attack_seats is per-BLOCK static (the fused block runs one cohort;
        # per-round fire decisions still vary inside the scan via round_idx).
        def scan_body(st, alive_r):
            return body(st, images, labels, idx, mask, weights, alive_r,
                        data_key, attack_seats)

        return jax.lax.scan(scan_body, state, alive, length=num_rounds)

    return multi


def _shard_wrap(body, cfg: RoundConfig, mesh, alive_ndim: int, donate: bool,
                layout: str = "presharded"):
    """Common shard_map+jit wrapper for the data-round bodies.

    Per-client state/assignment shard on the clients axis; FedAvg psums over
    ICI. The dataset's spec depends on the layout: presharded rows are
    per-client, so they SHARD on the clients axis (each device stores only
    its own clients' data); the gather layout's flat dataset replicates to
    every device (CIFAR-scale data fits HBM many times over, and replication
    keeps the gather local — no cross-chip data motion). ``alive_ndim`` is 1
    for a single-round body (``[clients]``) or 2 for the multi-round scan
    (``[rounds, clients]``, client axis sharded).
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from fedtpu.parallel.sharded import state_specs

    axis = cfg.mesh_axis
    if cfg.fed.num_clients % mesh.devices.size:
        raise ValueError(
            f"num_clients={cfg.fed.num_clients} not divisible by mesh size "
            f"{mesh.devices.size}"
        )
    data_spec = P(axis) if layout == "presharded" else P()
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            state_specs(axis),  # state
            data_spec,          # images ([clients, 2L, F] | flat replicated)
            data_spec,          # labels
            P(axis),            # idx
            P(axis),            # mask
            P(axis),            # weights
            P(axis) if alive_ndim == 1 else P(None, axis),  # alive
            P(),                # data_key
        ),
        out_specs=(
            state_specs(axis),
            # Scalar metrics replicate; per_client_loss and the screening
            # mask shard on their client axis — axis 0 for one round,
            # axis 1 when the scan stacks [R, n].
            RoundMetrics(
                P(), P(), P(), P(),
                P(axis) if alive_ndim == 1 else P(None, axis),
                P(axis) if alive_ndim == 1 else P(None, axis),
            ),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def make_sharded_multi_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    num_rounds: int,
    mesh,
    compressor=None,
    shuffle: bool = True,
    donate: bool = True,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
):
    """Mesh-parallel form of :func:`make_multi_round_step`: the scan runs
    inside ``shard_map``, so a whole multi-round run is one program with one
    psum per round over ICI and zero host involvement between rounds.
    ``alive`` is ``[num_rounds, clients]``, sharded on its client axis."""
    body = make_multi_round_step(
        model, cfg, steps, num_rounds, compressor, shuffle=shuffle,
        axis_name=cfg.mesh_axis, stream=stream, image_shape=image_shape,
        layout=layout,
    )
    return _shard_wrap(body, cfg, mesh, alive_ndim=2, donate=donate,
                       layout=layout)


def make_sharded_data_round_step(
    model,
    cfg: RoundConfig,
    steps: int,
    mesh,
    compressor=None,
    shuffle: bool = True,
    donate: bool = True,
    stream: Optional[bool] = None,
    image_shape: Optional[Tuple[int, ...]] = None,
    layout: str = "presharded",
):
    """Mesh-parallel round step with the on-device batch extraction inside
    each shard.

    Call signature matches :func:`make_data_round_step`; inputs must be
    placed with :func:`shard_data_arrays` / :func:`fedtpu.parallel.shard_state`.
    Sharding layout: see :func:`_shard_wrap`.
    """
    body = make_data_round_step(
        model, cfg, steps, compressor, shuffle=shuffle, axis_name=cfg.mesh_axis,
        stream=stream, image_shape=image_shape, layout=layout,
    )
    return _shard_wrap(body, cfg, mesh, alive_ndim=1, donate=donate,
                       layout=layout)
