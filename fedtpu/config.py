"""Central configuration for fedtpu.

The reference scatters configuration across three argparse surfaces and many
hardcoded constants (reference: ``src/server.py:270-274``, ``src/client.py:56-59``,
``src/main.py:20-26``; hardcoded round count at ``server.py:120``, model choice at
``main.py:69``, optimizer at ``main.py:99-101``). fedtpu centralises everything in
typed, hashable dataclasses so configs can be closed over by jitted functions.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Transient-fault handling for every RPC the federation issues.

    The pre-policy transport treated each RPC as one shot: a single
    transient ``grpc.RpcError`` (a TCP reset, a brief listener restart, an
    overloaded peer) marked the client dead for the round and handed it to
    the heartbeat/resync machinery — the failure path the paper reserves
    for *real* failures. Under this policy an RPC whose status code is in
    ``transient_codes`` (or whose reply payload fails the wire CRC — see
    :mod:`fedtpu.transport.wire`) is retried with exponential backoff +
    jitter up to ``max_attempts`` total attempts; only EXHAUSTED retries
    reach ``ClientRegistry.mark_failed``. Fatal codes (UNIMPLEMENTED,
    INVALID_ARGUMENT, ...) never retry — a config-mismatched peer must
    fail loudly, not thrash.

    Per-RPC deadlines live here too, replacing the constants that were
    scattered through the transport (StartTrain/SendModel 600 s at the old
    ``PrimaryServer(rpc_timeout=...)`` default, backup ping 2.0 s,
    heartbeat probe 1.0 s). Defaults reproduce the old values exactly, so
    a default-config federation behaves bit-identically in the absence of
    faults (retries only ever run where the old code failed).
    """

    # Total attempts per logical RPC (1 = the old single-shot behavior).
    max_attempts: int = 3
    backoff_s: float = 0.05          # sleep before attempt 2
    backoff_multiplier: float = 2.0  # growth per further attempt
    backoff_max_s: float = 2.0
    # Fraction of each backoff randomized (decorrelates retry storms;
    # irrelevant to determinism — fault *injection* is seeded, not retry
    # spacing).
    jitter: float = 0.2
    # grpc.StatusCode names treated as transient (retryable). Everything
    # else — UNIMPLEMENTED, INVALID_ARGUMENT, FAILED_PRECONDITION, ... —
    # is fatal and fails the call on the first attempt.
    transient_codes: Tuple[str, ...] = (
        "UNAVAILABLE",
        "DEADLINE_EXCEEDED",
        "RESOURCE_EXHAUSTED",
        "ABORTED",
        "INTERNAL",
        "UNKNOWN",
    )
    # Per-RPC deadlines (seconds). The data-plane deadlines default to the
    # old blanket rpc_timeout=600.0; the control-plane ones to the old
    # hardcoded constants they replace.
    start_train_timeout_s: float = 600.0
    send_model_timeout_s: float = 600.0
    fetch_model_timeout_s: float = 600.0
    probe_timeout_s: float = 1.0        # HeartBeat (was probe() default)
    backup_ping_timeout_s: float = 2.0  # CheckIfPrimaryUp (was literal 2.0)


def validate_retry_policy(rp: RetryPolicy) -> RetryPolicy:
    if rp.max_attempts < 1:
        raise ValueError(f"retry max_attempts must be >= 1, got {rp.max_attempts}")
    if rp.backoff_s < 0 or rp.backoff_max_s < 0:
        raise ValueError("retry backoff seconds must be >= 0")
    if rp.backoff_multiplier < 1.0:
        raise ValueError(
            f"retry backoff_multiplier must be >= 1, got {rp.backoff_multiplier}"
        )
    if not 0.0 <= rp.jitter <= 1.0:
        raise ValueError(f"retry jitter must be in [0, 1], got {rp.jitter}")
    return rp


@dataclasses.dataclass(frozen=True)
class ScreenConfig:
    """Fused update screening + client reputation (docs/FAULT_TOLERANCE.md).

    Screening is the defense for the DEFAULT fast path: median/trimmed_mean/
    krum protect the aggregate but are barrier-only and rewrite its math;
    screening instead REJECTS suspicious client rows before any combine, as
    one fused stats pass over the flat ``[clients, P]`` delta buffer
    (:func:`fedtpu.ops.flat.screen_rows`) — so it composes with
    ``server_pipeline='stream'``, with the plain mean, and with the robust
    aggregators (screened rows simply drop out of the weighted/robust
    combine through the existing exclusion mask, bit-cleanly).

    Three per-row statistics, each gated by its own threshold (0 / -1 =
    that check off; screening as a whole is off when all three are off):

    - ``norm_max``: absolute L2 bound on the update row — the blunt
      norm-bound defense against boosted/scaled updates.
    - ``zmax``: modified z-score bound on the row norms, computed against
      the live cohort's median/MAD (robust to the attackers inflating the
      spread, unlike a mean/std z-score); rejects norm outliers without an
      absolute calibration.
    - ``cos_min``: minimum cosine of the row against the live cohort's
      coordinate-wise median direction; rejects sign-flipped/contrarian
      updates whose norms look ordinary.

    Reputation closes the loop from per-round verdicts to membership
    action: every screening verdict feeds a per-client suspicion EWMA
    (``s' = (1-ewma)*s + ewma*flagged``) held on the
    :class:`~fedtpu.ft.membership.MembershipTable` and replicated to the
    backup. ``s >= quarantine_at`` escalates flagged -> QUARANTINED (the
    client still receives broadcasts and StartTrain — it can redeem itself
    — but its updates are ignored unconditionally); dropping back below
    ``release_at`` releases it; ``evict_after`` consecutive quarantined
    rounds escalates to eviction through the live membership machinery
    (``remove_client(reason='quarantine')``). ``evict_after=0`` = never
    auto-evict (quarantine is already containment).
    """

    norm_max: float = 0.0
    zmax: float = 0.0
    cos_min: float = -1.0
    ewma: float = 0.5
    quarantine_at: float = 0.75
    release_at: float = 0.25
    evict_after: int = 0


def screening_enabled(screen: ScreenConfig) -> bool:
    """True when any screening statistic is armed."""
    return screen.norm_max > 0 or screen.zmax > 0 or screen.cos_min > -1.0


def validate_screen_config(screen: ScreenConfig) -> ScreenConfig:
    if screen.norm_max < 0:
        raise ValueError(f"screen norm_max must be >= 0, got {screen.norm_max}")
    if screen.zmax < 0:
        raise ValueError(f"screen zmax must be >= 0, got {screen.zmax}")
    if not -1.0 <= screen.cos_min <= 1.0:
        raise ValueError(
            f"screen cos_min must be in [-1, 1], got {screen.cos_min}"
        )
    if not 0.0 < screen.ewma <= 1.0:
        raise ValueError(f"screen ewma must be in (0, 1], got {screen.ewma}")
    if not 0.0 <= screen.release_at <= screen.quarantine_at <= 1.0:
        raise ValueError(
            "screen thresholds must satisfy 0 <= release_at <= "
            f"quarantine_at <= 1, got release_at={screen.release_at} "
            f"quarantine_at={screen.quarantine_at}"
        )
    if screen.evict_after < 0:
        raise ValueError(
            f"screen evict_after must be >= 0, got {screen.evict_after}"
        )
    return screen


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Per-client local optimizer.

    Defaults mirror the reference trainer's *effective* behavior:
    SGD(lr=0.1, momentum=0.9, weight_decay=5e-4) at a CONSTANT learning rate.
    The reference constructs CosineAnnealingLR(T_max=200)
    (``src/main.py:101``) but never steps it — the driver loop containing
    ``scheduler.step()`` is commented out (``src/main.py:231-242``) and the
    federated ``train(epoch, rank, world)`` path (``src/main.py:128-165``)
    doesn't step it either — so its effective LR is always 0.1.
    ``schedule='cosine'`` implements the schedule the reference *intended*;
    parity runs pin ``schedule='constant'``.
    """

    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 5e-4
    # constant (reference effective behavior) | cosine (reference intent).
    schedule: str = "constant"
    # Cosine annealing horizon in *rounds* (the reference steps its scheduler
    # per epoch; in federated mode one round == one local epoch).
    cosine_t_max: int = 200
    nesterov: bool = False
    # HBM dtype of the per-client momentum buffers. "float32" is reference
    # parity (torch SGD buffers are f32). "bfloat16" is an opt-in NON-PARITY
    # mode that halves optimizer-state HBM traffic — BASELINE.md's bandwidth
    # roofline names f32 param+momentum traffic (~0.5 GB/step at the
    # 64-client bench) as a leading consumer. The buffer update is always
    # computed in f32; only the stored buffer is rounded, so the mode's
    # entire effect is one bf16 round-trip per step per buffer.
    momentum_dtype: str = "float32"  # float32 | bfloat16

    def lr_at(self, round_idx) -> float:
        """Learning rate for a given round (traceable)."""
        import jax.numpy as jnp

        if self.schedule == "constant":
            return jnp.asarray(self.learning_rate, jnp.float32)
        if self.schedule != "cosine":
            raise ValueError(f"unknown schedule: {self.schedule!r}")
        t = jnp.minimum(round_idx, self.cosine_t_max)
        return self.learning_rate * 0.5 * (
            1.0 + jnp.cos(jnp.pi * t / self.cosine_t_max)
        )


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Dataset + partitioning.

    ``partition='round_robin'`` reproduces the reference's shard rule where
    client ``rank`` keeps batch ``i`` iff ``(i + 1) % world == rank``
    (reference: ``src/main.py:141-144``). Other partitioners (iid, dirichlet)
    cover the BASELINE.md parity configs.
    """

    dataset: str = "cifar10"  # cifar10 | cifar100 | mnist | synthetic
    batch_size: int = 128  # reference: src/main.py:51
    eval_batch_size: int = 100  # reference: src/main.py:56
    partition: str = "round_robin"  # round_robin | iid | dirichlet
    dirichlet_alpha: float = 0.5
    augment: bool = True  # random crop + flip (reference: src/main.py:37-42)
    # The random-crop half of the augmentation (the horizontal flip always
    # applies while ``augment`` is on). The crop is the shift-accumulate
    # "fastcrop" formulation (fedtpu.data.augment, default-on; measured 2.0x
    # on-chip vs the dynamic-slice crop, artifacts/BENCH_LIVE_r04_fastcrop).
    # ``augment_crop=False`` skips the crop entirely — flip-only, with a
    # bit-parity pin in tests (the rng split structure is shared, so the
    # flip draw is identical either way).
    augment_crop: bool = True
    seed: int = 0
    # Truncate the loaded dataset (None = full). Mainly for tests and quick
    # runs; the reference always trains on the full set.
    num_examples: Optional[int] = None
    # HBM layout of the device-resident dataset (fedtpu.data.device).
    #   "presharded": the dataset is reorganised ONCE at upload into
    #     [clients, 2*shard_len, features] (each client's shard, cycled to
    #     pad and stored twice along the shard axis), so each round's batch
    #     extraction is ONE contiguous dynamic-slice at a per-round rotation
    #     offset. Measured motivation: the gather layout's computed-index
    #     row-gather lowers on TPU to ~2 us dynamic-slice loops per example
    #     (~250k ops/dispatch at the 64-client CIFAR bench,
    #     artifacts/MFU_PROFILE_r04.json) and dominates the fused round.
    #   "gather": dataset stays [N, features]; batches come from a per-round
    #     index gather (exact per-round permutation shuffling, arbitrary
    #     shard-length raggedness, no 2x data HBM). The exact semantics of
    #     rounds 1-3 artifacts.
    device_layout: str = "presharded"  # presharded | gather


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Massive-cohort simulation (:mod:`fedtpu.sim`): decouple the simulated
    **population** from the per-round **cohort**.

    With ``population > 0`` the engine CLI runs a
    :class:`fedtpu.sim.engine.SimFederation`: ``population`` clients exist
    as lightweight host-side rows (dataset assignment, last-seen loss,
    availability, sampling bookkeeping) while the device keeps only the
    engine's fixed ``FedConfig.num_clients``-sized buffers — the cohort. A
    seeded sampler draws each round's cohort and its rows are gathered into
    those buffers, so device memory is O(cohort), not O(population)
    (FedJAX-style, arXiv:2108.02117). ``population == num_clients`` with the
    uniform sampler reproduces the resident engine bit-for-bit (test-pinned).
    """

    # 0 = off (resident engine: every client is a live device slot).
    population: int = 0
    # How each round's cohort is drawn from the available population:
    # "uniform" (without replacement) | "loss" (proportional to last-seen
    # training loss, optimistic prior for never-sampled clients).
    cohort_sampler: str = "uniform"
    # Scenario spec for the POPULATION partition (fedtpu.sim.scenario), e.g.
    # "pathological:shards=2" or "dirichlet:alpha=0.1+quantity_skew:power=1.5".
    # "" = use DataConfig.partition unchanged.
    scenario: str = ""
    # Optimistic loss prior for never-sampled clients under the "loss"
    # sampler; < 0 = the max observed loss (the engine's existing fill rule).
    loss_prior: float = -1.0
    # Availability/churn trace (fedtpu.sim.population.Population): stationary
    # up-fraction and per-round P(up -> down). availability=1, churn=0 =
    # everyone always available.
    availability: float = 1.0
    churn: float = 0.0
    # Extra sampler seed (folded with data.seed so two sim runs over the
    # same data can draw different cohort sequences).
    seed: int = 0
    # Adversarial-participant axis (fedtpu.sim.adversary): this fraction of
    # the simulated population (or of num_clients on the resident engine)
    # is seeded Byzantine — their client ids are a deterministic function
    # of (data.seed, sim.seed), so attack runs replay bit-identically.
    malicious_fraction: float = 0.0
    # What the attackers DO, as an attack spec
    # "kind[:key=val,...]": sign_flip | scale:factor=F | noise:std=S |
    # label_flip:offset=K, with shared options p= (per-round fire
    # probability), rounds=lo-hi (half-open round window) and collude=1
    # (colluding-cohort mode: one shared draw/noise vector for the whole
    # malicious set — the coordinated attack that defeats distance-based
    # defenses like krum when uncoordinated noise would not).
    attack: str = "sign_flip"


def validate_sim_config(fed: "FedConfig") -> None:
    """Raise on inconsistent sim settings (cheap, before any build work)."""
    sim = fed.sim
    if not 0.0 <= sim.malicious_fraction < 1.0:
        raise ValueError(
            f"sim.malicious_fraction must be in [0, 1), got "
            f"{sim.malicious_fraction}"
        )
    if sim.malicious_fraction > 0:
        from fedtpu.sim.adversary import parse_attack

        parse_attack(sim.attack)  # raises on a malformed spec
    if sim.population <= 0:
        return
    if sim.population < fed.num_clients:
        raise ValueError(
            f"sim.population={sim.population} < cohort "
            f"(num_clients={fed.num_clients}); the cohort is drawn FROM the "
            "population"
        )
    if sim.cohort_sampler not in ("uniform", "loss"):
        raise ValueError(
            f"unknown cohort_sampler {sim.cohort_sampler!r}; "
            "have uniform | loss"
        )
    if fed.participation_fraction != 1.0:
        raise ValueError(
            "sim.population and participation_fraction are mutually "
            "exclusive: the cohort sampler IS the participation model "
            "(set participation_fraction=1.0)"
        )
    if not 0.0 < sim.availability <= 1.0:
        raise ValueError(
            f"sim.availability must be in (0, 1], got {sim.availability}"
        )
    if not 0.0 <= sim.churn <= 1.0:
        raise ValueError(f"sim.churn must be in [0, 1], got {sim.churn}")


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Federated topology + algorithm."""

    num_clients: int = 2  # reference default: two clients (src/server.py:281-282)
    num_rounds: int = 20  # reference: src/server.py:120
    local_epochs: int = 1  # reference: one epoch per StartTrain (src/client.py:17)
    algorithm: str = "fedavg"  # fedavg | fedprox
    fedprox_mu: float = 0.0
    # Uniform (unweighted) averaging matches the reference aggregator
    # (src/server.py:163-171); weighted=True uses per-client example counts.
    weighted: bool = True
    # Client sampling fraction per round (1.0 == all clients, reference behavior).
    participation_fraction: float = 1.0
    # How the sampled subset is drawn: "uniform", or "loss" — importance
    # sampling proportional to each client's last observed training loss
    # (clients the model serves worst get picked more often; see e.g.
    # arXiv:2306.03240). Falls back to uniform until a loss is observed.
    participation_sampling: str = "uniform"  # uniform | loss
    # Compression of client deltas before aggregation (parity with -c Y,
    # reference: src/server.py:104-107).
    #   none | topk | int8, any delta_layout; plus the seeded sketch codecs
    #   rotq (rotated b-bit quantization, rotq_bits below) and randk
    #   (random-coordinate subsampling, reusing topk_fraction as the keep
    #   fraction) — flat-layout only (docs/FLAT_DELTA.md §Codec matrix).
    compression: str = "none"
    topk_fraction: float = 0.01
    error_feedback: bool = True
    # Bit width for compression='rotq' (1 | 2 | 4 | 8): wire cost is
    # rotq_bits * pow2(P) / 8 bytes per client per round.
    rotq_bits: int = 4
    # Codec selection on the distributed edge (fedtpu.transport.federation):
    #   "static": every client uses `compression` every round (the default).
    #   "adaptive": the coordinator picks a codec per client per round from
    #     {none, int8, topk, rotq, randk} by observed bytes x RTT
    #     (fedtpu.transport.codec_policy.AdaptiveCodecPolicy), shipping the
    #     choice in StartTrain. Requires delta_layout='flat' (the sketch
    #     codecs only exist there). Engine-side federation ignores this.
    codec_policy: str = "static"  # static | adaptive
    # HOW the per-client delta travels through compression/aggregation.
    #   "per_leaf": every codec stage + the FedAvg reduction run once per
    #     pytree leaf (the original path; the parity default).
    #   "flat": all leaves are packed once per round into one lane-aligned
    #     [clients, P] buffer (fedtpu.ops.flat) — one top_k / one quantize /
    #     one reduction per round instead of hundreds on deep zoo models.
    #     Bit-identical aggregates for compression='none' and 'int8'; for
    #     'topk' the keep budget becomes GLOBAL across the model instead of
    #     per-leaf (documented in docs/FLAT_DELTA.md).
    delta_layout: str = "per_leaf"  # per_leaf | flat
    # Server-side optimizer applied to the aggregated delta (the FedOpt
    # family, Reddi et al. 2021 — "adaptive federated optimization"). The
    # reference applies the mean delta directly (src/server.py:170-179),
    # which is server_optimizer="none" (== FedAvg). "momentum" = FedAvgM,
    # "adam" = FedAdam, "yogi" = FedYogi; the mean client delta acts as the
    # pseudo-gradient.
    server_optimizer: str = "none"  # none | momentum | adam | yogi
    server_lr: float = 1.0
    server_momentum: float = 0.9
    server_beta2: float = 0.999
    server_eps: float = 1e-8
    # How client deltas combine. "mean" is the reference's (weighted) FedAvg;
    # "median" / "trimmed_mean" are coordinate-wise Byzantine-robust
    # aggregators (Yin et al. 2018); "krum" is selection-based (Blanchard et
    # al. 2017, f = floor(trim_fraction * n) assumed Byzantine, pairwise
    # distances as one MXU matmul). Robust aggregators ignore example-count
    # weights by construction and tolerate ~trim_fraction adversaries.
    aggregator: str = "mean"  # mean | median | trimmed_mean | krum
    trim_fraction: float = 0.1
    # Differential privacy (DP-FedAvg, McMahan et al. 2018): clip each
    # client's delta to L2 norm dp_clip_norm (0 = off), then add Gaussian
    # noise with std = dp_clip_norm * dp_noise_multiplier / n_participants
    # to the aggregated delta. Requires uniform weighting (weighted=False)
    # and compression='none' — both enforced — so the per-client
    # sensitivity bound clip/n actually holds.
    dp_clip_norm: float = 0.0
    dp_noise_multiplier: float = 0.0
    # HOW the distributed server (fedtpu.transport.federation.PrimaryServer)
    # consumes StartTrain replies.
    #   "barrier": decode every reply into per-leaf host pytrees, stack
    #     leaf-by-leaf after the LAST reply, then transfer + aggregate in
    #     one jitted program (the original path; per-leaf parity reference).
    #   "stream": decode each reply directly into its row of one
    #     preallocated flat [clients, P] buffer and ship it to the device
    #     as it arrives (decode + H2D overlap the remaining clients'
    #     network wait); the post-barrier work is a single fused
    #     mean/unpack/server-opt finalize over the already-resident rows.
    #     Mean aggregation is bit-identical to "barrier" (the finalize runs
    #     the same order-stable stacked reduce — see
    #     docs/PERF_ANALYSIS.md). Requires aggregator='mean' and no DP
    #     clipping (validated in resolve_server_pipeline).
    #   "auto" (default): "stream" whenever the flat delta layout is on and
    #     the combination supports it, else "barrier".
    # Engine-side (simulated) federation ignores this knob: there is no
    # network edge to overlap.
    server_pipeline: str = "auto"  # auto | barrier | stream
    # How much the framework measures itself (fedtpu.obs; see
    # docs/OBSERVABILITY.md):
    #   "off":   no registry metrics, no spans. Round records keep their
    #     wire/phase fields (that accounting is part of the round API).
    #   "basic" (default): thread-safe counters/gauges/histograms (RPC
    #     bytes, compression ratio, phase times, retries, heartbeat misses,
    #     failover transitions, rounds completed), exportable as Prometheus
    #     text. Overhead <1% of round wall time (bench.py
    #     --telemetry-microbench, artifacts/TELEMETRY_MICROBENCH.json).
    #   "trace": basic plus the span tracer — nested round/client/phase
    #     spans exported as Chrome trace-event JSON (Perfetto-loadable) and
    #     bridged to jax.profiler.TraceAnnotation so XLA device activity
    #     nests under framework spans when a profiler session is active.
    telemetry: str = "basic"  # off | basic | trace
    # Transient-fault handling on the gRPC edge: retry/backoff + per-RPC
    # deadlines (see RetryPolicy). Defaults reproduce the old constants;
    # the engine (simulated) path has no RPC edge and ignores this.
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    # Minimum fraction of this round's SAMPLED clients that must deliver
    # updates for the round to commit. Below quorum the round aborts
    # cleanly: the global model (and server-optimizer state) is left
    # bit-identical to its pre-round value — no partial average — the
    # clients are re-synced to that global, and the round re-runs.
    # 0.0 (default) = the old behavior: aggregate whatever arrived.
    round_quorum: float = 0.0
    # FT timing constants, previously hardcoded in the transport/ft stack
    # (docs/FAULT_TOLERANCE.md): the backup's promotion watchdog window,
    # the dead-client re-probe period, and the async reply-queue poll.
    ft_watchdog_timeout_s: float = 10.0
    ft_heartbeat_period_s: float = 1.0
    async_poll_s: float = 1.0
    # Massive-cohort simulation (population >> cohort decoupling): see
    # SimConfig / fedtpu.sim. num_clients doubles as the COHORT size when
    # sim.population > 0 — the engine's device buffers stay that size.
    sim: SimConfig = dataclasses.field(default_factory=SimConfig)
    # Fused update screening + reputation/quarantine (ScreenConfig;
    # docs/FAULT_TOLERANCE.md). Off by default (all thresholds disarmed) —
    # arming any statistic turns on per-round row rejection and, on the
    # distributed server, the suspicion EWMA -> quarantine -> evict
    # escalation. Unlike the robust aggregators this composes with
    # server_pipeline='stream' and with aggregator='mean'.
    screen: ScreenConfig = dataclasses.field(default_factory=ScreenConfig)
    # Device compute dtype for the local-training fast path
    # (docs/PERF_ANALYSIS.md §Roofline).
    #   "float32": full-precision parity (the seed default). The legacy
    #     RoundConfig.dtype knob keeps selecting the activation dtype for
    #     callers that set it directly (the bench has always run bf16
    #     activations through it).
    #   "bfloat16_mixed": params, activations and the device-resident
    #     dataset live in bf16 through the (fused) local step — master-copy
    #     mixed precision: FederatedState.params stays f32 and the bf16
    #     cast happens at use inside the jitted step, so gradients, the
    #     [clients, P] flat aggregation surface, FedOpt moments, screening
    #     statistics and checkpoints all keep f32 semantics (test-pinned).
    #     Measured lever: bf16 residency alone was worth 2.4x on-chip
    #     (artifacts/BENCH_LIVE_r04_bf16.json).
    compute_dtype: str = "float32"  # float32 | bfloat16_mixed
    # Fold k simulated clients into ONE [k*batch, features] MXU pass inside
    # the vmapped round body (fedtpu.core.round): a group of k clients
    # shares one parameter trajectory per round (sound because every client
    # starts each round at the same global params), per-example weights
    # keep masked/dead members exact, and per-member metrics + deltas are
    # broadcast back onto the [clients] axis so screening, compression and
    # aggregation are untouched. Raises arithmetic intensity for the
    # small-model zoo: k skinny matmuls become one wide one (the
    # bandwidth-bound diagnosis in artifacts/MFU_PROFILE_r04*.json).
    # 0 = off (the per-client path, the parity default); k >= 1 engages the
    # megabatched body (k=1 is the debug setting, test-pinned bit-identical
    # to the per-client path); k must divide num_clients. k > 1 is a
    # documented approximation: members share BN batch statistics over the
    # k*batch examples, one augment/dropout rng stream and one optimizer
    # trajectory per group.
    megabatch_clients: int = 0
    # Hierarchical multi-tier aggregation (docs/ARCHITECTURE.md
    # §Multi-tier): 0 (default) = flat one-tier federation. N >= 1 turns
    # the distributed server into a two-tier ROOT whose roster entries are
    # leaf AggregatorServer addresses, each fronting a cohort of up to N
    # clients: the root pulls ONE pre-weighted partial sum per aggregator
    # per round (SubmitPartial), so its per-round decode+combine work is
    # O(aggregators), not O(clients). Root world = capacity * tier_fanout;
    # aggregator seat j owns data-partition ranks [j*N, (j+1)*N). Requires
    # the streaming pipeline with aggregator='mean', no DP and no
    # screening (validate_tier_config) — partial sums destroy the
    # per-client rows those need. Exactness: the root divides the summed
    # partials ONCE, so the 2-tier result is bit-identical to the flat
    # weighted mean (tests/test_aggregator.py parity pins).
    tier_fanout: int = 0


def validate_tier_config(fed: FedConfig, face: str) -> None:
    """Raise on FedConfig combinations hierarchical aggregation cannot
    honour, naming the requesting ``face`` (root or leaf — BOTH tiers run
    this, so a misconfigured topology fails at construction on every
    process rather than silently changing semantics mid-federation).

    A partial SUM destroys per-client structure: anything that needs
    individual client rows at the combine — robust aggregators, DP
    clipping, Byzantine screening — is incompatible with tiering.
    """
    if fed.tier_fanout < 0:
        raise ValueError(
            f"tier_fanout must be >= 0, got {fed.tier_fanout}"
        )
    if fed.aggregator != "mean":
        raise ValueError(
            f"hierarchical aggregation ({face}) requires aggregator='mean': "
            f"{fed.aggregator!r} needs every client row at the combine, "
            "but tiers forward only pre-weighted sums"
        )
    if fed.dp_clip_norm > 0:
        raise ValueError(
            f"hierarchical aggregation ({face}) cannot compose with DP "
            "clipping: per-client sensitivity bounds need individual rows "
            "at the root"
        )
    if screening_enabled(fed.screen):
        raise ValueError(
            f"hierarchical aggregation ({face}) cannot compose with update "
            "screening: screening statistics need individual client rows "
            "(screen at a future leaf tier instead)"
        )
    if resolve_server_pipeline(fed) != "stream":
        raise ValueError(
            f"hierarchical aggregation ({face}) requires the streaming "
            "pipeline: partial sums arrive as flat rows and fold through "
            "the [rows, P] stream buffer (server_pipeline='barrier' has "
            "no flat layout to decode them into)"
        )


def resolve_compute_dtype(cfg: "RoundConfig") -> str:
    """Resolve the effective activation/param compute dtype for the local
    step, as a dtype name ("float32" | "bfloat16").

    ``FedConfig.compute_dtype`` is the user-facing switch:
    ``"bfloat16_mixed"`` resolves to bf16 compute over the f32 master
    state; ``"float32"`` defers to the legacy ``RoundConfig.dtype`` knob so
    callers that set it directly (bench variants) keep working unchanged.
    """
    if cfg.fed.compute_dtype not in ("float32", "bfloat16_mixed"):
        raise ValueError(
            f"unknown compute_dtype {cfg.fed.compute_dtype!r}; "
            "have float32 | bfloat16_mixed"
        )
    if cfg.fed.compute_dtype == "bfloat16_mixed":
        return "bfloat16"
    return cfg.dtype


def validate_megabatch(fed: FedConfig) -> None:
    """Raise on inconsistent megabatch settings (cheap, before build work)."""
    k = fed.megabatch_clients
    if k < 0:
        raise ValueError(f"megabatch_clients must be >= 0, got {k}")
    if k and fed.num_clients % k:
        raise ValueError(
            f"megabatch_clients={k} must divide num_clients="
            f"{fed.num_clients}: the group regrouping is a static reshape "
            "of the [clients] axis"
        )


def resolve_server_pipeline(fed: FedConfig) -> str:
    """Resolve ``FedConfig.server_pipeline`` to ``"barrier"`` or
    ``"stream"``, naming WHY a combination cannot stream.

    The streaming collect path folds rows into the aggregate as they
    arrive, so it only supports combines that are per-coordinate sums:
    the (weighted) mean. Robust aggregators and DP clipping need every
    client's full row on device at once — they stay on the stacked
    barrier path.
    """
    if fed.server_pipeline not in ("auto", "barrier", "stream"):
        raise ValueError(
            f"unknown server_pipeline {fed.server_pipeline!r}; "
            "have auto | barrier | stream"
        )
    streamable = fed.aggregator == "mean" and fed.dp_clip_norm == 0
    if fed.server_pipeline == "stream":
        if fed.aggregator != "mean":
            raise ValueError(
                f"server_pipeline='stream' cannot compose with "
                f"aggregator={fed.aggregator!r}: median/trimmed_mean/krum "
                "are not per-coordinate sums, so they need every client "
                "row at once — use server_pipeline='barrier' (the stacked "
                "[clients, ...] path)."
            )
        if fed.dp_clip_norm > 0:
            raise ValueError(
                "server_pipeline='stream' cannot compose with DP clipping: "
                "DP-FedAvg clips each client's full delta before the "
                "combine, so rows cannot fold into a running aggregate — "
                "use server_pipeline='barrier'."
            )
        return "stream"
    if fed.server_pipeline == "barrier":
        return "barrier"
    # auto: stream is the default for the flat delta layout (the perf
    # config the layout exists for); per_leaf keeps the parity path.
    return "stream" if (fed.delta_layout == "flat" and streamable) else "barrier"


@dataclasses.dataclass(frozen=True)
class RoundConfig:
    """Everything a single jitted round step needs, bundled + hashable."""

    model: str = "MobileNet"  # reference default: src/main.py:69
    num_classes: int = 10
    image_size: Tuple[int, int, int] = (32, 32, 3)
    opt: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    fed: FedConfig = dataclasses.field(default_factory=FedConfig)
    # Steps of local SGD per round per client; with static shapes this is the
    # padded maximum — shorter shards are masked (see fedtpu.core.client).
    steps_per_round: int = 8
    dtype: str = "float32"  # compute dtype for activations; params stay f32
    mesh_axis: str = "clients"
    # Per-block rematerialisation for models that support it (resnet*):
    # trades recompute FLOPs for HBM so big vmapped-client configs fit one
    # chip (measured: BASELINE.md config 4 OOMs one v5e without it).
    remat: bool = False
    # Per-batch console feedback from INSIDE the jitted local epoch
    # (jax.debug.print) — the reference prints loss/acc per batch mid-epoch
    # (src/utils.py:51-92, called at src/main.py:124,158). Off by default:
    # each print is a host callback that serialises the device against the
    # host, so this is a debugging aid, never a benchmarking mode.
    debug_per_batch: bool = False


DEFAULT_ROUND_CONFIG = RoundConfig()
