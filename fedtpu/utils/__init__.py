from fedtpu.utils import trees
from fedtpu.utils.metrics import MetricsLogger, format_time

__all__ = ["trees", "MetricsLogger", "format_time"]
