from fedtpu.utils import trees
from fedtpu.utils.metrics import MetricsLogger, format_time
from fedtpu.utils.progress import ProgressBar, profile_rounds

__all__ = [
    "trees",
    "MetricsLogger",
    "format_time",
    "ProgressBar",
    "profile_rounds",
]
