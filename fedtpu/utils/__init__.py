from fedtpu.utils import trees
from fedtpu.utils.metrics import MetricsLogger, format_time
from fedtpu.utils.progress import ProgressBar, profile_rounds
from fedtpu.utils.stats import get_mean_and_std, kaiming_init_params

__all__ = [
    "trees",
    "MetricsLogger",
    "format_time",
    "ProgressBar",
    "profile_rounds",
    "get_mean_and_std",
    "kaiming_init_params",
]
