"""Structured metrics + logging.

Replaces the reference's tty-bound progress bar (``src/utils.py:51-92`` — which
reads the terminal width via ``stty size`` at import time and therefore breaks
headless runs) with a headless-safe structured logger, and keeps a
``format_time`` pretty-printer for parity (``src/utils.py:94-124``).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any, Dict, Optional

logger = logging.getLogger("fedtpu")


def format_time(seconds: float) -> str:
    """Human-readable duration, e.g. ``1h23m45s`` (parity: src/utils.py:94-124)."""
    seconds = float(seconds)
    days, seconds = divmod(seconds, 86400)
    hours, seconds = divmod(seconds, 3600)
    minutes, seconds = divmod(seconds, 60)
    secs = int(seconds)
    millis = int((seconds - secs) * 1000)

    parts = []
    if days >= 1:
        parts.append(f"{int(days)}D")
    if hours >= 1 or parts:
        parts.append(f"{int(hours)}h")
    if minutes >= 1 or parts:
        parts.append(f"{int(minutes)}m")
    parts.append(f"{secs}s")
    if not parts[:-1] and secs == 0:
        return f"{millis}ms"
    return "".join(parts[:3])


class MetricsLogger:
    """Round-level metrics sink: JSONL file and/or stderr lines.

    Replaces the reference's print-based observability
    (``src/server.py:121,130,148``) with structured records the driver or a
    dashboard can consume.

    Superseded by :class:`fedtpu.obs.RoundRecordWriter` (same ``log``
    surface, plus a pinned ``schema_version`` on every record) — the CLIs
    now write through that; this class stays for callers that want raw,
    unversioned JSONL.
    """

    def __init__(self, path: Optional[str] = None, echo: bool = True):
        self._path = path
        self._echo = echo
        self._fh = open(path, "a") if path else None
        self._t0 = time.time()

    def log(self, step: int, **metrics: Any) -> None:
        rec: Dict[str, Any] = {"step": int(step), "t": round(time.time() - self._t0, 4)}
        for k, v in metrics.items():
            try:
                rec[k] = float(v)
            except (TypeError, ValueError):
                rec[k] = v
        line = json.dumps(rec)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self._echo:
            print(line, file=sys.stderr)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
