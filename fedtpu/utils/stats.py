"""Dataset statistics + init helpers (parity: ``src/utils.py:15-42`` —
``get_mean_and_std`` and ``init_params``, which the reference defines but
never calls; here they are tested and usable)."""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np


def get_mean_and_std(images: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-channel mean/std of an NHWC image array (the numbers hardcoded in
    the reference's transform, ``src/main.py:39-47``, were computed this way)."""
    images = np.asarray(images, np.float64)
    mean = images.mean(axis=(0, 1, 2))
    std = images.std(axis=(0, 1, 2))
    return mean.astype(np.float32), std.astype(np.float32)


def kaiming_init_params(params, rng: jax.Array):
    """Re-initialise a param pytree: Kaiming-normal for rank>=2 weights
    (fan_out, as the reference's ``init_params`` uses for convs), zeros for
    biases/rank-1 leaves (parity: ``src/utils.py:29-42``)."""
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    rngs = jax.random.split(rng, len(leaves))
    out = []
    for leaf, r in zip(leaves, rngs):
        if leaf.ndim >= 2:
            fan_out = leaf.shape[-1] * int(np.prod(leaf.shape[:-2]))
            std = float(np.sqrt(2.0 / max(fan_out, 1)))
            out.append(std * jax.random.normal(r, leaf.shape, leaf.dtype))
        else:
            out.append(jnp.zeros_like(leaf))
    return jax.tree_util.tree_unflatten(treedef, out)
