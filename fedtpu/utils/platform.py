"""Platform/env plumbing shared by every process entry point.

One canonical implementation of the XLA virtual-device-count flag munging so
the CLI, the driver entry and the examples cannot drift (each previously
hand-rolled its own append/replace of ``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import os
import re
from typing import MutableMapping, Optional


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point jax's persistent compilation cache at ``path`` (default:
    ``FEDTPU_COMPILE_CACHE`` or ``~/.cache/fedtpu-xla``). On the remote-tunnel
    TPU a large program's compile can outlive the tunnel window that started
    it (observed: the remat resnet18 fused program, round 4); with the cache
    on, the next window resumes from the cached executables instead of
    recompiling from scratch. Safe to call before or after backend init;
    no-op on failure (older jax without the config).

    Skipped when the platform is pinned to CPU (config or ``JAX_PLATFORMS``)
    unless ``path`` is given explicitly: caching only pays on the wedge-prone
    accelerator, and XLA:CPU AOT reload warns about host machine-feature
    mismatches ("could lead to SIGILL") — not a risk worth taking to save
    seconds-scale CPU compiles in tests."""
    try:
        import jax

        if path is None:
            pinned = (
                getattr(jax.config, "jax_platforms", None)
                or os.environ.get("JAX_PLATFORMS", "")
                or ""
            )
            if pinned and "cpu" in pinned and "tpu" not in pinned:
                return
        cache = path or os.environ.get(
            "FEDTPU_COMPILE_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache", "fedtpu-xla"),
        )
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
    except Exception:
        pass


def force_host_device_count(
    n: int, env: Optional[MutableMapping[str, str]] = None
) -> None:
    """Set ``--xla_force_host_platform_device_count=n`` in ``env`` (default:
    ``os.environ``), replacing any existing occurrence. Must run before jax
    initialises its backends to have any effect."""
    if env is None:
        env = os.environ
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()
