"""Platform/env plumbing shared by every process entry point.

One canonical implementation of the XLA virtual-device-count flag munging so
the CLI, the driver entry and the examples cannot drift (each previously
hand-rolled its own append/replace of ``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import os
import re
from typing import MutableMapping, Optional


def force_host_device_count(
    n: int, env: Optional[MutableMapping[str, str]] = None
) -> None:
    """Set ``--xla_force_host_platform_device_count=n`` in ``env`` (default:
    ``os.environ``), replacing any existing occurrence. Must run before jax
    initialises its backends to have any effect."""
    if env is None:
        env = os.environ
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()
