"""Platform/env plumbing shared by every process entry point.

One canonical implementation of the XLA virtual-device-count flag munging so
the CLI, the driver entry and the examples cannot drift (each previously
hand-rolled its own append/replace of ``--xla_force_host_platform_device_count``).
"""

from __future__ import annotations

import os
import re
from typing import MutableMapping, Optional


def shard_map(body, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` across jax versions.

    jax >= 0.6 exposes it as public ``jax.shard_map`` with the replication
    checker spelled ``check_vma``; 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the same flag spelled
    ``check_rep``. Every fedtpu call site goes through this one wrapper so a
    version bump is a one-line change (and the 0.4.x environment actually
    runs the mesh suite instead of AttributeError-ing on ``jax.shard_map``).
    """
    import jax

    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point jax's persistent compilation cache at ``path`` (default:
    ``FEDTPU_COMPILE_CACHE`` or ``~/.cache/fedtpu-xla``). On the remote-tunnel
    TPU a large program's compile can outlive the tunnel window that started
    it (observed: the remat resnet18 fused program, round 4); with the cache
    on, the next window resumes from the cached executables instead of
    recompiling from scratch. Safe to call before or after backend init;
    no-op on failure (older jax without the config).

    Skipped when the ACTIVE backend is CPU, unless ``path`` or
    ``FEDTPU_COMPILE_CACHE`` opts in explicitly: caching only pays on
    accelerators, and XLA:CPU AOT reload warns about host machine-feature
    mismatches ("could lead to SIGILL") — not a risk worth taking to save
    seconds-scale CPU compiles in tests. Deciding on the real backend
    (``jax.default_backend()``) rather than the pin strings keeps a
    ``cuda,cpu`` fallback list cached and an unpinned CPU-only box safe;
    callers (the engine) are about to touch the backend anyway, so this
    introduces no new hang point on a wedged tunnel."""
    try:
        import jax

        explicit = path or os.environ.get("FEDTPU_COMPILE_CACHE")
        if not explicit and jax.default_backend() == "cpu":
            return
        cache = explicit or os.path.join(
            os.path.expanduser("~"), ".cache", "fedtpu-xla"
        )
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
    except Exception:
        pass


def force_host_device_count(
    n: int, env: Optional[MutableMapping[str, str]] = None
) -> None:
    """Set ``--xla_force_host_platform_device_count=n`` in ``env`` (default:
    ``os.environ``), replacing any existing occurrence. Must run before jax
    initialises its backends to have any effect."""
    if env is None:
        env = os.environ
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        env.get("XLA_FLAGS", ""),
    )
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={int(n)}"
    ).strip()
