"""Headless-safe progress rendering + profiling hooks.

The reference's ``progress_bar`` (``src/utils.py:51-92``) reads the terminal
width via ``stty size`` at *import* time and crashes headless runs; this one
probes lazily, falls back to 80 columns, and degrades to plain line logging
when stdout isn't a tty. ``profile_rounds`` wraps a block in
``jax.profiler.trace`` so a round loop can be profiled with one flag
(``fedtpu.cli.run --profile-dir``) — the subsystem the reference lacks
entirely (SURVEY §5: tracing "minimal").
"""

from __future__ import annotations

import contextlib
import shutil
import sys
import time
from typing import Iterator, Optional

from fedtpu.utils.metrics import format_time


class ProgressBar:
    """Per-step progress with loss/acc readout (parity: ``progress_bar``,
    ``src/utils.py:51-92``, minus the tty landmines)."""

    def __init__(self, total: int, width: Optional[int] = None, out=None):
        self.total = total
        self.out = out or sys.stderr
        self._tty = hasattr(self.out, "isatty") and self.out.isatty()
        cols = width or (shutil.get_terminal_size((80, 24)).columns if self._tty else 80)
        self.bar_width = max(10, min(40, cols - 45))
        self.t0 = time.time()
        self.last = self.t0

    def update(self, step: int, msg: str = "") -> None:
        now = time.time()
        step_time, self.last = now - self.last, now
        done = int(self.bar_width * (step + 1) / self.total)
        line = (
            f" [{'=' * done}{'.' * (self.bar_width - done)}] "
            f"{step + 1}/{self.total} "
            f"step {format_time(step_time)} tot {format_time(now - self.t0)}"
        )
        if msg:
            line += " | " + msg
        if self._tty:
            self.out.write("\r" + line[: shutil.get_terminal_size((80, 24)).columns - 1])
            if step + 1 >= self.total:
                self.out.write("\n")
        else:
            self.out.write(line + "\n")
        self.out.flush()


@contextlib.contextmanager
def profile_rounds(trace_dir: Optional[str]) -> Iterator[None]:
    """``with profile_rounds("/tmp/trace"):`` captures an XLA/TPU profile of
    the enclosed rounds (viewable in TensorBoard/XProf); no-op when
    ``trace_dir`` is None."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
