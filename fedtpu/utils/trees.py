"""Pytree arithmetic helpers.

The reference does its parameter arithmetic key-by-key over torch state_dicts
on the host (reference: ``src/server.py:163-171``). Here the equivalents are
traceable pytree maps that stay on-device and fuse into the surrounding XLA
program.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Pytree = Any


def tree_add(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.add, a, b)


def tree_sub(a: Pytree, b: Pytree) -> Pytree:
    return jax.tree.map(jnp.subtract, a, b)


def tree_scale(a: Pytree, s) -> Pytree:
    return jax.tree.map(lambda x: x * s, a)


def tree_zeros_like(a: Pytree) -> Pytree:
    return jax.tree.map(jnp.zeros_like, a)


def tree_dot(a: Pytree, b: Pytree):
    leaves = jax.tree.leaves(jax.tree.map(lambda x, y: jnp.vdot(x, y), a, b))
    return sum(leaves, start=jnp.zeros((), dtype=jnp.float32))


def tree_sq_norm(a: Pytree):
    leaves = jax.tree.leaves(
        jax.tree.map(lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))), a)
    )
    return sum(leaves, start=jnp.zeros((), dtype=jnp.float32))


def tree_norm(a: Pytree):
    return jnp.sqrt(tree_sq_norm(a))


def tree_size(a: Pytree) -> int:
    """Total number of scalar parameters (static)."""
    return sum(int(x.size) for x in jax.tree.leaves(a))


def tree_cast(a: Pytree, dtype) -> Pytree:
    return jax.tree.map(lambda x: x.astype(dtype), a)


def tree_ravel(a: Pytree):
    """Flatten a pytree to a single 1-D vector plus an unravel closure."""
    return jax.flatten_util.ravel_pytree(a)


def tree_concat_flat(a: Pytree) -> jnp.ndarray:
    """Concatenate every leaf, raveled, into one ``[total]`` f32 vector
    (``jax.tree_util.tree_flatten`` order). The single-tree packer primitive
    behind the flat delta layout (:mod:`fedtpu.ops.flat`)."""
    leaves = jax.tree_util.tree_leaves(a)
    return jnp.concatenate(
        [l.reshape((-1,)).astype(jnp.float32) for l in leaves]
    )


def tree_concat_rows(a: Pytree) -> jnp.ndarray:
    """Concatenate ``[n, ...]``-stacked leaves into one ``[n, total]`` f32
    buffer: each leaf reshaped to ``[n, size]``, joined along axis 1. Pure
    data movement (XLA folds it into the surrounding program); the stacked
    packer primitive behind the flat delta layout."""
    leaves = jax.tree_util.tree_leaves(a)
    n = leaves[0].shape[0]
    return jnp.concatenate(
        [l.reshape((n, -1)).astype(jnp.float32) for l in leaves], axis=1
    )


def tree_stack(trees):
    """Stack a list of identically-structured pytrees along a new axis 0."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *trees)


def tree_index(stacked: Pytree, i) -> Pytree:
    """Select slot ``i`` along the leading axis of every leaf."""
    return jax.tree.map(lambda x: x[i], stacked)
