"""Mesh-parallel federated round via ``shard_map``.

This is the scale-out path replacing the reference's thread-per-client gRPC
fan-out (``src/server.py:124-153``): the ``clients`` axis of all per-client
state and data is sharded across the mesh, each device vmaps local SGD over
its own slice of clients, and FedAvg is a ``lax.psum`` over the mesh axis —
XLA lowers it to ICI all-reduces with zero host involvement.
"""

from __future__ import annotations

from typing import Callable, Tuple

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fedtpu.utils.platform import shard_map
from fedtpu.config import RoundConfig
from fedtpu.core.round import (
    FederatedState,
    RoundBatch,
    RoundMetrics,
    make_round_step,
)

Pytree = object


def state_specs(axis: str) -> FederatedState:
    """PartitionSpecs for FederatedState: global model replicated, per-client
    state sharded along the clients axis."""
    return FederatedState(
        params=P(),
        batch_stats=P(),
        opt_state=P(axis),
        client_rng=P(axis),
        round_idx=P(),
        comp_state=P(axis),
        server_opt_state=P(),  # server moments act on the global model
        last_client_loss=P(axis),
    )


def batch_specs(axis: str) -> RoundBatch:
    return RoundBatch(
        x=P(axis), y=P(axis), step_mask=P(axis), weights=P(axis), alive=P(axis)
    )


def make_sharded_round_step(
    model: nn.Module,
    cfg: RoundConfig,
    mesh: Mesh,
    compressor=None,  # Optional[fedtpu.ops.compression.Compressor]
    donate: bool = True,
) -> Callable[[FederatedState, RoundBatch], Tuple[FederatedState, RoundMetrics]]:
    """Jitted round step over a client mesh.

    ``cfg.fed.num_clients`` must be divisible by the mesh size; each device
    simulates ``num_clients / mesh_size`` clients.
    """
    axis = cfg.mesh_axis
    n_dev = mesh.devices.size
    if cfg.fed.num_clients % n_dev:
        raise ValueError(
            f"num_clients={cfg.fed.num_clients} not divisible by mesh size {n_dev}"
        )

    body = make_round_step(model, cfg, compressor=compressor, axis_name=axis)

    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_specs(axis), batch_specs(axis)),
        out_specs=(
            state_specs(axis),
            RoundMetrics(P(), P(), P(), P(), P(axis), P(axis)),
        ),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())


def async_state_specs(axis: str):
    """PartitionSpecs for :class:`fedtpu.core.async_engine.AsyncState`.

    Same layout rule as the sync state: the global model (and the server
    optimizer moments + version counter) replicated, every per-client array
    sharded along the clients axis. Async's defining extra — per-client
    DIVERGED model copies (``client_*``) and pull snapshots (``base_*``) —
    shard by client exactly like presharded data rows, so per-device HBM is
    ``3 * params * clients_per_device`` (local + base + momentum) instead of
    ``3 * params * clients``: the mesh is what makes large async
    populations fit, not a reason async can't shard.
    """
    from fedtpu.core.async_engine import AsyncState

    return AsyncState(
        params=P(),
        batch_stats=P(),
        client_params=P(axis),
        client_stats=P(axis),
        base_params=P(axis),
        base_stats=P(axis),
        opt_state=P(axis),
        client_rng=P(axis),
        base_version=P(axis),
        version=P(),
        pending=P(axis),
        server_opt_state=P(),
        last_client_loss=P(axis),
    )


def _async_data_specs(axis: str, layout: str):
    """(images, labels, idx, mask) specs per device layout — mirrors
    ``Federation._ensure_device_data``: presharded per-client rows shard by
    client; the gather layout's flat dataset is replicated with only the
    assignment sharded."""
    if layout == "presharded":
        return (P(axis), P(axis), P(axis), P(axis))
    return (P(), P(), P(axis), P(axis))


def make_sharded_async_step(
    model: nn.Module,
    cfg: RoundConfig,
    mesh: Mesh,
    steps: int,
    staleness_power: float = 0.5,
    shuffle: bool = True,
    image_shape=None,
    layout: str = "presharded",
    num_ticks: int | None = None,
    staleness_damping: bool = True,
):
    """Jitted FedBuff tick (or ``num_ticks``-tick fused scan) over a client
    mesh — the async analogue of :func:`make_sharded_round_step`. Buffer
    aggregation and scalar metrics are ``psum`` collectives over ICI; the
    host schedules arrivals exactly as in the single-program form.
    """
    from fedtpu.core.async_engine import (
        AsyncMetrics,
        make_async_step,
        make_multi_async_step,
    )

    axis = cfg.mesh_axis
    n_dev = mesh.devices.size
    if cfg.fed.num_clients % n_dev:
        raise ValueError(
            f"num_clients={cfg.fed.num_clients} not divisible by mesh size {n_dev}"
        )
    if num_ticks is None:
        body = make_async_step(
            model, cfg, steps, staleness_power, shuffle=shuffle,
            image_shape=image_shape, layout=layout, axis_name=axis,
            staleness_damping=staleness_damping,
        )
        sched_spec = P(axis)  # arrive/alive: [clients]
    else:
        body = make_multi_async_step(
            model, cfg, steps, num_ticks, staleness_power, shuffle=shuffle,
            image_shape=image_shape, layout=layout, axis_name=axis,
            staleness_damping=staleness_damping,
        )
        sched_spec = P(None, axis)  # arrive/alive: [ticks, clients]

    metric_specs = AsyncMetrics(
        loss=P(), accuracy=P(), num_arrived=P(), staleness_mean=P(),
        update_norm=P(), per_client_loss=P(axis),
    )
    if num_ticks is not None:
        # Stacked over the scan axis: scalars gain a leading ticks dim.
        metric_specs = AsyncMetrics(
            loss=P(), accuracy=P(), num_arrived=P(), staleness_mean=P(),
            update_norm=P(), per_client_loss=P(None, axis),
        )
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            async_state_specs(axis),
            *_async_data_specs(axis, layout),
            P(axis),      # weights
            sched_spec,   # arrive
            sched_spec,   # alive
            P(),          # data_key
        ),
        out_specs=(async_state_specs(axis), metric_specs),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def _put(x, mesh: Mesh, spec) -> jax.Array:
    """Place a host-global array onto the mesh.

    Single-process: plain ``device_put`` (device-to-device for inputs already
    on device — no host roundtrip). Multi-controller: ``make_array_from_callback``
    so each process materialises only the shards its local devices own, even
    though the mesh spans every host (see :mod:`fedtpu.parallel.multihost`).
    """
    import numpy as np

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


def shard_state(state: FederatedState, mesh: Mesh, axis: str) -> FederatedState:
    """Place a host-built FederatedState onto the mesh with the right
    shardings (global model replicated, client state split)."""
    specs = state_specs(axis)

    def put(x, spec):
        return _put(x, mesh, spec)

    return FederatedState(
        params=jax.tree.map(lambda x: put(x, specs.params), state.params),
        batch_stats=jax.tree.map(
            lambda x: put(x, specs.batch_stats), state.batch_stats
        ),
        opt_state=jax.tree.map(lambda x: put(x, P(axis)), state.opt_state),
        client_rng=put(state.client_rng, P(axis)),
        round_idx=put(state.round_idx, P()),
        comp_state=jax.tree.map(lambda x: put(x, P(axis)), state.comp_state),
        server_opt_state=jax.tree.map(
            lambda x: put(x, P()), state.server_opt_state
        ),
        last_client_loss=put(state.last_client_loss, P(axis)),
    )


def shard_batch(batch: RoundBatch, mesh: Mesh, axis: str) -> RoundBatch:
    def put(x, spec):
        return _put(x, mesh, spec)

    return RoundBatch(
        x=put(batch.x, P(axis)),
        y=put(batch.y, P(axis)),
        step_mask=put(batch.step_mask, P(axis)),
        weights=put(batch.weights, P(axis)),
        alive=put(batch.alive, P(axis)),
    )
