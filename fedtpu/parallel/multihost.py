"""Multi-host (pod / multi-pod) initialization.

The reference's "distributed backend" is hub-and-spoke gRPC between
arbitrary hosts (SURVEY §2e). fedtpu's intra-pod story needs none of that:
on a TPU pod each host runs this same program, ``jax.distributed`` wires the
controllers together, and the single jitted round step sees ALL the pod's
devices — the clients-axis ``psum`` rides ICI between chips and DCN between
hosts, inserted by XLA, with zero application-level networking.

Usage on each host of a slice:

    from fedtpu.parallel import multihost
    multihost.initialize()              # env-driven on Cloud TPU
    mesh = client_mesh()                # now spans every host's devices

The gRPC edge (:mod:`fedtpu.transport`) remains for federation *across*
trust/admin boundaries — real cross-silo FL — where collective transport is
not an option.
"""

from __future__ import annotations

from typing import Optional

import jax


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bring up the multi-controller runtime (idempotent).

    With no arguments, relies on the TPU environment's auto-detection
    (Cloud TPU sets the coordinator/process topology). Explicit arguments
    support CPU/GPU fleets or tests:
    ``initialize("host0:1234", num_processes=2, process_id=...)``.
    """
    # NOTE: must not touch jax.process_count()/jax.devices() here — any such
    # call initializes the XLA backend, after which distributed.initialize()
    # refuses to run. The distributed-client check is backend-free.
    if _already_initialized():
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError:
        if kwargs or _cluster_env_detected():
            # Explicit args, or a cluster environment that *should* have
            # worked: silently degrading to N independent single-host runs
            # (each believing it is the coordinator) would be far worse than
            # failing here.
            raise
        # Env auto-detection found no cluster (single host, no pod
        # environment): multi-controller setup simply isn't needed.


def _cluster_env_detected() -> bool:
    import os

    return any(
        os.environ.get(k)
        for k in (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
        )
    )


def _already_initialized() -> bool:
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def is_coordinator() -> bool:
    """True on process 0 — the host that should write checkpoints/metrics
    (all hosts execute the same jitted step; only one should do IO)."""
    return jax.process_index() == 0


def local_client_slice(num_clients: int) -> slice:
    """The contiguous block of the global clients axis this host feeds.

    With ``num_clients`` divisible by ``process_count``, host ``i`` loads
    data only for clients ``[i * per_host, (i + 1) * per_host)`` — each host
    materialises 1/P of the batch tensors and ``jax.make_array_from_process_local_data``
    (or ``shard_batch`` on a global mesh) assembles the global array.
    """
    procs = max(1, jax.process_count())
    if num_clients % procs:
        raise ValueError(
            f"num_clients={num_clients} must be divisible by "
            f"process_count={procs} (remainder clients would silently get "
            f"no data)"
        )
    per_host = num_clients // procs
    start = jax.process_index() * per_host
    return slice(start, start + per_host)
