"""Device mesh construction.

The reference's "topology" is a hub-and-spoke of gRPC channels over TCP
(``src/server.py:109-111,281-282``). The TPU-native topology is a
``jax.sharding.Mesh``: one logical ``clients`` axis over all chips (pure
federated data parallelism — §2d of SURVEY.md), with room for extra axes
(``model``) if a future model is too large for one chip.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def client_mesh(
    num_devices: Optional[int] = None,
    axis_name: str = "clients",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """A 1-D mesh mapping the federated clients axis across chips.

    On multi-host TPU slices ``jax.devices()`` already spans hosts, so the
    same mesh scales from 1 chip to a pod; the collectives XLA inserts for the
    psum-FedAvg ride ICI (and DCN between slices) automatically.
    """
    devs = list(devices) if devices is not None else jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def client_sharded(mesh: Mesh, axis_name: str = "clients") -> NamedSharding:
    return NamedSharding(mesh, P(axis_name))


def partial_row_sharding(
    num_rows: int, axis_name: str = "clients",
    devices: Optional[Sequence[jax.Device]] = None,
) -> NamedSharding:
    """Row-axis sharding for the tiered root's ``[aggregators, P]``
    partial-sum buffer (docs/ARCHITECTURE.md §Multi-tier).

    Only the leading (row) axis shards — each device then holds whole
    partial rows and the root combine's axis-0 sum lowers to one
    psum-style cross-device reduce, with the wide P axis left contiguous
    for the VPU. When ``num_rows`` doesn't divide the device count the
    mesh shrinks to the largest divisor prefix (worst case 1 device,
    where this degrades to the ordinary single-buffer placement — the
    CPU-backed test/bench topologies land there and are no-ops).
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    while n > 1 and num_rows % n:
        n -= 1
    mesh = Mesh(np.asarray(devs[:n]), (axis_name,))
    return NamedSharding(mesh, P(axis_name))
