from fedtpu.parallel.mesh import client_mesh, client_sharded, replicated
from fedtpu.parallel.sharded import (
    async_state_specs,
    make_sharded_async_step,
    make_sharded_round_step,
    shard_batch,
    shard_state,
)
from fedtpu.parallel.dryrun import dryrun_multichip, dryrun_multichip_light
from fedtpu.parallel import multihost

__all__ = [
    "multihost",
    "client_mesh",
    "client_sharded",
    "replicated",
    "async_state_specs",
    "make_sharded_async_step",
    "make_sharded_round_step",
    "shard_batch",
    "shard_state",
    "dryrun_multichip",
    "dryrun_multichip_light",
]
