from fedtpu.parallel.mesh import client_mesh, client_sharded, replicated
from fedtpu.parallel.sharded import (
    make_sharded_round_step,
    shard_batch,
    shard_state,
)
from fedtpu.parallel.dryrun import dryrun_multichip
from fedtpu.parallel import multihost

__all__ = [
    "multihost",
    "client_mesh",
    "client_sharded",
    "replicated",
    "make_sharded_round_step",
    "shard_batch",
    "shard_state",
    "dryrun_multichip",
]
