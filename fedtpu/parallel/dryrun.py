"""Multi-chip dry run: jit the full sharded round step over an N-device
``clients`` mesh and execute one step on tiny shapes.

The standard way to validate the sharding story without hardware is N
virtual CPU devices (``XLA_FLAGS=--xla_force_host_platform_device_count=N``),
which must be set before jax initialises — see tests/conftest.py. On a real
slice the same call validates placement on actual chips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fedtpu.config import DataConfig, FedConfig, OptimizerConfig, RoundConfig


def dryrun_multichip(n_devices: int, model: str = "smallcnn") -> None:
    """Create an ``n_devices`` clients mesh, jit the full federated training
    step over it (2 simulated clients per device), run one step, and assert
    every client participated. Raises on any sharding/compile failure."""
    from fedtpu import models
    from fedtpu.core import round as round_lib
    from fedtpu.parallel import (
        client_mesh,
        make_sharded_round_step,
        shard_batch,
        shard_state,
    )

    cfg = RoundConfig(
        model=model,
        num_classes=10,
        opt=OptimizerConfig(),
        data=DataConfig(dataset="synthetic", batch_size=4),
        fed=FedConfig(num_clients=2 * n_devices),
        steps_per_round=2,
    )
    mdl = models.create(cfg.model, num_classes=cfg.num_classes)
    state = round_lib.init_state(
        mdl, cfg, jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.float32)
    )
    mesh = client_mesh(n_devices, cfg.mesh_axis)

    rng = np.random.default_rng(0)
    n, s, b = cfg.fed.num_clients, cfg.steps_per_round, cfg.data.batch_size
    batch = round_lib.RoundBatch(
        x=jnp.asarray(rng.normal(size=(n, s, b, 16, 16, 3)).astype(np.float32)),
        y=jnp.asarray(rng.integers(0, 10, size=(n, s, b)).astype(np.int32)),
        step_mask=jnp.ones((n, s), bool),
        weights=jnp.ones((n,), jnp.float32),
        alive=jnp.ones((n,), bool),
    )

    step = make_sharded_round_step(mdl, cfg, mesh, donate=False)
    new_state, metrics = step(
        shard_state(state, mesh, cfg.mesh_axis),
        shard_batch(batch, mesh, cfg.mesh_axis),
    )
    jax.block_until_ready(new_state)
    assert int(metrics.num_active) == n

    # Also compile+run the fused multi-round scan over the same mesh (the
    # headline-bench path): 2 rounds as one XLA program, shard_map inside.
    from fedtpu.core import Federation

    fed = Federation(cfg, seed=0, mesh=mesh)
    stacked = fed.run_on_device(2)
    assert stacked.loss.shape == (2,)
    assert int(fed.state.round_idx) == 2

    # And the bench's actual residency mode: bf16 compute with the device
    # dataset stored in the compute dtype, presharded rows sharded by client
    # over the mesh (round 4's perf path — engine._store_dtype).
    import dataclasses

    bf16 = dataclasses.replace(cfg, dtype="bfloat16")
    fed16 = Federation(bf16, seed=0, mesh=mesh)
    stacked16 = fed16.run_on_device(2)
    assert stacked16.loss.shape == (2,)

    # Top-k delta compression (error feedback riding per-client comp_state,
    # sharded by client) through the same mesh round (VERDICT r4 #8).
    topk = dataclasses.replace(
        cfg, fed=dataclasses.replace(cfg.fed, compression="topk",
                                     topk_fraction=0.1))
    fedc = Federation(topk, seed=0, mesh=mesh)
    mc = fedc.step()
    assert np.isfinite(float(mc.loss))

    # Byzantine-robust aggregation: the coordinate-wise median and the
    # pairwise-distance Krum rule, both of which all_gather the per-shard
    # deltas over the mesh axis (fedtpu.core.round._robust_over_clients).
    robust_losses = {}
    for rule in ("median", "krum"):
        rcfg = dataclasses.replace(
            cfg, fed=dataclasses.replace(cfg.fed, aggregator=rule,
                                         weighted=False))
        fedr = Federation(rcfg, seed=0, mesh=mesh)
        mr = fedr.step()
        robust_losses[rule] = float(mr.loss)
        assert np.isfinite(robust_losses[rule])

    # Async FedBuff tick under the mesh: per-client DIVERGED trajectories
    # sharded by client, buffer aggregation as a psum (core.async_engine
    # mesh mode).
    from fedtpu.core import AsyncFederation

    asyn = AsyncFederation(cfg, seed=0, buffer_k=2, mesh=mesh)
    ma = asyn.tick()
    assert int(asyn.state.version) == 1
    assert np.isfinite(float(ma.loss))

    # Non-power-of-two client count (3 clients per device): every leg above
    # runs 2/device, so an even-tiling assumption baked anywhere in the
    # shard/vmap plumbing would pass the whole battery and still break the
    # first odd deployment. On an 8-device mesh this is 24 clients; the
    # 16-device sweep leg makes it 48 (VERDICT r5 #7).
    import dataclasses as _dc

    odd = _dc.replace(
        cfg, fed=_dc.replace(cfg.fed, num_clients=3 * n_devices)
    )
    fodd = Federation(odd, seed=0, mesh=mesh)
    modd = fodd.step()
    assert np.isfinite(float(modd.loss))

    print(
        f"dryrun_multichip ok: {n_devices} devices, {n} clients, "
        f"loss={float(metrics.loss):.4f}, fused2_loss={float(stacked.loss[-1]):.4f}, "
        f"bf16_fused2_loss={float(stacked16.loss[-1]):.4f}, "
        f"topk_loss={float(mc.loss):.4f}, "
        f"median_loss={robust_losses['median']:.4f}, "
        f"krum_loss={robust_losses['krum']:.4f}, "
        f"async_tick_loss={float(ma.loss):.4f}, "
        f"odd_clients_loss={float(modd.loss):.4f} ({3 * n_devices}c)"
    )


def dryrun_multichip_light(n_devices: int, model: str = "smallcnn") -> None:
    """Reduced dryrun for the wide-mesh sweep leg: jit + run ONE sharded
    round step at 2 clients/device and one at a NON-power-of-two 3
    clients/device, skipping the full battery (fused scans, codecs, robust
    aggregators, async) that :func:`dryrun_multichip` already exercises at
    8 devices. A 16-virtual-device mesh catches divisibility/layout edges
    the 8-device mesh cannot (VERDICT r5 #7) at a fraction of the compile
    bill."""
    import dataclasses as _dc

    from fedtpu import models
    from fedtpu.core import round as round_lib
    from fedtpu.parallel import (
        client_mesh,
        make_sharded_round_step,
        shard_batch,
        shard_state,
    )

    losses = {}
    for per_device in (2, 3):
        cfg = RoundConfig(
            model=model,
            num_classes=10,
            opt=OptimizerConfig(),
            data=DataConfig(dataset="synthetic", batch_size=4),
            fed=FedConfig(num_clients=per_device * n_devices),
            steps_per_round=2,
        )
        mdl = models.create(cfg.model, num_classes=cfg.num_classes)
        state = round_lib.init_state(
            mdl, cfg, jax.random.PRNGKey(0),
            jnp.zeros((1, 16, 16, 3), jnp.float32),
        )
        mesh = client_mesh(n_devices, cfg.mesh_axis)
        rng = np.random.default_rng(0)
        n, s, b = cfg.fed.num_clients, cfg.steps_per_round, cfg.data.batch_size
        batch = round_lib.RoundBatch(
            x=jnp.asarray(
                rng.normal(size=(n, s, b, 16, 16, 3)).astype(np.float32)
            ),
            y=jnp.asarray(rng.integers(0, 10, size=(n, s, b)).astype(np.int32)),
            step_mask=jnp.ones((n, s), bool),
            weights=jnp.ones((n,), jnp.float32),
            alive=jnp.ones((n,), bool),
        )
        step = make_sharded_round_step(mdl, cfg, mesh, donate=False)
        new_state, metrics = step(
            shard_state(state, mesh, cfg.mesh_axis),
            shard_batch(batch, mesh, cfg.mesh_axis),
        )
        jax.block_until_ready(new_state)
        assert int(metrics.num_active) == n
        losses[per_device] = float(metrics.loss)

    print(
        f"dryrun_multichip_light ok: {n_devices} devices, "
        f"loss_2perdev={losses[2]:.4f} ({2 * n_devices}c), "
        f"loss_3perdev={losses[3]:.4f} ({3 * n_devices}c)"
    )
