"""ResNeXt-29 family for CIFAR (parity: reference ``src/models/resnext.py``).

Grouped-convolution bottleneck blocks (1x1 → grouped 3x3 → 1x1, expansion 2)
over three stages of three blocks; the bottleneck width doubles per stage.
Constructors match the reference exports ResNeXt29_{2x64,4x64,8x64,32x4}d
(``src/models/resnext.py:77-87``).
"""

from __future__ import annotations

import flax.linen as nn

from fedtpu.models.common import batch_norm, conv1x1, global_avg_pool
from fedtpu.models.registry import register


class ResNeXtBlock(nn.Module):
    cardinality: int
    bottleneck_width: int
    stride: int = 1
    expansion: int = 2

    @nn.compact
    def __call__(self, x, train: bool = False):
        group_width = self.cardinality * self.bottleneck_width
        out_ch = self.expansion * group_width
        y = conv1x1(group_width)(x)
        y = nn.relu(batch_norm(train)(y))
        y = nn.Conv(
            group_width,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=self.cardinality,
            use_bias=False,
        )(y)
        y = nn.relu(batch_norm(train)(y))
        y = conv1x1(out_ch)(y)
        y = batch_norm(train)(y)
        if self.stride != 1 or x.shape[-1] != out_ch:
            shortcut = conv1x1(out_ch, strides=(self.stride, self.stride))(x)
            shortcut = batch_norm(train)(shortcut)
        else:
            shortcut = x
        return nn.relu(y + shortcut)


class ResNeXtModule(nn.Module):
    num_blocks: tuple
    cardinality: int
    bottleneck_width: int
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv1x1(64)(x)
        x = nn.relu(batch_norm(train)(x))
        width = self.bottleneck_width
        for stage, n in enumerate(self.num_blocks):
            for i in range(n):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                x = ResNeXtBlock(self.cardinality, width, stride)(x, train=train)
            width *= 2  # bottleneck width doubles after each stage
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("resnext29_2x64d")
def ResNeXt29_2x64d(num_classes: int = 10) -> nn.Module:
    return ResNeXtModule((3, 3, 3), 2, 64, num_classes)


@register("resnext29_4x64d")
def ResNeXt29_4x64d(num_classes: int = 10) -> nn.Module:
    return ResNeXtModule((3, 3, 3), 4, 64, num_classes)


@register("resnext29_8x64d")
def ResNeXt29_8x64d(num_classes: int = 10) -> nn.Module:
    return ResNeXtModule((3, 3, 3), 8, 64, num_classes)


@register("resnext29_32x4d")
def ResNeXt29_32x4d(num_classes: int = 10) -> nn.Module:
    return ResNeXtModule((3, 3, 3), 32, 4, num_classes)
