"""RegNet X/Y for CIFAR (parity: reference ``src/models/regnet.py``).

Bottleneck blocks: 1x1 → grouped 3x3 (group width from config) → optional SE
(RegNetY) → 1x1, projected shortcut on stride/width change. Stage
depths/widths/strides per the reference configs
(``src/models/regnet.py:110-143``): RegNetX_200MF, RegNetX_400MF,
RegNetY_400MF.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class RegNetBlock(nn.Module):
    features: int
    stride: int
    group_width: int
    bottleneck_ratio: float = 1.0
    se_ratio: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        w_b = int(round(self.features * self.bottleneck_ratio))
        y = conv1x1(w_b)(x)
        y = nn.relu(batch_norm(train)(y))
        y = nn.Conv(
            w_b,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=w_b // self.group_width,
            use_bias=False,
        )(y)
        y = nn.relu(batch_norm(train)(y))
        if self.se_ratio > 0:
            w_se = int(round(in_ch * self.se_ratio))
            w = jnp.mean(y, axis=(1, 2), keepdims=True)
            w = nn.relu(nn.Conv(w_se, (1, 1))(w))
            w = nn.sigmoid(nn.Conv(w_b, (1, 1))(w))
            y = y * w
        y = conv1x1(self.features)(y)
        y = batch_norm(train)(y)
        if self.stride != 1 or in_ch != self.features:
            shortcut = conv1x1(self.features, strides=(self.stride, self.stride))(x)
            shortcut = batch_norm(train)(shortcut)
        else:
            shortcut = x
        return nn.relu(y + shortcut)


class RegNetModule(nn.Module):
    depths: Sequence[int]
    widths: Sequence[int]
    strides: Sequence[int]
    group_width: int
    bottleneck_ratio: float = 1.0
    se_ratio: float = 0.0
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(64)(x)
        x = nn.relu(batch_norm(train)(x))
        for depth, width, stride in zip(self.depths, self.widths, self.strides):
            for i in range(depth):
                x = RegNetBlock(
                    width,
                    stride if i == 0 else 1,
                    self.group_width,
                    self.bottleneck_ratio,
                    self.se_ratio,
                )(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("regnetx_200mf")
def RegNetX_200MF(num_classes: int = 10) -> nn.Module:
    return RegNetModule(
        (1, 1, 4, 7), (24, 56, 152, 368), (1, 1, 2, 2), 8, num_classes=num_classes
    )


@register("regnetx_400mf")
def RegNetX_400MF(num_classes: int = 10) -> nn.Module:
    return RegNetModule(
        (1, 2, 7, 12), (32, 64, 160, 384), (1, 1, 2, 2), 16, num_classes=num_classes
    )


@register("regnety_400mf")
def RegNetY_400MF(num_classes: int = 10) -> nn.Module:
    return RegNetModule(
        (1, 2, 7, 12),
        (32, 64, 160, 384),
        (1, 1, 2, 2),
        16,
        se_ratio=0.25,
        num_classes=num_classes,
    )
