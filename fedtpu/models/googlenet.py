"""GoogLeNet / Inception for CIFAR (parity: reference ``src/models/googlenet.py``).

Four-branch Inception modules (1x1 | 1x1→3x3 | 1x1→3x3→3x3 | pool→1x1, all
conv+BN+ReLU, biased convs as in the reference) concatenated on channels; the
CIFAR stem is a single 3x3/192 conv. Branch widths follow the reference table
(``src/models/googlenet.py:60-72``); 8x8 global pool + dense head.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, global_avg_pool, max_pool
from fedtpu.models.registry import register


def _conv_bn_relu(x, features, kernel, train):
    x = nn.Conv(features, (kernel, kernel), padding=(kernel - 1) // 2)(x)
    return nn.relu(batch_norm(train)(x))


class Inception(nn.Module):
    n1x1: int
    n3x3red: int
    n3x3: int
    n5x5red: int
    n5x5: int
    pool_planes: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        b1 = _conv_bn_relu(x, self.n1x1, 1, train)

        b2 = _conv_bn_relu(x, self.n3x3red, 1, train)
        b2 = _conv_bn_relu(b2, self.n3x3, 3, train)

        # The "5x5" branch is two stacked 3x3 convs, as in the reference.
        b3 = _conv_bn_relu(x, self.n5x5red, 1, train)
        b3 = _conv_bn_relu(b3, self.n5x5, 3, train)
        b3 = _conv_bn_relu(b3, self.n5x5, 3, train)

        b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
        b4 = _conv_bn_relu(b4, self.pool_planes, 1, train)

        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


# (n1x1, n3x3red, n3x3, n5x5red, n5x5, pool_planes) per module; None = maxpool.
_PLAN: Sequence = (
    (64, 96, 128, 16, 32, 32),     # a3 (in 192)
    (128, 128, 192, 32, 96, 64),   # b3 (in 256)
    None,
    (192, 96, 208, 16, 48, 64),    # a4 (in 480)
    (160, 112, 224, 24, 64, 64),   # b4
    (128, 128, 256, 24, 64, 64),   # c4
    (112, 144, 288, 32, 64, 64),   # d4
    (256, 160, 320, 32, 128, 128), # e4
    None,
    (256, 160, 320, 32, 128, 128), # a5
    (384, 192, 384, 48, 128, 128), # b5 (out 1024)
)


class GoogLeNetModule(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _conv_bn_relu(x, 192, 3, train)
        for spec in _PLAN:
            if spec is None:
                x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
            else:
                x = Inception(*spec)(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("googlenet")
def GoogLeNet(num_classes: int = 10) -> nn.Module:
    return GoogLeNetModule(num_classes=num_classes)
