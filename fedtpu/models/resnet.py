"""CIFAR ResNet family (parity: reference ``src/models/resnet.py``).

BasicBlock (two 3x3 convs, expansion 1) and Bottleneck (1x1-3x3-1x1,
expansion 4) residual stages over widths (64, 128, 256, 512) with strides
(1, 2, 2, 2), 3x3/64 stem, global pool + dense head. Exported constructors
match the reference: ResNet18/34/50/101/152 (``src/models/resnet.py:107-124``).
"""

from __future__ import annotations

from typing import Sequence, Type

import flax.linen as nn

from fedtpu.models.common import (
    batch_norm,
    conv1x1,
    conv3x3,
    global_avg_pool,
    maybe_remat,
)
from fedtpu.models.registry import register


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.features * self.expansion
        residual = x
        y = conv3x3(self.features, strides=(self.stride, self.stride))(x)
        y = batch_norm(train)(y)
        y = nn.relu(y)
        y = conv3x3(self.features)(y)
        y = batch_norm(train)(y)
        if self.stride != 1 or x.shape[-1] != out_ch:
            residual = conv1x1(out_ch, strides=(self.stride, self.stride))(x)
            residual = batch_norm(train)(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    features: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.features * self.expansion
        residual = x
        y = conv1x1(self.features)(x)
        y = batch_norm(train)(y)
        y = nn.relu(y)
        y = conv3x3(self.features, strides=(self.stride, self.stride))(y)
        y = batch_norm(train)(y)
        y = nn.relu(y)
        y = conv1x1(out_ch)(y)
        y = batch_norm(train)(y)
        if self.stride != 1 or x.shape[-1] != out_ch:
            residual = conv1x1(out_ch, strides=(self.stride, self.stride))(x)
            residual = batch_norm(train)(residual)
        return nn.relu(y + residual)


class ResNetModule(nn.Module):
    block: Type[nn.Module]
    num_blocks: Sequence[int]
    num_classes: int = 10
    # Per-block rematerialisation: backward recomputes each residual block
    # instead of storing its activations — the standard TPU FLOPs-for-HBM
    # trade. Measured to matter: the 64-client CIFAR-100 federated round
    # (BASELINE.md config 4) exceeds one v5e's 16 GB HBM without it.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(64)(x)
        x = batch_norm(train)(x)
        x = nn.relu(x)
        count = 0
        for stage, (features, n) in enumerate(zip((64, 128, 256, 512), self.num_blocks)):
            for i in range(n):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                # Explicit name keeps params/checkpoints identical whether or
                # not remat is on (see common.maybe_remat).
                x = maybe_remat(self.block, self.remat)(
                    features=features,
                    stride=stride,
                    name=f"{self.block.__name__}_{count}",
                )(x, train)
                count += 1
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("resnet18")
def ResNet18(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return ResNetModule(BasicBlock, (2, 2, 2, 2), num_classes, remat)


@register("resnet34")
def ResNet34(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return ResNetModule(BasicBlock, (3, 4, 6, 3), num_classes, remat)


@register("resnet50")
def ResNet50(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return ResNetModule(Bottleneck, (3, 4, 6, 3), num_classes, remat)


@register("resnet101")
def ResNet101(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return ResNetModule(Bottleneck, (3, 4, 23, 3), num_classes, remat)


@register("resnet152")
def ResNet152(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return ResNetModule(Bottleneck, (3, 8, 36, 3), num_classes, remat)
