"""ShuffleNetV2 for CIFAR (parity: reference ``src/models/shufflenetv2.py``).

Basic blocks split channels 50/50, transform one half (1x1 → 3x3 depthwise →
1x1), concat, then shuffle with 2 groups; down blocks transform both halves
with stride 2 and concat. Size configs 0.5/1/1.5/2 follow the reference table
(``src/models/shufflenetv2.py:141-160``); ``ShuffleNetV2(net_size)`` is the
constructor surface.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register
from fedtpu.models.shufflenet import channel_shuffle

_CONFIGS = {
    0.5: {"out_channels": (48, 96, 192, 1024), "num_blocks": (3, 7, 3)},
    1: {"out_channels": (116, 232, 464, 1024), "num_blocks": (3, 7, 3)},
    1.5: {"out_channels": (176, 352, 704, 1024), "num_blocks": (3, 7, 3)},
    2: {"out_channels": (224, 488, 976, 2048), "num_blocks": (3, 7, 3)},
}


def _depthwise(features, stride):
    return nn.Conv(
        features,
        (3, 3),
        strides=(stride, stride),
        padding=1,
        feature_group_count=features,
        use_bias=False,
    )


class SplitBlock(nn.Module):
    split_ratio: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        c = int(x.shape[-1] * self.split_ratio)
        x1, x2 = x[..., :c], x[..., c:]
        y = conv1x1(c)(x2)
        y = nn.relu(batch_norm(train)(y))
        y = _depthwise(c, 1)(y)
        y = batch_norm(train)(y)
        y = conv1x1(c)(y)
        y = nn.relu(batch_norm(train)(y))
        out = jnp.concatenate([x1, y], axis=-1)
        return channel_shuffle(out, 2)


class DownBlock(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        mid = self.features // 2
        # Left: depthwise stride-2 then 1x1.
        left = _depthwise(in_ch, 2)(x)
        left = batch_norm(train)(left)
        left = conv1x1(mid)(left)
        left = nn.relu(batch_norm(train)(left))
        # Right: 1x1, depthwise stride-2, 1x1.
        right = conv1x1(mid)(x)
        right = nn.relu(batch_norm(train)(right))
        right = _depthwise(mid, 2)(right)
        right = batch_norm(train)(right)
        right = conv1x1(mid)(right)
        right = nn.relu(batch_norm(train)(right))
        out = jnp.concatenate([left, right], axis=-1)
        return channel_shuffle(out, 2)


class ShuffleNetV2Module(nn.Module):
    out_channels: Sequence[int]
    num_blocks: Sequence[int]
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(24)(x)
        x = nn.relu(batch_norm(train)(x))
        for out, n in zip(self.out_channels[:3], self.num_blocks):
            x = DownBlock(out)(x, train=train)
            for _ in range(n):
                x = SplitBlock()(x, train=train)
        x = conv1x1(self.out_channels[3])(x)
        x = nn.relu(batch_norm(train)(x))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("shufflenetv2")
def ShuffleNetV2(net_size: float = 1, num_classes: int = 10) -> nn.Module:
    cfg = _CONFIGS[net_size]
    return ShuffleNetV2Module(cfg["out_channels"], cfg["num_blocks"], num_classes)
