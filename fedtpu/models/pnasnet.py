"""PNASNet A/B for CIFAR (parity: reference ``src/models/pnasnet.py``).

Cell A: 7x7 separable conv + 3x3 max-pool branch, summed. Cell B: two left
branches (7x7 and 3x3 separable) and two right branches (max-pool and 5x5
separable), pairwise-summed, concatenated and reduced by a 1x1 conv. Three
6-cell stages at widths (p, 2p, 4p) with stride-2 cells between —
PNASNetA (p=44), PNASNetB (p=32) (``src/models/pnasnet.py:112-116``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class SepConv(nn.Module):
    """Depthwise-grouped k x k conv + BN (one group per input channel)."""

    features: int
    kernel_size: int
    stride: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        k = self.kernel_size
        y = nn.Conv(
            self.features,
            (k, k),
            strides=(self.stride, self.stride),
            padding=(k - 1) // 2,
            feature_group_count=x.shape[-1],
            use_bias=False,
        )(x)
        return batch_norm(train)(y)


class CellA(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        y1 = SepConv(self.features, 7, self.stride)(x, train=train)
        y2 = nn.max_pool(
            x,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
        )
        if self.stride == 2:
            y2 = batch_norm(train)(conv1x1(self.features)(y2))
        return nn.relu(y1 + y2)


class CellB(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        y1 = SepConv(self.features, 7, self.stride)(x, train=train)
        y2 = SepConv(self.features, 3, self.stride)(x, train=train)
        y3 = nn.max_pool(
            x,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=((1, 1), (1, 1)),
        )
        if self.stride == 2:
            y3 = batch_norm(train)(conv1x1(self.features)(y3))
        y4 = SepConv(self.features, 5, self.stride)(x, train=train)
        b = jnp.concatenate([nn.relu(y1 + y2), nn.relu(y3 + y4)], axis=-1)
        b = conv1x1(self.features)(b)
        return nn.relu(batch_norm(train)(b))


class PNASNetModule(nn.Module):
    cell: type
    num_cells: int
    num_planes: int
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        p = self.num_planes
        x = conv3x3(p)(x)
        x = nn.relu(batch_norm(train)(x))
        for width, downsample in ((p, False), (2 * p, True), (4 * p, True)):
            if downsample:
                x = self.cell(width, stride=2)(x, train=train)
            for _ in range(self.num_cells):
                x = self.cell(width, stride=1)(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("pnasneta")
def PNASNetA(num_classes: int = 10) -> nn.Module:
    return PNASNetModule(CellA, num_cells=6, num_planes=44, num_classes=num_classes)


@register("pnasnetb")
def PNASNetB(num_classes: int = 10) -> nn.Module:
    return PNASNetModule(CellB, num_cells=6, num_planes=32, num_classes=num_classes)
