"""Dual Path Networks for CIFAR (parity: reference ``src/models/dpn.py``).

Each bottleneck (1x1 → grouped 3x3 (32 groups) → 1x1) emits
``out_planes + dense_depth`` channels: the first ``out_planes`` are summed
with the shortcut (residual path) and the tail is concatenated (dense path),
so the dense path grows by ``dense_depth`` every block. Constructors match
the reference: DPN26, DPN92 (``src/models/dpn.py:73-89``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class DualPathBlock(nn.Module):
    in_planes: int       # bottleneck width
    out_planes: int      # residual-path width
    dense_depth: int
    stride: int = 1
    first_layer: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.out_planes
        y = conv1x1(self.in_planes)(x)
        y = nn.relu(batch_norm(train)(y))
        y = nn.Conv(
            self.in_planes,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=32,
            use_bias=False,
        )(y)
        y = nn.relu(batch_norm(train)(y))
        y = conv1x1(d + self.dense_depth)(y)
        y = batch_norm(train)(y)
        if self.first_layer:
            shortcut = conv1x1(
                d + self.dense_depth, strides=(self.stride, self.stride)
            )(x)
            shortcut = batch_norm(train)(shortcut)
        else:
            shortcut = x
        out = jnp.concatenate(
            [shortcut[..., :d] + y[..., :d], shortcut[..., d:], y[..., d:]],
            axis=-1,
        )
        return nn.relu(out)


class DPNModule(nn.Module):
    in_planes: Sequence[int]
    out_planes: Sequence[int]
    num_blocks: Sequence[int]
    dense_depth: Sequence[int]
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(64)(x)
        x = nn.relu(batch_norm(train)(x))
        for stage in range(4):
            for i in range(self.num_blocks[stage]):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                x = DualPathBlock(
                    self.in_planes[stage],
                    self.out_planes[stage],
                    self.dense_depth[stage],
                    stride=stride,
                    first_layer=i == 0,
                )(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("dpn26")
def DPN26(num_classes: int = 10) -> nn.Module:
    return DPNModule(
        (96, 192, 384, 768),
        (256, 512, 1024, 2048),
        (2, 2, 2, 2),
        (16, 32, 24, 128),
        num_classes,
    )


@register("dpn92")
def DPN92(num_classes: int = 10) -> nn.Module:
    return DPNModule(
        (96, 192, 384, 768),
        (256, 512, 1024, 2048),
        (3, 4, 20, 3),
        (16, 32, 24, 128),
        num_classes,
    )
