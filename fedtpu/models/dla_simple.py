"""Simplified DLA for CIFAR (parity: reference ``src/models/dla_simple.py``).

Binary aggregation trees: each tree is (left subtree at stride s, right
subtree at stride 1 fed from the left) joined by a two-input Root; level-1
subtrees are residual BasicBlocks. Same stage plan as :mod:`fedtpu.models.dla`.
"""

from __future__ import annotations

import flax.linen as nn

from fedtpu.models.common import batch_norm, conv3x3, global_avg_pool
from fedtpu.models.registry import register
from fedtpu.models.dla import BasicBlock, Root


class SimpleTree(nn.Module):
    features: int
    level: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.level == 1:
            left = BasicBlock(self.features, self.stride)(x, train=train)
            right = BasicBlock(self.features, 1)(left, train=train)
        else:
            left = SimpleTree(self.features, self.level - 1, self.stride)(
                x, train=train
            )
            right = SimpleTree(self.features, self.level - 1, 1)(left, train=train)
        return Root(self.features)([left, right], train=train)


class SimpleDLAModule(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        for features in (16, 16, 32):
            x = conv3x3(features)(x)
            x = nn.relu(batch_norm(train)(x))
        x = SimpleTree(64, level=1, stride=1)(x, train=train)
        x = SimpleTree(128, level=2, stride=2)(x, train=train)
        x = SimpleTree(256, level=2, stride=2)(x, train=train)
        x = SimpleTree(512, level=1, stride=2)(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("simpledla")
def SimpleDLA(num_classes: int = 10) -> nn.Module:
    return SimpleDLAModule(num_classes=num_classes)
