"""fedtpu model zoo — flax.linen rebuilds of the reference CIFAR zoo
(``src/models/__init__.py:1-18``) plus the BASELINE parity models.

Constructor names mirror the reference exports so users of the reference find
the same surface: ``MobileNet()``, ``ResNet18()``, ``VGG('VGG19')``,
``ShuffleNetV2(1)``, ... Every architecture is also reachable by registry
string via :func:`create`.
"""

from fedtpu.models.registry import available, create, register

from fedtpu.models.mlp import MLP
from fedtpu.models.smallcnn import SmallCNN
from fedtpu.models.lenet import LeNet
from fedtpu.models.mobilenet import MobileNet
from fedtpu.models.mobilenetv2 import MobileNetV2
from fedtpu.models.resnet import ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from fedtpu.models.preact_resnet import (
    PreActResNet18,
    PreActResNet34,
    PreActResNet50,
    PreActResNet101,
    PreActResNet152,
)
from fedtpu.models.vgg import VGG
from fedtpu.models.googlenet import GoogLeNet
from fedtpu.models.densenet import (
    DenseNet121,
    DenseNet161,
    DenseNet169,
    DenseNet201,
    densenet_cifar,
)
from fedtpu.models.resnext import (
    ResNeXt29_2x64d,
    ResNeXt29_4x64d,
    ResNeXt29_8x64d,
    ResNeXt29_32x4d,
)
from fedtpu.models.senet import SENet18
from fedtpu.models.dpn import DPN26, DPN92
from fedtpu.models.shufflenet import ShuffleNetG2, ShuffleNetG3
from fedtpu.models.shufflenetv2 import ShuffleNetV2
from fedtpu.models.efficientnet import EfficientNetB0
from fedtpu.models.regnet import RegNetX_200MF, RegNetX_400MF, RegNetY_400MF
from fedtpu.models.pnasnet import PNASNetA, PNASNetB
from fedtpu.models.dla import DLA
from fedtpu.models.dla_simple import SimpleDLA

__all__ = [
    "available",
    "create",
    "register",
    "MLP",
    "SmallCNN",
    "LeNet",
    "MobileNet",
    "MobileNetV2",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "PreActResNet18",
    "PreActResNet34",
    "PreActResNet50",
    "PreActResNet101",
    "PreActResNet152",
    "VGG",
    "GoogLeNet",
    "DenseNet121",
    "DenseNet161",
    "DenseNet169",
    "DenseNet201",
    "densenet_cifar",
    "ResNeXt29_2x64d",
    "ResNeXt29_4x64d",
    "ResNeXt29_8x64d",
    "ResNeXt29_32x4d",
    "SENet18",
    "DPN26",
    "DPN92",
    "ShuffleNetG2",
    "ShuffleNetG3",
    "ShuffleNetV2",
    "EfficientNetB0",
    "RegNetX_200MF",
    "RegNetX_400MF",
    "RegNetY_400MF",
    "PNASNetA",
    "PNASNetB",
    "DLA",
    "SimpleDLA",
]
