"""fedtpu model zoo — flax.linen rebuilds of the reference CIFAR zoo
(``src/models/__init__.py:1-18``) plus the BASELINE parity models.

Constructor names mirror the reference exports so users of the reference find
the same surface: ``MobileNet()``, ``ResNet18()``, ``VGG('VGG19')``, ...
"""

from fedtpu.models.registry import available, create, register

from fedtpu.models.mlp import MLP
from fedtpu.models.smallcnn import SmallCNN
from fedtpu.models.lenet import LeNet
from fedtpu.models.mobilenet import MobileNet
from fedtpu.models.resnet import ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from fedtpu.models.vgg import VGG

__all__ = [
    "available",
    "create",
    "register",
    "MLP",
    "SmallCNN",
    "LeNet",
    "MobileNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "VGG",
]
