"""Small CNN — the BASELINE.md config-2/3 model ("small CNN on CIFAR-10").

Two conv+pool stages and a two-layer dense head; no BatchNorm, so it is also
the simplest all-weights FedAvg target.
"""

from __future__ import annotations

import flax.linen as nn

from fedtpu.models.common import max_pool
from fedtpu.models.registry import register


class SmallCNNModule(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(32, (3, 3), padding=1)(x)
        x = nn.relu(x)
        x = max_pool(x, 2)
        x = nn.Conv(64, (3, 3), padding=1)(x)
        x = nn.relu(x)
        x = max_pool(x, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


@register("smallcnn")
def SmallCNN(num_classes: int = 10) -> nn.Module:
    return SmallCNNModule(num_classes=num_classes)
