"""Small CNN — the BASELINE.md config-2/3 model ("small CNN on CIFAR-10").

Two conv+pool stages and a two-layer dense head; no BatchNorm, so it is also
the simplest all-weights FedAvg target.

``smallcnn_avgpool`` is a NON-PARITY perf-ablation variant: identical
parameters (pools are parameter-free), with both max-pools replaced by
average pools. Max-pool's gradient lowers to ``select_and_scatter``, the
largest single op family in the round-4 on-chip traces
(``artifacts/MFU_PROFILE_r04_bf16.json``, ~34% of the fused dispatch) and
the one both custom-VJP rewrites failed to beat (see
``fedtpu.models.common._tiled_max_pool``); avg-pool's gradient is a dense
broadcast with no scatter, so benching this variant bounds what
``select_and_scatter`` actually costs END-TO-END rather than by
trace-share arithmetic.
"""

from __future__ import annotations

import flax.linen as nn

from fedtpu.models.common import avg_pool, max_pool
from fedtpu.models.registry import register


class SmallCNNModule(nn.Module):
    num_classes: int = 10
    pool: str = "max"  # max | avg

    @nn.compact
    def __call__(self, x, train: bool = False):
        pool = max_pool if self.pool == "max" else avg_pool
        x = nn.Conv(32, (3, 3), padding=1)(x)
        x = nn.relu(x)
        x = pool(x, 2)
        x = nn.Conv(64, (3, 3), padding=1)(x)
        x = nn.relu(x)
        x = pool(x, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)


@register("smallcnn")
def SmallCNN(num_classes: int = 10) -> nn.Module:
    return SmallCNNModule(num_classes=num_classes)


@register("smallcnn_avgpool")
def SmallCNNAvgPool(num_classes: int = 10) -> nn.Module:
    return SmallCNNModule(num_classes=num_classes, pool="avg")
