"""MobileNet(v1) for CIFAR — the reference's default architecture
(parity: reference ``src/models/mobilenet.py``; selected at ``src/main.py:69``
and hardcoded into the aggregator at ``src/server.py:158``).

Depthwise-separable blocks: 3x3 depthwise conv + BN + ReLU, then 1x1 pointwise
conv + BN + ReLU. Config (64, (128,2), 128, (256,2), 256, (512,2), 512 x 5,
(1024,2), 1024) after a 3x3/32 stem; global pool + dense head.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import flax.linen as nn

from fedtpu.models.common import (
    batch_norm,
    conv1x1,
    conv3x3,
    global_avg_pool,
    maybe_remat,
)
from fedtpu.models.registry import register

_CFG: Sequence[Union[int, Tuple[int, int]]] = (
    64,
    (128, 2),
    128,
    (256, 2),
    256,
    (512, 2),
    512,
    512,
    512,
    512,
    512,
    (1024, 2),
    1024,
)


class DepthwiseSeparable(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        # Depthwise: one 3x3 filter per input channel.
        x = nn.Conv(
            in_ch,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=in_ch,
            use_bias=False,
        )(x)
        x = batch_norm(train)(x)
        x = nn.relu(x)
        # Pointwise expansion.
        x = conv1x1(self.features)(x)
        x = batch_norm(train)(x)
        return nn.relu(x)


class MobileNetModule(nn.Module):
    num_classes: int = 10
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(32, strides=(1, 1))(x)
        x = batch_norm(train)(x)
        x = nn.relu(x)
        for count, entry in enumerate(_CFG):
            features, stride = (entry, 1) if isinstance(entry, int) else entry
            x = maybe_remat(DepthwiseSeparable, self.remat)(
                features, stride, name=f"DepthwiseSeparable_{count}"
            )(x, train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("mobilenet")
def MobileNet(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return MobileNetModule(num_classes=num_classes, remat=remat)
