"""MobileNetV2 for CIFAR (parity: reference ``src/models/mobilenetv2.py``).

Inverted-residual blocks: 1x1 expand → 3x3 depthwise → 1x1 project (linear),
residual added when stride is 1 (with a projected shortcut if the channel
count changes — the reference's CIFAR variant adds the shortcut whenever
stride == 1, ``src/models/mobilenetv2.py:36-38``). Config per the reference's
CIFAR table (stride of stage 2 and the stem lowered to 1 for 32x32 inputs).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import flax.linen as nn

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register

# (expansion, out_channels, num_blocks, stride)
_CFG: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 1),  # stride 2 -> 1 for CIFAR
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class InvertedResidual(nn.Module):
    features: int
    expansion: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        mid = self.expansion * in_ch
        y = conv1x1(mid)(x)
        y = nn.relu(batch_norm(train)(y))
        y = nn.Conv(
            mid,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=mid,
            use_bias=False,
        )(y)
        y = nn.relu(batch_norm(train)(y))
        y = conv1x1(self.features)(y)
        y = batch_norm(train)(y)
        if self.stride == 1:
            shortcut = x
            if in_ch != self.features:
                shortcut = batch_norm(train)(conv1x1(self.features)(x))
            y = y + shortcut
        return y


class MobileNetV2Module(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(32)(x)
        x = nn.relu(batch_norm(train)(x))
        for expansion, features, n, stride in _CFG:
            for i in range(n):
                x = InvertedResidual(
                    features, expansion, stride if i == 0 else 1
                )(x, train=train)
        x = conv1x1(1280)(x)
        x = nn.relu(batch_norm(train)(x))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("mobilenetv2")
def MobileNetV2(num_classes: int = 10) -> nn.Module:
    return MobileNetV2Module(num_classes=num_classes)
