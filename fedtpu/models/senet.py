"""SENet-18 for CIFAR (parity: reference ``src/models/senet.py``).

Pre-activation basic blocks with squeeze-and-excitation: a global-pooled
1x1→ReLU→1x1→sigmoid gate (reduction 16) rescales the block output before the
residual add. Stage plan (64, 128, 256, 512) x (2, 2, 2, 2), strides
(1, 2, 2, 2) — ``SENet18`` (``src/models/senet.py:112``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import maybe_remat, batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class SEGate(nn.Module):
    """Squeeze-and-excitation: per-channel sigmoid gate from global context."""

    reduction: int = 16

    @nn.compact
    def __call__(self, x):
        ch = x.shape[-1]
        w = jnp.mean(x, axis=(1, 2), keepdims=True)
        w = nn.relu(nn.Conv(ch // self.reduction, (1, 1))(w))
        w = nn.sigmoid(nn.Conv(ch, (1, 1))(w))
        return x * w


class SEPreActBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        pre = nn.relu(batch_norm(train)(x))
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = conv1x1(self.features, strides=(self.stride, self.stride))(pre)
        else:
            shortcut = x
        y = conv3x3(self.features, strides=(self.stride, self.stride))(pre)
        y = nn.relu(batch_norm(train)(y))
        y = conv3x3(self.features)(y)
        y = SEGate()(y)
        return y + shortcut


class SENetModule(nn.Module):
    num_blocks: tuple = (2, 2, 2, 2)
    num_classes: int = 10
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(64)(x)
        x = nn.relu(batch_norm(train)(x))
        count = 0
        for stage, (features, n) in enumerate(
            zip((64, 128, 256, 512), self.num_blocks)
        ):
            for i in range(n):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                x = maybe_remat(SEPreActBlock, self.remat)(
                    features, stride, name=f"SEPreActBlock_{count}"
                )(x, train)
                count += 1
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("senet18")
def SENet18(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return SENetModule((2, 2, 2, 2), num_classes, remat)
