"""Shared building blocks for the CIFAR zoo.

All models take NHWC inputs (TPU-friendly layout: the channel dimension lands
on the 128-wide lane axis) and return ``[batch, num_classes]`` logits. Batch
statistics live in a ``batch_stats`` collection so that, under FedAvg, they are
part of the aggregated state exactly as the reference averages BN running
stats alongside weights (``src/server.py:163-171``).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any

# Conv with PyTorch-style default initialisation is unnecessary; flax defaults
# (lecun_normal) are fine for parity-by-accuracy. Bias-free convs before BN
# mirror the reference blocks (e.g. src/models/mobilenet.py:15-20).
conv3x3 = partial(nn.Conv, kernel_size=(3, 3), use_bias=False, padding=1)
conv1x1 = partial(nn.Conv, kernel_size=(1, 1), use_bias=False, padding=0)


def batch_norm(train: bool) -> nn.Module:
    """BatchNorm matching torch ``nn.BatchNorm2d`` defaults: torch momentum
    0.1 corresponds to flax momentum 0.9 (flax keeps
    ``momentum * old + (1 - momentum) * new``)."""
    return nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)


def maybe_remat(block_cls, remat: bool):
    """Per-block rematerialisation wrapper (the HBM-for-FLOPs trade; see
    ``RoundConfig.remat``). ``static_argnums=(2,)`` marks the ``train`` flag
    static in ``__call__(self, x, train)``. Callers MUST pin the module
    ``name=`` explicitly: ``nn.remat`` renames modules to
    ``Checkpoint<Block>_N``, which would split the init RNG tree differently
    and break checkpoint compatibility with the non-remat form."""
    if not remat:
        return block_cls
    return nn.remat(block_cls, static_argnums=(2,))


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """Mean over the spatial dims of an NHWC tensor."""
    return jnp.mean(x, axis=(1, 2))


def max_pool(x, window: int, stride: int | None = None, padding: str = "VALID"):
    stride = stride or window
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding=padding)


def avg_pool(x, window: int, stride: int | None = None, padding: str = "VALID"):
    stride = stride or window
    return nn.avg_pool(x, (window, window), strides=(stride, stride), padding=padding)
