"""Shared building blocks for the CIFAR zoo.

All models take NHWC inputs (TPU-friendly layout: the channel dimension lands
on the 128-wide lane axis) and return ``[batch, num_classes]`` logits. Batch
statistics live in a ``batch_stats`` collection so that, under FedAvg, they are
part of the aggregated state exactly as the reference averages BN running
stats alongside weights (``src/server.py:163-171``).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any

# Conv with PyTorch-style default initialisation is unnecessary; flax defaults
# (lecun_normal) are fine for parity-by-accuracy. Bias-free convs before BN
# mirror the reference blocks (e.g. src/models/mobilenet.py:15-20).
conv3x3 = partial(nn.Conv, kernel_size=(3, 3), use_bias=False, padding=1)
conv1x1 = partial(nn.Conv, kernel_size=(1, 1), use_bias=False, padding=0)


class BatchNorm(nn.BatchNorm):
    """``nn.BatchNorm`` with compute-dtype-safe normalization.

    flax's ``nn.BatchNorm`` upcasts the WHOLE activation to f32 for the
    statistics reduction and keeps every activation-sized elementwise op
    (``x - mean``, ``y * mul``, ``y + bias``) in f32, casting only the
    final output back — under ``compute_dtype=bfloat16_mixed`` that made
    BN intermediates ~73% of the analytic per-round bytes on the BN-dense
    zoo (DenseNet/ResNet), erasing the residency lever this knob exists
    for. Here the statistics stay in f32 (stability; running stats remain
    f32 exactly as flax keeps them) but the feature-sized ``mean``/``mul``
    are cast to ``x.dtype`` BEFORE the activation-sized math, so the
    normalize runs in the compute dtype. For f32 inputs every cast is a
    no-op and the op sequence matches flax's fast-variance path exactly —
    bit-identical, pinned by tests/test_mixed_precision.py. The subclass
    keeps the class name so flax auto-naming (``BatchNorm_N``) and hence
    param/batch_stats trees and checkpoints are unchanged.

    Supports only the configuration :func:`batch_norm` constructs (no
    ``axis_name``/``mask``/custom ``axis``/``dtype`` — asserted below).
    """

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        assert (
            self.axis == -1 and self.axis_name is None and self.dtype is None
            and self.use_bias and self.use_scale and self.use_fast_variance
        ), "compute-dtype-safe BatchNorm supports batch_norm() defaults only"
        use_running_average = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        feature_shape = (x.shape[-1],)
        reduction_axes = tuple(range(x.ndim - 1))
        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32),
            feature_shape,
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32),
            feature_shape,
        )
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            # f32 statistics, exactly flax's fast-variance formulation.
            xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
            mean = xf.mean(reduction_axes)
            mean2 = jax.lax.square(xf).mean(reduction_axes)
            var = jnp.maximum(0.0, mean2 - jax.lax.square(mean))
            if not self.is_initializing():
                ra_mean.value = (
                    self.momentum * ra_mean.value + (1 - self.momentum) * mean
                )
                ra_var.value = (
                    self.momentum * ra_var.value + (1 - self.momentum) * var
                )
        scale = self.param(
            "scale", self.scale_init, feature_shape, self.param_dtype
        )
        bias = self.param(
            "bias", self.bias_init, feature_shape, self.param_dtype
        )
        y = x - mean.astype(x.dtype)
        mul = jax.lax.rsqrt(var + self.epsilon) * scale
        y = y * mul.astype(x.dtype)
        return y + bias.astype(x.dtype)


def batch_norm(train: bool) -> nn.Module:
    """BatchNorm matching torch ``nn.BatchNorm2d`` defaults: torch momentum
    0.1 corresponds to flax momentum 0.9 (flax keeps
    ``momentum * old + (1 - momentum) * new``)."""
    return BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)


def maybe_remat(block_cls, remat: bool):
    """Per-block rematerialisation wrapper (the HBM-for-FLOPs trade; see
    ``RoundConfig.remat``). ``static_argnums=(2,)`` marks the ``train`` flag
    static in ``__call__(self, x, train)``. Callers MUST pin the module
    ``name=`` explicitly: ``nn.remat`` renames modules to
    ``Checkpoint<Block>_N``, which would split the init RNG tree differently
    and break checkpoint compatibility with the non-remat form."""
    if not remat:
        return block_cls
    return nn.remat(block_cls, static_argnums=(2,))


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """Mean over the spatial dims of an NHWC tensor."""
    return jnp.mean(x, axis=(1, 2))


def max_pool(x, window: int, stride: int | None = None, padding: str = "VALID"):
    stride = stride or window
    if (
        os.environ.get("FEDTPU_TILED_POOL", "0") == "1"
        and stride == window
        and padding == "VALID"
        and x.ndim == 4
        and x.shape[1] % window == 0
        and x.shape[2] % window == 0
    ):
        return _tiled_max_pool(x, window)
    return nn.max_pool(x, (window, window), strides=(stride, stride), padding=padding)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tiled_max_pool(x, k: int):
    """Non-overlapping NHWC max-pool as transpose-free two-stage reshape+max.

    ``nn.max_pool``'s gradient lowers to ``select_and_scatter``, which the
    round-4 on-chip traces measured as the single largest op family in the
    fused round dispatch (~34% at the bf16 bench config,
    ``artifacts/MFU_PROFILE_r04_bf16.json``). OPT-IN via
    ``FEDTPU_TILED_POOL=1`` and kept as a twice-measured NEGATIVE result:
    despite that trace line, both reformulations LOST end-to-end on the
    real chip (``moveaxis``-flattened windows: 399 vs 598
    client-epochs/s/chip; this transpose-free two-stage version: 380 vs
    598) — the custom VJP is opaque to XLA's fusion and its argmax
    residuals add HBM traffic that ``select_and_scatter``, for all its op
    time, does not pay. Here the windowed view ``[N, H/k, k, W/k, k, C]``
    is a FREE reshape (row-major compatible); forward is
    ``max`` over the two window axes in turn, and the custom VJP routes the
    cotangent with one-hot ``argmax`` masks per stage. Two-stage first-max
    composes to FIRST max in row-major window order — the row holding the
    window max is the first row whose row-max equals it — matching both
    ``select_and_scatter`` and torch's ``MaxPool2d`` at ties (common right
    after ReLU), so forward AND backward are bit-identical to the
    ``nn.max_pool`` formulation.
    """
    n, h, w, c = x.shape
    return x.reshape(n, h // k, k, w // k, k, c).max(axis=(2, 4))


def _tiled_max_pool_fwd(x, k: int):
    n, h, w, c = x.shape
    xw = x.reshape(n, h // k, k, w // k, k, c)
    rowmax = xw.max(axis=4)                      # [n, h/k, k, w/k, c]
    colidx = jnp.argmax(xw, axis=4)
    rowidx = jnp.argmax(rowmax, axis=2)          # [n, h/k, w/k, c]
    return rowmax.max(axis=2), (rowidx, colidx, x.shape)


def _tiled_max_pool_bwd(k: int, res, g):
    rowidx, colidx, (n, h, w, c) = res
    win = jnp.arange(k, dtype=rowidx.dtype)
    zero = jnp.zeros((), g.dtype)
    # Stage 1: route g to the selected row of each window.
    rmask = win[None, None, :, None, None] == rowidx[:, :, None, :, :]
    g_row = jnp.where(rmask, g[:, :, None, :, :], zero)  # [n,h/k,k,w/k,c]
    # Stage 2: route each row's share to its selected column.
    cmask = win[None, None, None, None, :, None] == colidx[:, :, :, :, None, :]
    g_xw = jnp.where(cmask, g_row[:, :, :, :, None, :], zero)
    return (g_xw.reshape(n, h, w, c),)


_tiled_max_pool.defvjp(_tiled_max_pool_fwd, _tiled_max_pool_bwd)


def avg_pool(x, window: int, stride: int | None = None, padding: str = "VALID"):
    stride = stride or window
    return nn.avg_pool(x, (window, window), strides=(stride, stride), padding=padding)
