"""VGG for CIFAR (parity: reference ``src/models/vgg.py``).

Conv3x3+BN+ReLU stacks per the VGG11/13/16/19 configs with 2x2 max-pools,
then a single dense head (the CIFAR variant has no 4096-wide FC layers).
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn

from fedtpu.models.common import batch_norm, max_pool
from fedtpu.models.registry import register

_CFGS = {
    "VGG11": (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "VGG13": (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    "VGG16": (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M"),
    "VGG19": (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGGModule(nn.Module):
    cfg: Sequence[Union[int, str]]
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        for entry in self.cfg:
            if entry == "M":
                x = max_pool(x, 2)
            else:
                # Biased convs, matching the reference's default Conv2d (the
                # bias is redundant before BN but kept for exact param parity).
                x = nn.Conv(entry, (3, 3), padding=1)(x)
                x = batch_norm(train)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(self.num_classes)(x)


def VGG(name: str = "VGG19", num_classes: int = 10) -> nn.Module:
    return VGGModule(cfg=_CFGS[name], num_classes=num_classes)


for _name in _CFGS:
    register(_name)(
        lambda num_classes=10, _n=_name: VGG(_n, num_classes=num_classes)
    )
register("vgg")(lambda num_classes=10: VGG("VGG19", num_classes=num_classes))
