"""LeNet-5 for 32x32 inputs (parity: reference ``src/models/lenet.py``).

Two 5x5 valid convs with 2x2 max-pools, then 120/84/num_classes dense head.
"""

from __future__ import annotations

import flax.linen as nn

from fedtpu.models.common import max_pool
from fedtpu.models.registry import register


class LeNetModule(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(6, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = max_pool(x, 2)
        x = nn.Conv(16, (5, 5), padding="VALID")(x)
        x = nn.relu(x)
        x = max_pool(x, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)


@register("lenet")
def LeNet(num_classes: int = 10) -> nn.Module:
    return LeNetModule(num_classes=num_classes)
