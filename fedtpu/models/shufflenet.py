"""ShuffleNet(v1) for CIFAR (parity: reference ``src/models/shufflenet.py``).

Grouped 1x1 → channel shuffle → 3x3 depthwise → grouped 1x1 bottlenecks; the
first block of each stage strides 2 and *concatenates* an avg-pooled shortcut
(so its conv path emits ``out - in`` channels), later blocks add the identity.
The first stage's entry 1x1 is ungrouped (stem has only 24 channels).
Constructors match the reference: ShuffleNetG2, ShuffleNetG3
(``src/models/shufflenet.py:86-101``).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import avg_pool, batch_norm, conv1x1, global_avg_pool
from fedtpu.models.registry import register


def channel_shuffle(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    """Interleave channels across groups: NHWC [..., g, C/g] -> [..., C/g, g]."""
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, -1, -2)
    return x.reshape(n, h, w, c)


def _grouped_conv1x1(features, groups):
    return nn.Conv(
        features, (1, 1), padding=0, feature_group_count=groups, use_bias=False
    )


class ShuffleBottleneck(nn.Module):
    out_planes: int  # channels added by the conv path
    stride: int
    groups: int
    first_groups: int  # 1 for the stem-fed block, else == groups

    @nn.compact
    def __call__(self, x, train: bool = False):
        mid = self.out_planes // 4
        y = _grouped_conv1x1(mid, self.first_groups)(x)
        y = nn.relu(batch_norm(train)(y))
        y = channel_shuffle(y, self.first_groups)
        y = nn.Conv(
            mid,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            feature_group_count=mid,
            use_bias=False,
        )(y)
        y = nn.relu(batch_norm(train)(y))
        y = _grouped_conv1x1(self.out_planes, self.groups)(y)
        y = batch_norm(train)(y)
        if self.stride == 2:
            shortcut = avg_pool(x, 3, 2, padding=((1, 1), (1, 1)))
            return nn.relu(jnp.concatenate([y, shortcut], axis=-1))
        return nn.relu(y + x)


class ShuffleNetModule(nn.Module):
    out_planes: Sequence[int]
    num_blocks: Sequence[int]
    groups: int
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv1x1(24)(x)
        x = nn.relu(batch_norm(train)(x))
        in_planes = 24
        for stage, (out, n) in enumerate(zip(self.out_planes, self.num_blocks)):
            for i in range(n):
                stride = 2 if i == 0 else 1
                cat_planes = in_planes if i == 0 else 0
                x = ShuffleBottleneck(
                    out - cat_planes,
                    stride=stride,
                    groups=self.groups,
                    first_groups=1 if in_planes == 24 else self.groups,
                )(x, train=train)
                in_planes = out
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("shufflenetg2")
def ShuffleNetG2(num_classes: int = 10) -> nn.Module:
    return ShuffleNetModule((200, 400, 800), (4, 8, 4), 2, num_classes)


@register("shufflenetg3")
def ShuffleNetG3(num_classes: int = 10) -> nn.Module:
    return ShuffleNetModule((240, 480, 960), (4, 8, 4), 3, num_classes)
