"""DLA for CIFAR (parity: reference ``src/models/dla.py``).

Deep-layer-aggregation trees of residual BasicBlocks. A level-1 tree is
(left block, right block) joined by a Root (concat → 1x1 conv+BN+ReLU); a
level-k tree chains a ``prev_root`` block and k-1 subtrees, feeding every
intermediate into one wide Root — matching the reference's flat-root variant.
Stages: three conv stems (16, 16, 32) then trees at (64, l1), (128, l2),
(256, l2), (512, l1) with strides (1, 2, 2, 2).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class BasicBlock(nn.Module):
    features: int
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = conv3x3(self.features, strides=(self.stride, self.stride))(x)
        y = nn.relu(batch_norm(train)(y))
        y = conv3x3(self.features)(y)
        y = batch_norm(train)(y)
        if self.stride != 1 or x.shape[-1] != self.features:
            shortcut = conv1x1(self.features, strides=(self.stride, self.stride))(x)
            shortcut = batch_norm(train)(shortcut)
        else:
            shortcut = x
        return nn.relu(y + shortcut)


class Root(nn.Module):
    features: int

    @nn.compact
    def __call__(self, xs, train: bool = False):
        x = jnp.concatenate(xs, axis=-1)
        x = conv1x1(self.features)(x)
        return nn.relu(batch_norm(train)(x))


class Tree(nn.Module):
    features: int
    level: int = 1
    stride: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        xs = []
        if self.level > 1:
            xs.append(BasicBlock(self.features, self.stride)(x, train=train))
            for lvl in reversed(range(1, self.level)):
                x = Tree(self.features, level=lvl, stride=self.stride)(
                    x, train=train
                )
                xs.append(x)
            x = BasicBlock(self.features, 1)(x, train=train)
        else:
            x = BasicBlock(self.features, self.stride)(x, train=train)
        xs.append(x)
        x = BasicBlock(self.features, 1)(x, train=train)
        xs.append(x)
        return Root(self.features)(xs, train=train)


class DLAModule(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        for features in (16, 16, 32):
            x = conv3x3(features)(x)
            x = nn.relu(batch_norm(train)(x))
        x = Tree(64, level=1, stride=1)(x, train=train)
        x = Tree(128, level=2, stride=2)(x, train=train)
        x = Tree(256, level=2, stride=2)(x, train=train)
        x = Tree(512, level=1, stride=2)(x, train=train)
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("dla")
def DLA(num_classes: int = 10) -> nn.Module:
    return DLAModule(num_classes=num_classes)
