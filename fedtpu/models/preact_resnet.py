"""Pre-activation ResNet family (parity: reference ``src/models/preact_resnet.py``).

BN→ReLU→conv ordering (He et al., identity mappings); the shortcut taps the
*pre-activated* input when projecting. Same stage plan as ResNet: widths
(64, 128, 256, 512), strides (1, 2, 2, 2), 3x3/64 stem, global pool + head.
Constructors match the reference exports PreActResNet18/34/50/101/152
(``src/models/preact_resnet.py:97-110``).
"""

from __future__ import annotations

from typing import Sequence, Type

import flax.linen as nn

from fedtpu.models.common import maybe_remat, batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class PreActBlock(nn.Module):
    features: int
    stride: int = 1
    expansion: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.features * self.expansion
        pre = nn.relu(batch_norm(train)(x))
        if self.stride != 1 or x.shape[-1] != out_ch:
            shortcut = conv1x1(out_ch, strides=(self.stride, self.stride))(pre)
        else:
            shortcut = x
        y = conv3x3(self.features, strides=(self.stride, self.stride))(pre)
        y = nn.relu(batch_norm(train)(y))
        y = conv3x3(self.features)(y)
        return y + shortcut


class PreActBottleneck(nn.Module):
    features: int
    stride: int = 1
    expansion: int = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = self.features * self.expansion
        pre = nn.relu(batch_norm(train)(x))
        if self.stride != 1 or x.shape[-1] != out_ch:
            shortcut = conv1x1(out_ch, strides=(self.stride, self.stride))(pre)
        else:
            shortcut = x
        y = conv1x1(self.features)(pre)
        y = nn.relu(batch_norm(train)(y))
        y = conv3x3(self.features, strides=(self.stride, self.stride))(y)
        y = nn.relu(batch_norm(train)(y))
        y = conv1x1(out_ch)(y)
        return y + shortcut


class PreActResNetModule(nn.Module):
    block: Type[nn.Module]
    num_blocks: Sequence[int]
    num_classes: int = 10
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(64)(x)
        count = 0
        for stage, (features, n) in enumerate(
            zip((64, 128, 256, 512), self.num_blocks)
        ):
            for i in range(n):
                stride = (1 if stage == 0 else 2) if i == 0 else 1
                x = maybe_remat(self.block, self.remat)(
                    features=features,
                    stride=stride,
                    name=f"{self.block.__name__}_{count}",
                )(x, train)
                count += 1
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("preactresnet18")
def PreActResNet18(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return PreActResNetModule(PreActBlock, (2, 2, 2, 2), num_classes, remat)


@register("preactresnet34")
def PreActResNet34(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return PreActResNetModule(PreActBlock, (3, 4, 6, 3), num_classes, remat)


@register("preactresnet50")
def PreActResNet50(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return PreActResNetModule(PreActBottleneck, (3, 4, 6, 3), num_classes, remat)


@register("preactresnet101")
def PreActResNet101(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return PreActResNetModule(PreActBottleneck, (3, 4, 23, 3), num_classes, remat)


@register("preactresnet152")
def PreActResNet152(num_classes: int = 10, remat: bool = False) -> nn.Module:
    return PreActResNetModule(PreActBottleneck, (3, 8, 36, 3), num_classes, remat)
