"""2-layer MLP — the BASELINE.md config-1 model (MNIST).

Not in the reference zoo (which is CNN-only); included because the driver's
parity config 1 is "FedAvg 2-layer MLP on MNIST, 2 clients, IID split".
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.registry import register


class MLPModule(nn.Module):
    num_classes: int = 10
    hidden: int = 256

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes)(x)
        return x


@register("mlp")
def MLP(num_classes: int = 10, hidden: int = 256) -> nn.Module:
    return MLPModule(num_classes=num_classes, hidden=hidden)


@register("mlp_tiny")
def MLPTiny(num_classes: int = 10, hidden: int = 32) -> nn.Module:
    """Deliberately small MLP for population-scale simulation benches: at
    10k vmapped clients the per-seat state (momentum + param copies +
    deltas) of even the 256-hidden MLP is tens of GB; this keeps a
    10k-cohort round inside one host (bench.py --cohort-scale)."""
    return MLPModule(num_classes=num_classes, hidden=hidden)
