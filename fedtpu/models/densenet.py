"""DenseNet family for CIFAR (parity: reference ``src/models/densenet.py``).

Dense bottleneck layers (BN→ReLU→1x1(4k)→BN→ReLU→3x3(k)) whose outputs are
concatenated with their input; transition layers (BN→ReLU→1x1 halve → 2x2
avg-pool) between the four dense stages. Constructors match the reference:
DenseNet121/169/201/161 and ``densenet_cifar``
(``src/models/densenet.py:86-99``).
"""

from __future__ import annotations

import math
from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from fedtpu.models.common import avg_pool, batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


class DenseLayer(nn.Module):
    growth_rate: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = nn.relu(batch_norm(train)(x))
        y = conv1x1(4 * self.growth_rate)(y)
        y = nn.relu(batch_norm(train)(y))
        y = conv3x3(self.growth_rate)(y)
        return jnp.concatenate([y, x], axis=-1)


class Transition(nn.Module):
    features: int

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.relu(batch_norm(train)(x))
        x = conv1x1(self.features)(x)
        return avg_pool(x, 2)


class DenseNetModule(nn.Module):
    num_blocks: Sequence[int]
    growth_rate: int = 12
    reduction: float = 0.5
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        k = self.growth_rate
        planes = 2 * k
        x = conv3x3(planes)(x)
        for stage, n in enumerate(self.num_blocks):
            for _ in range(n):
                x = DenseLayer(k)(x, train=train)
            planes += n * k
            if stage < len(self.num_blocks) - 1:
                planes = int(math.floor(planes * self.reduction))
                x = Transition(planes)(x, train=train)
        x = nn.relu(batch_norm(train)(x))
        x = global_avg_pool(x)
        return nn.Dense(self.num_classes)(x)


@register("densenet121")
def DenseNet121(num_classes: int = 10) -> nn.Module:
    return DenseNetModule((6, 12, 24, 16), growth_rate=32, num_classes=num_classes)


@register("densenet169")
def DenseNet169(num_classes: int = 10) -> nn.Module:
    return DenseNetModule((6, 12, 32, 32), growth_rate=32, num_classes=num_classes)


@register("densenet201")
def DenseNet201(num_classes: int = 10) -> nn.Module:
    return DenseNetModule((6, 12, 48, 32), growth_rate=32, num_classes=num_classes)


@register("densenet161")
def DenseNet161(num_classes: int = 10) -> nn.Module:
    return DenseNetModule((6, 12, 36, 24), growth_rate=48, num_classes=num_classes)


@register("densenet_cifar")
def densenet_cifar(num_classes: int = 10) -> nn.Module:
    return DenseNetModule((6, 12, 24, 16), growth_rate=12, num_classes=num_classes)
