"""Model registry.

The reference exposes its zoo through star-imports of constructor functions
(``src/models/__init__.py:1-18``) and hardcodes the active architecture in two
places (``src/main.py:69``, ``src/server.py:158``). fedtpu keeps the same
constructor-style surface (``MobileNet()``, ``ResNet18()``, ``VGG('VGG19')``)
but backs it with a string registry so the architecture is a config value, not
an edit.
"""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn

_REGISTRY: Dict[str, Callable[..., nn.Module]] = {}


def register(name: str):
    def deco(ctor: Callable[..., nn.Module]):
        _REGISTRY[name.lower()] = ctor
        return ctor

    return deco


def create(name: str, num_classes: int = 10, **kwargs) -> nn.Module:
    """Build a model by registry name (case-insensitive).

    Accepts both plain names (``"mobilenet"``) and the reference's constructor
    spellings (``"MobileNet"``, ``"ResNet18"``, ``"VGG19"``).
    """
    key = name.lower()
    if key not in _REGISTRY:
        raise KeyError(
            f"unknown model '{name}'; available: {sorted(_REGISTRY)}"
        )
    ctor = _REGISTRY[key]
    if "remat" in kwargs:
        import inspect

        if "remat" not in inspect.signature(ctor).parameters:
            if kwargs["remat"]:
                raise ValueError(
                    f"model '{name}' does not support remat; models that do: "
                    + str([
                        n for n, c in sorted(_REGISTRY.items())
                        if "remat" in inspect.signature(c).parameters
                    ])
                )
            kwargs.pop("remat")  # remat=False is a no-op everywhere
    return ctor(num_classes=num_classes, **kwargs)


def available() -> list[str]:
    return sorted(_REGISTRY)
