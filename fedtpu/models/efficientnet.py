"""EfficientNet-B0 for CIFAR (parity: reference ``src/models/efficientnet.py``).

MBConv blocks: 1x1 expand (skipped at expansion 1) → k x k depthwise →
squeeze-excitation (ratio 0.25 of *input* channels, swish inside) → 1x1 linear
project, swish activations, identity skip with stochastic depth (drop-connect
rate ramping linearly over block index). B0 config per the reference table
(``src/models/efficientnet.py:154-163``); CIFAR stem is 3x3/32 stride 1.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from fedtpu.models.common import batch_norm, conv1x1, conv3x3, global_avg_pool
from fedtpu.models.registry import register


def swish(x):
    return x * nn.sigmoid(x)


class MBConv(nn.Module):
    features: int
    kernel_size: int
    stride: int
    expand_ratio: int
    se_ratio: float = 0.25
    drop_rate: float = 0.0

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        mid = self.expand_ratio * in_ch
        y = x
        if self.expand_ratio != 1:
            y = conv1x1(mid)(y)
            y = swish(batch_norm(train)(y))
        k = self.kernel_size
        y = nn.Conv(
            mid,
            (k, k),
            strides=(self.stride, self.stride),
            padding=(k - 1) // 2,
            feature_group_count=mid,
            use_bias=False,
        )(y)
        y = swish(batch_norm(train)(y))
        # Squeeze-excitation (biased 1x1 convs, swish then sigmoid).
        se_ch = int(in_ch * self.se_ratio)
        w = jnp.mean(y, axis=(1, 2), keepdims=True)
        w = swish(nn.Conv(se_ch, (1, 1))(w))
        w = nn.sigmoid(nn.Conv(mid, (1, 1))(w))
        y = y * w
        y = conv1x1(self.features)(y)
        y = batch_norm(train)(y)
        if self.stride == 1 and in_ch == self.features:
            if train and self.drop_rate > 0:
                # Drop-connect (stochastic depth): zero whole samples' residual
                # branch, rescaled to keep the expectation.
                keep = 1.0 - self.drop_rate
                rng = self.make_rng("dropout")
                mask = jax.random.bernoulli(rng, keep, (y.shape[0], 1, 1, 1))
                y = jnp.where(mask, y / keep, 0.0)
            y = y + x
        return y


class EfficientNetModule(nn.Module):
    num_blocks: Sequence[int]
    expansion: Sequence[int]
    out_channels: Sequence[int]
    kernel_size: Sequence[int]
    stride: Sequence[int]
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = conv3x3(32)(x)
        x = swish(batch_norm(train)(x))
        b, total = 0, sum(self.num_blocks)
        for e, out, n, k, s in zip(
            self.expansion,
            self.out_channels,
            self.num_blocks,
            self.kernel_size,
            self.stride,
        ):
            for i in range(n):
                x = MBConv(
                    out,
                    k,
                    s if i == 0 else 1,
                    e,
                    drop_rate=self.drop_connect_rate * b / total,
                )(x, train=train)
                b += 1
        x = global_avg_pool(x)
        if self.dropout_rate > 0:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes)(x)


@register("efficientnetb0")
def EfficientNetB0(num_classes: int = 10) -> nn.Module:
    return EfficientNetModule(
        num_blocks=(1, 2, 2, 3, 3, 4, 1),
        expansion=(1, 6, 6, 6, 6, 6, 6),
        out_channels=(16, 24, 40, 80, 112, 192, 320),
        kernel_size=(3, 3, 5, 3, 5, 5, 3),
        stride=(1, 2, 2, 2, 1, 2, 1),
        num_classes=num_classes,
    )
