"""Process entry points: ``fedtpu.cli.run`` (TPU-native simulated
federation), ``fedtpu.cli.server`` (primary/backup over gRPC),
``fedtpu.cli.client`` (client agent) — the L5 surface of SURVEY §1."""
