"""``python -m fedtpu.cli.server`` — primary or backup federated server.

Parity with ``python3 server.py`` (``src/server.py:268-301``): ``--p y``
starts the primary round loop against the client registry; without it the
process is the backup (watchdog + promotion). The reference hardcodes the
registry (``src/server.py:281-282``); here it's ``--clients``. Adds what the
reference lacked: checkpoint/resume of the global model every round.
"""

from __future__ import annotations

import argparse
import logging
import time

from fedtpu.cli.common import (
    add_checkpoint_hardening_flags,
    add_fed_flags,
    add_model_flags,
    add_obs_flags,
    add_platform_flag,
    add_profile_flags,
    add_robustness_flags,
    add_telemetry_export_flags,
    apply_platform_flag,
    build_config,
    compress_enabled,
    install_compile_watcher,
    install_final_flush,
    make_capture_window,
    make_chaos,
    make_checkpointer,
    make_flight_recorder,
    start_obs_server,
)
from fedtpu.transport.federation import BackupServer, PrimaryServer


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_fed_flags(p)
    p.add_argument("--p", default="N", help="y = run as primary")
    p.add_argument(
        "--role", default="auto",
        choices=["auto", "primary", "backup", "aggregator"],
        help="coordinator role. auto (default) keeps the legacy --p "
        "switch: y = primary, else backup. aggregator = a mid-tier leaf "
        "of the hierarchical topology (docs/ARCHITECTURE.md §Multi-tier): "
        "serves SubmitPartial/SendModel on --listen for the root named by "
        "--parent, fans StartTrain out to its --clients cohort, and "
        "forwards one pre-weighted partial sum per round upstream "
        "(requires --tier-fanout on BOTH tiers)",
    )
    p.add_argument(
        "--parent", default=None, metavar="HOST:PORT",
        help="aggregator role: the root's membership gate to announce "
        "this aggregator's --listen address to (omit when the root lists "
        "us statically in its --clients)",
    )
    p.add_argument("--backupAddress", default="localhost")
    p.add_argument("--backupPort", default="50060")
    p.add_argument("--listen", default="localhost:50060",
                   help="bind address (backup and aggregator roles)")
    p.add_argument(
        "--clients",
        default="localhost:50051,localhost:50052",
        help="comma-separated client registry (reference default)",
    )
    p.add_argument("--checkpoint-dir", default=None)
    add_checkpoint_hardening_flags(p)
    p.add_argument(
        "--gate", default=None, metavar="HOST:PORT",
        help="host the membership gate on this address (primary role): a "
        "gRPC listener answering Join/Leave, so clients can enter and "
        "exit the federation at runtime instead of being frozen into "
        "--clients at startup (docs/FAULT_TOLERANCE.md). Joiners are "
        "admitted into the versioned MembershipTable, resynced with the "
        "current global model, and sampled into rounds from then on; the "
        "roster replicates to the backup every round",
    )
    p.add_argument(
        "--metrics", default=None,
        help="JSONL metrics path: one schema-versioned round record "
        "(fedtpu.obs.RoundRecordWriter) per round — participants, wire "
        "bytes, and the collect/decode/H2D/aggregate phase timing the "
        "streaming pipeline reports (see --server-pipeline; summarize "
        "with tools/metrics_report.py)",
    )
    add_telemetry_export_flags(p)
    add_obs_flags(p)
    add_profile_flags(p)
    add_robustness_flags(p)
    p.add_argument("-r", "--resume", action="store_true",
                   help="resume the global model from the latest checkpoint")
    p.add_argument(
        "--watchdog-timeout", default=None, type=float,
        help="backup promotion watchdog window (seconds; default "
        "FedConfig.ft_watchdog_timeout_s = 10.0)",
    )
    p.add_argument(
        "--async-updates",
        default=0,
        type=int,
        metavar="N",
        help="run the FedBuff semi-asynchronous mode for N server updates "
        "instead of synchronous rounds: clients train continuously, the "
        "server aggregates every --buffer-k replies with staleness-"
        "discounted weights (the reference has no async mode)",
    )
    p.add_argument("--buffer-k", default=2, type=int)
    p.add_argument("--staleness-power", default=0.5, type=float)
    p.add_argument(
        "--staleness-damping", default="on", choices=["on", "off"],
        help="on (default): the staleness discount scales the applied "
        "update's magnitude (FedBuff-paper semantics); off: "
        "weight-normalized mean",
    )
    p.add_argument(
        "--round-deadline",
        default=None,
        type=float,
        metavar="SECONDS",
        help="straggler mitigation: aggregate whatever StartTrain replies "
        "arrived within this budget instead of blocking on the slowest "
        "client (stragglers stay alive and rejoin next round). Default: "
        "wait indefinitely (reference behavior, src/server.py:132-135)",
    )
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    clients = [c.strip() for c in args.clients.split(",") if c.strip()]
    cfg = build_config(args, num_clients=len(clients))
    compress = compress_enabled(args)
    role = args.role
    if role == "auto":
        role = "primary" if str(args.p).lower() == "y" else "backup"

    if role == "aggregator":
        from fedtpu.transport.aggregator import serve_aggregator

        flight = make_flight_recorder("aggregator")
        server, agg = serve_aggregator(
            args.listen,
            cfg,
            clients=clients,
            parent=args.parent,
            compress=compress,
            chaos=make_chaos(args, role="aggregator"),
        )
        agg.flight = flight
        obs = start_obs_server(
            args,
            registry=agg.telemetry.registry,
            status_fn=agg.status_snapshot,
            flight=flight,
        )
        flush = install_final_flush(args, agg.telemetry)
        logging.info(
            "aggregator serving on %s (cohort=%d, parent=%s)",
            args.listen, agg.cohort_size, args.parent or "static",
        )
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            flush()
            agg.stop()
            if obs is not None:
                obs.stop()
            server.stop(0)
        return 0

    if role == "primary":
        # Process-wide black box: armed before anything can fail, handed to
        # the server so spans/rounds/FT events feed the same ring.
        flight = make_flight_recorder("primary")
        chaos = make_chaos(args, role="primary")
        primary = PrimaryServer(
            cfg,
            clients,
            backup_address=f"{args.backupAddress}:{args.backupPort}",
            compress=compress,
            round_deadline_s=args.round_deadline,
            flight=flight,
            chaos=chaos,
        )
        # One hardened checkpoint store (fsync + manifests + generation
        # fallback; background writer unless --checkpoint-sync), sharing
        # the primary's metrics registry, flight recorder and chaos
        # schedule — the disk is part of the same failure domain.
        ckpt = make_checkpointer(
            args, telemetry=primary.telemetry, flight=flight, chaos=chaos,
        )
        start_round = 0
        if ckpt is not None and args.resume:
            # Cold-start recovery: full server state (model + lineage
            # counter + membership roster incl. reputation + FedOpt
            # moments) from the newest VERIFIED generation, falling back
            # past torn/bit-rotten ones; pre-membership and legacy
            # model-only checkpoints restore through the template ladder.
            start_round = primary.restore_from_checkpoint(ckpt) or 0
            if start_round:
                logging.info(
                    "resumed global model from round %d", start_round - 1
                )
        from fedtpu.obs import RoundRecordWriter

        metrics = RoundRecordWriter(path=args.metrics) if args.metrics else None
        # Performance observatory: compile counting on /statusz (the server
        # jits decode/aggregate/screening programs too) + the
        # --profile-rounds device-trace window, driven from on_round below.
        compile_w = install_compile_watcher(
            telemetry=primary.telemetry, flight=flight
        )
        if compile_w is not None:
            primary.compile_watcher = compile_w
        capture = make_capture_window(
            args, role="primary", telemetry=primary.telemetry
        )
        if capture is not None:
            capture.maybe_start(0)
        # Exit-time exporters must survive SIGTERM, not just clean exits;
        # the same idempotent flush also serves the finally below.
        flush = install_final_flush(args, primary.telemetry, metrics=metrics)
        obs = start_obs_server(
            args,
            registry=primary.telemetry.registry,
            status_fn=primary.status_snapshot,
            flight=flight,
            health_fn=primary.health,
        )
        if args.gate:
            primary.start_gate(args.gate)

        def on_round(r: int, rec: dict) -> None:
            if capture is not None:
                # on_round fires AFTER round r: close the window once it is
                # past, (re)arm it for the round about to start.
                capture.maybe_stop(r + 1)
                capture.maybe_start(r + 1)
            if compile_w is not None and not compile_w.steady and r >= 1:
                # Round 0 compiles decode/aggregate (and screening, which
                # jits on its first armed round); by the end of round 1 the
                # steady set has run — later compiles are perf bugs.
                compile_w.mark_steady()
            if metrics is not None:
                metrics.log(start_round + r, **rec)
            # No checkpoint on a sub-quorum abort: the state is unchanged
            # by construction, and the save would just churn the dir.
            if ckpt is not None and not rec.get("aborted"):
                ckpt.save(start_round + r, primary.state_tree())

        # run() (not a bare round() loop) so the heartbeat recovery thread
        # and the backup liveness pinger actually run in the CLI deployment.
        try:
            if args.async_updates:
                primary.run_async(
                    num_updates=args.async_updates,
                    buffer_k=args.buffer_k,
                    staleness_power=args.staleness_power,
                    staleness_damping=args.staleness_damping == "on",
                    on_update=on_round,
                )
            else:
                primary.run(
                    num_rounds=cfg.fed.num_rounds - start_round,
                    on_round=on_round,
                )
        finally:
            if capture is not None:
                capture.stop()  # idempotent: flush a tail-spanning window
            if compile_w is not None:
                compile_w.uninstall()  # listeners are process-global
            if ckpt is not None:
                # Drain the background writer FIRST: the final generation
                # must be durable before the process reports done.
                ckpt.close()
            flush()
            primary.stop_gate()
            if obs is not None:
                obs.stop()
        return 0

    flight = make_flight_recorder("backup")
    backup = BackupServer(
        cfg, clients, compress=compress,
        watchdog_timeout=args.watchdog_timeout,
        round_deadline_s=args.round_deadline,
        flight=flight,
        chaos=make_chaos(args, role="backup"),
    )
    server = backup.start(args.listen)
    obs = start_obs_server(
        args,
        registry=backup.telemetry.registry,
        status_fn=backup.status_snapshot,
        flight=flight,
        health_fn=backup.health,
    )
    logging.info("backup serving on %s", args.listen)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        backup.watchdog.stop()
        if obs is not None:
            obs.stop()
        server.stop(0)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
