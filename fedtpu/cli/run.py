"""``python -m fedtpu.cli.run`` — TPU-native simulated federation.

The deployment mode the reference cannot do: all clients as one array axis in
a single jitted program on the device mesh (SURVEY §7 design stance). This is
the path that hits the rounds/sec north star; the gRPC server/client CLIs
exist for the reference's multi-process edge topology.
"""

from __future__ import annotations

import argparse
import logging
import time

import numpy as np

from fedtpu.cli.common import (
    add_checkpoint_hardening_flags,
    add_fed_flags,
    add_model_flags,
    add_obs_flags,
    add_platform_flag,
    add_profile_flags,
    add_robustness_flags,
    add_sim_flags,
    add_telemetry_export_flags,
    apply_platform_flag,
    build_config,
    install_compile_watcher,
    install_final_flush,
    make_capture_window,
    make_chaos,
    make_checkpointer,
    make_flight_recorder,
    resolve_mfu_mode,
    start_obs_server,
)
from fedtpu.core import Federation
from fedtpu.data import load
from fedtpu.obs import RoundRecordWriter


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_fed_flags(p)
    p.add_argument("--num-clients", default=2, type=int)
    p.add_argument("--steps-per-round", default=8, type=int)
    add_sim_flags(p)
    p.add_argument(
        "--mesh",
        default="auto",
        choices=["auto", "off"],
        help="auto: when >1 device is visible and num-clients divides evenly, "
        "shard the clients axis over all devices (shard_map + psum FedAvg)",
    )
    p.add_argument(
        "--fused",
        default=1,
        type=int,
        metavar="N",
        help="run rounds in fused blocks of N: each block is ONE XLA program "
        "(lax.scan over the round body) with zero host involvement between "
        "rounds — numerically identical to per-round stepping. Eval and "
        "checkpointing happen at block boundaries. 1 = dispatch per round.",
    )
    p.add_argument(
        "--async-updates",
        default=0,
        type=int,
        metavar="N",
        help="run the ENGINE-side FedBuff async mode for N server updates "
        "instead of synchronous rounds: every live client trains its own "
        "model copy each tick, --buffer-k clients report per tick with "
        "staleness-discounted weights (fedtpu.core.async_engine; the "
        "simulated twin of the gRPC server's --async-updates)",
    )
    p.add_argument("--buffer-k", default=2, type=int)
    p.add_argument("--staleness-power", default=0.5, type=float)
    p.add_argument(
        "--staleness-damping", default="on", choices=["on", "off"],
        help="on (default): the staleness discount scales the applied "
        "update's magnitude (FedBuff-paper semantics — fixes the "
        "homogeneous-speed stall, see fedtpu.core.async_engine); off: "
        "weight-normalized mean (round-4 artifact semantics)",
    )
    p.add_argument(
        "--speed-sigma",
        default=0.0,
        type=float,
        help="client-speed heterogeneity for async arrivals (log-normal "
        "sigma; 0 = uniform). Larger -> slow clients accumulate staleness",
    )
    p.add_argument("--eval-every", default=5, type=int)
    p.add_argument(
        "--metrics", default=None,
        help="JSONL metrics path: one schema-versioned round record per "
        "round (fedtpu.obs.RoundRecordWriter; summarize with "
        "tools/metrics_report.py)",
    )
    add_telemetry_export_flags(p)
    add_obs_flags(p)
    add_profile_flags(p)
    add_robustness_flags(p)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", default=10, type=int)
    add_checkpoint_hardening_flags(p)
    p.add_argument("-r", "--resume", action="store_true")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the rounds here")
    p.add_argument("--progress", action="store_true",
                   help="per-round progress bar (headless-safe)")
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg = build_config(
        args,
        # --cohort is the device-buffer size in population mode; it IS
        # num_clients to everything downstream of the config.
        num_clients=args.cohort or args.num_clients,
        steps_per_round=args.steps_per_round,
    )
    if args.async_updates:
        if cfg.fed.sim.population:
            raise SystemExit(
                "--population composes with synchronous rounds only "
                "(the async FedBuff engine keeps per-client model copies — "
                "inherently O(clients) device state)"
            )
        return _run_async(args, cfg)
    if cfg.fed.sim.population:
        from fedtpu.sim import SimFederation

        if _auto_mesh(args) is not None:
            logging.warning(
                "--population runs single-program for now; ignoring the "
                "device mesh"
            )
        fed = SimFederation(cfg, seed=args.seed)
        logging.info(
            "sim population=%d cohort=%d scenario=%s sampler=%s "
            "heterogeneity=%.3f",
            cfg.fed.sim.population, cfg.fed.num_clients, fed.scenario_spec,
            cfg.fed.sim.cohort_sampler, fed._hetero,
        )
    else:
        fed = Federation(cfg, seed=args.seed, mesh=_auto_mesh(args))

    # The simulated engine has no RPC edge; chaos here means crash/latency
    # drills — delay/kill rules on the pseudo-RPC "Round", once per block —
    # plus the ckpt_* disk faults against the checkpoint store below.
    chaos = make_chaos(args, role="engine")
    logger = RoundRecordWriter(path=args.metrics, echo=not args.progress)
    flight = make_flight_recorder("engine", telemetry=fed.telemetry)
    # Performance observatory (fedtpu.obs.profile): compile counting from
    # the very first jit, MFU accounting when the registry is live, and the
    # --profile-rounds device-trace window driven from the round loop.
    compile_w = install_compile_watcher(
        telemetry=fed.telemetry, flight=flight
    )
    if compile_w is not None:
        fed.compile_watcher = compile_w
    mfu_mode = resolve_mfu_mode(args)
    if mfu_mode != "off" and hasattr(fed, "enable_mfu_accounting"):
        fed.enable_mfu_accounting(xla_check=mfu_mode == "xla")
    capture = make_capture_window(args, role="engine", telemetry=fed.telemetry)
    ckpt, start_round, state = _restore_from(
        args, like=fed.state, telemetry=fed.telemetry, flight=flight,
        chaos=chaos,
    )
    if state is not None:
        import jax
        import jax.numpy as jnp

        # Federation's state setter handles mesh re-placement.
        fed.state = jax.tree.map(jnp.asarray, state)
        logging.info("resumed from round %d", start_round)

    flush = install_final_flush(args, fed.telemetry, metrics=logger)
    obs = start_obs_server(
        args,
        registry=fed.telemetry.registry,
        status_fn=fed.status_snapshot,
        flight=flight,
    )
    eval_data = load(
        args.dataset, "test", seed=args.seed, num=args.num_examples
    )
    from fedtpu.utils.progress import ProgressBar, profile_rounds

    bar = (
        ProgressBar(cfg.fed.num_rounds - start_round) if args.progress else None
    )
    t0 = time.time()
    with profile_rounds(args.profile_dir):
        r = start_round
        while r < cfg.fed.num_rounds:
            if chaos is not None:
                chaos.tick_round(r)
            block = min(max(1, args.fused), cfg.fed.num_rounds - r)
            if capture is not None:
                # Fused blocks are captured whole — the profiler cannot cut
                # inside one XLA dispatch.
                capture.maybe_start(r, r + block - 1)
            if block > 1:
                stacked = fed.run_on_device(block)
                # Bulk transfers, not per-round scalar fetches — per-round
                # float() would re-add the host round-trips fusion removes.
                losses = np.asarray(stacked.loss)
                accs = np.asarray(stacked.accuracy)
                actives = np.asarray(stacked.num_active)
                worsts = np.asarray(stacked.per_client_loss).max(axis=1)
                screens = np.asarray(stacked.screened).sum(axis=1)
                per_round = [
                    (float(losses[i]), float(accs[i]), float(actives[i]),
                     float(worsts[i]), int(screens[i]))
                    for i in range(block)
                ]
            else:
                m = fed.step()
                per_round = [
                    (float(m.loss), float(m.accuracy), float(m.num_active),
                     float(np.asarray(m.per_client_loss).max()),
                     int(np.asarray(m.screened).sum()))
                ]
            # Eval/checkpoint cadences in fused mode: mid-block model states
            # never exist on the host, so a cadence point inside a block is
            # honored at the NEXT block boundary (interval-crossing test, not
            # exact alignment — --fused 4 --eval-every 5 still evals ~every 5
            # rounds instead of silently never).
            crossed_eval = args.eval_every and (
                (r + block) // args.eval_every > r // args.eval_every
            )
            from fedtpu.config import screening_enabled

            for i, (loss, acc, active, worst, screened) in enumerate(
                per_round
            ):
                ri = r + i
                rec = {
                    "loss": loss,
                    "acc": acc,
                    "active": active,
                    "worst_client_loss": worst,
                    "dataset": cfg.data.dataset,
                    # 'synthetic' marks loader-fallback runs: their accuracy
                    # curves are not comparable to real-data results.
                    "data_source": fed.data_source,
                }
                if screening_enabled(cfg.fed.screen):
                    rec["screened"] = screened
                    if screened:
                        fed.telemetry.counter(
                            "fedtpu_screening_rejected_total",
                            "client rows rejected by the fused screening "
                            "stage, by surface",
                            labels={"surface": "engine"},
                        ).inc(screened)
                if getattr(fed, "profiler", None) is not None:
                    rec.update(fed.profiler.record_fields())
                if crossed_eval and i == len(per_round) - 1:
                    rec["test_loss"], rec["test_acc"] = fed.evaluate(*eval_data)
                logger.log(ri, **rec)
                if bar is not None:
                    msg = f"loss {rec['loss']:.3f} acc {rec['acc']:.3f}"
                    if "test_acc" in rec:
                        msg += f" test_acc {rec['test_acc']:.3f}"
                    bar.update(ri - start_round, msg)
            if compile_w is not None and not compile_w.steady and (
                crossed_eval or not args.eval_every
            ):
                # Every program this loop runs has now compiled (round body
                # + eval); any further compile is a steady-state recompile.
                compile_w.mark_steady()
            prev = r
            r += block
            if capture is not None:
                capture.maybe_stop(r)
            if ckpt is not None and (
                r // args.checkpoint_every > prev // args.checkpoint_every
                or r == cfg.fed.num_rounds
            ):
                ckpt.save(r, fed.state)
    if capture is not None:
        capture.stop()  # idempotent: flush a window that spans the tail
    dt = time.time() - t0
    done = cfg.fed.num_rounds - start_round
    logging.info(
        "%d rounds in %.1fs (%.2f rounds/s)", done, dt, done / max(dt, 1e-9)
    )
    if ckpt is not None:
        ckpt.close()  # drain the background writer before reporting done
    if compile_w is not None:
        compile_w.uninstall()  # listeners are process-global
    # Idempotent with the atexit/SIGTERM registration — crash paths flush
    # the same way this clean exit does.
    flush()
    if obs is not None:
        obs.stop()
    return 0


def _restore_from(args, like, telemetry=None, flight=None, chaos=None):
    """Shared --checkpoint-dir/-r machinery for the sync and async loops:
    ``(checkpointer | None, start_index, restored_state | None)``. The
    checkpointer is the hardened store (fsync + manifests + generation
    fallback on restore, disk-chaos hooks), wrapped in the background
    writer unless --checkpoint-sync. Callers install the state themselves
    — the engines differ (Federation's state setter vs
    AsyncFederation.load_state), both mesh-aware — and own ``close()``."""
    ckpt = make_checkpointer(
        args, telemetry=telemetry, flight=flight, chaos=chaos,
    )
    if ckpt is None:
        return None, 0, None
    if not args.resume:
        return ckpt, 0, None
    latest = ckpt.restore_latest(like=like)
    if latest is None:
        return ckpt, 0, None
    return ckpt, latest[0], latest[1]


def _auto_mesh(args):
    """--mesh auto: shard the clients axis when >1 device is visible and the
    client count divides evenly. One rule for the sync AND async paths."""
    if args.mesh != "auto":
        return None
    import jax

    n_dev = len(jax.devices())
    if n_dev > 1 and args.num_clients % n_dev == 0:
        from fedtpu.parallel import client_mesh

        logging.info("clients axis sharded over %d devices", n_dev)
        return client_mesh()
    return None


def _run_async(args, cfg) -> int:
    """Engine-side FedBuff loop (fedtpu.core.async_engine): --async-updates
    server updates, --fused-sized scan blocks, eval at block boundaries."""
    from fedtpu.core import AsyncFederation

    if args.progress:
        logging.warning("--progress is ignored in async mode")
    fed = AsyncFederation(
        cfg,
        seed=args.seed,
        buffer_k=args.buffer_k,
        staleness_power=args.staleness_power,
        speed_sigma=args.speed_sigma,
        mesh=_auto_mesh(args),
        staleness_damping=args.staleness_damping == "on",
    )
    chaos = make_chaos(args, role="async_engine")
    logger = RoundRecordWriter(path=args.metrics, echo=True)
    flight = make_flight_recorder("async_engine", telemetry=fed.telemetry)
    ckpt, start_tick, state = _restore_from(
        args, like=fed.state, telemetry=fed.telemetry, flight=flight,
        chaos=chaos,
    )
    if state is not None:
        fed.load_state(state)  # async re-placement (mesh-aware)
        logging.info("resumed async state from update %d", start_tick)
    flush = install_final_flush(args, fed.telemetry, metrics=logger)
    obs = start_obs_server(
        args,
        registry=fed.telemetry.registry,
        status_fn=fed.status_snapshot,
        flight=flight,
    )
    eval_data = load(
        args.dataset, "test", seed=args.seed, num=args.num_examples
    )
    from fedtpu.utils.progress import profile_rounds

    t0 = time.time()
    with profile_rounds(args.profile_dir):
        _async_loop(args, fed, logger, eval_data, ckpt, start_tick, chaos)
    dt = time.time() - t0
    done = max(0, args.async_updates - start_tick)  # executed THIS run
    logging.info(
        "%d async updates in %.1fs (%.2f updates/s)",
        done, dt, done / max(dt, 1e-9),
    )
    if ckpt is not None:
        ckpt.close()
    flush()
    if obs is not None:
        obs.stop()
    return 0


def _async_loop(args, fed, logger, eval_data, ckpt=None, start_tick=0,
                chaos=None) -> None:
    # Same resume semantics as the sync loop: --async-updates is the TOTAL
    # update count, a resumed run finishes the remainder.
    t = start_tick
    while t < args.async_updates:
        if chaos is not None:
            chaos.tick_round(t)
        block = min(max(1, args.fused), args.async_updates - t)
        if block > 1:
            m = fed.run_on_device(block)
            losses = np.asarray(m.loss)
            stale = np.asarray(m.staleness_mean)
            rows = [
                (float(losses[i]), float(stale[i])) for i in range(block)
            ]
        else:
            m = fed.tick()
            rows = [(float(m.loss), float(m.staleness_mean))]
        crossed_eval = args.eval_every and (
            (t + block) // args.eval_every > t // args.eval_every
        )
        for i, (loss, stal) in enumerate(rows):
            rec = {
                "loss": loss,
                "staleness": stal,
                "buffer_k": args.buffer_k,
                "dataset": fed.cfg.data.dataset,
                "data_source": fed.data_source,
            }
            if crossed_eval and i == len(rows) - 1:
                rec["test_loss"], rec["test_acc"] = fed.evaluate(*eval_data)
            logger.log(t + i, **rec)
        t += block
        if ckpt is not None:
            crossed_ckpt = args.checkpoint_every and (
                t // args.checkpoint_every
                > (t - block) // args.checkpoint_every
            )
            if crossed_ckpt or t >= args.async_updates:
                # checkpoint.save owns the host transfer for every caller
                # (and the background writer snapshots before enqueue).
                ckpt.save(t, fed.state)


if __name__ == "__main__":
    raise SystemExit(main())
