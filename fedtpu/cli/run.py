"""``python -m fedtpu.cli.run`` — TPU-native simulated federation.

The deployment mode the reference cannot do: all clients as one array axis in
a single jitted program on the device mesh (SURVEY §7 design stance). This is
the path that hits the rounds/sec north star; the gRPC server/client CLIs
exist for the reference's multi-process edge topology.
"""

from __future__ import annotations

import argparse
import logging
import time

import numpy as np

from fedtpu.checkpoint import Checkpointer
from fedtpu.cli.common import add_fed_flags, add_model_flags, add_platform_flag, apply_platform_flag, build_config
from fedtpu.core import Federation
from fedtpu.data import load
from fedtpu.utils.metrics import MetricsLogger


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_fed_flags(p)
    p.add_argument("--num-clients", default=2, type=int)
    p.add_argument("--steps-per-round", default=8, type=int)
    p.add_argument(
        "--mesh",
        default="auto",
        choices=["auto", "off"],
        help="auto: when >1 device is visible and num-clients divides evenly, "
        "shard the clients axis over all devices (shard_map + psum FedAvg)",
    )
    p.add_argument(
        "--fused",
        default=1,
        type=int,
        metavar="N",
        help="run rounds in fused blocks of N: each block is ONE XLA program "
        "(lax.scan over the round body) with zero host involvement between "
        "rounds — numerically identical to per-round stepping. Eval and "
        "checkpointing happen at block boundaries. 1 = dispatch per round.",
    )
    p.add_argument("--eval-every", default=5, type=int)
    p.add_argument("--metrics", default=None, help="JSONL metrics path")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", default=10, type=int)
    p.add_argument("-r", "--resume", action="store_true")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the rounds here")
    p.add_argument("--progress", action="store_true",
                   help="per-round progress bar (headless-safe)")
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg = build_config(
        args, num_clients=args.num_clients, steps_per_round=args.steps_per_round
    )
    mesh = None
    if args.mesh == "auto":
        import jax

        n_dev = len(jax.devices())
        if n_dev > 1 and args.num_clients % n_dev == 0:
            from fedtpu.parallel import client_mesh

            mesh = client_mesh()
            logging.info("clients axis sharded over %d devices", n_dev)
    fed = Federation(cfg, seed=args.seed, mesh=mesh)

    ckpt = None
    start_round = 0
    if args.checkpoint_dir:
        ckpt = Checkpointer(args.checkpoint_dir, backend="wire")
        if args.resume:
            latest = ckpt.restore_latest(like=fed.state)
            if latest is not None:
                start_round, state = latest
                import jax
                import jax.numpy as jnp

                fed.state = jax.tree.map(jnp.asarray, state)
                logging.info("resumed from round %d", start_round)

    logger = MetricsLogger(path=args.metrics, echo=not args.progress)
    eval_data = load(
        args.dataset, "test", seed=args.seed, num=args.num_examples
    )
    from fedtpu.utils.progress import ProgressBar, profile_rounds

    bar = (
        ProgressBar(cfg.fed.num_rounds - start_round) if args.progress else None
    )
    t0 = time.time()
    with profile_rounds(args.profile_dir):
        r = start_round
        while r < cfg.fed.num_rounds:
            block = min(max(1, args.fused), cfg.fed.num_rounds - r)
            if block > 1:
                stacked = fed.run_on_device(block)
                # Bulk transfers, not per-round scalar fetches — per-round
                # float() would re-add the host round-trips fusion removes.
                losses = np.asarray(stacked.loss)
                accs = np.asarray(stacked.accuracy)
                actives = np.asarray(stacked.num_active)
                worsts = np.asarray(stacked.per_client_loss).max(axis=1)
                per_round = [
                    (float(losses[i]), float(accs[i]), float(actives[i]),
                     float(worsts[i]))
                    for i in range(block)
                ]
            else:
                m = fed.step()
                per_round = [
                    (float(m.loss), float(m.accuracy), float(m.num_active),
                     float(np.asarray(m.per_client_loss).max()))
                ]
            # Eval/checkpoint cadences in fused mode: mid-block model states
            # never exist on the host, so a cadence point inside a block is
            # honored at the NEXT block boundary (interval-crossing test, not
            # exact alignment — --fused 4 --eval-every 5 still evals ~every 5
            # rounds instead of silently never).
            crossed_eval = args.eval_every and (
                (r + block) // args.eval_every > r // args.eval_every
            )
            for i, (loss, acc, active, worst) in enumerate(per_round):
                ri = r + i
                rec = {
                    "loss": loss,
                    "acc": acc,
                    "active": active,
                    "worst_client_loss": worst,
                    "dataset": cfg.data.dataset,
                    # 'synthetic' marks loader-fallback runs: their accuracy
                    # curves are not comparable to real-data results.
                    "data_source": fed.data_source,
                }
                if crossed_eval and i == len(per_round) - 1:
                    rec["test_loss"], rec["test_acc"] = fed.evaluate(*eval_data)
                logger.log(ri, **rec)
                if bar is not None:
                    msg = f"loss {rec['loss']:.3f} acc {rec['acc']:.3f}"
                    if "test_acc" in rec:
                        msg += f" test_acc {rec['test_acc']:.3f}"
                    bar.update(ri - start_round, msg)
            prev = r
            r += block
            if ckpt is not None and (
                r // args.checkpoint_every > prev // args.checkpoint_every
                or r == cfg.fed.num_rounds
            ):
                ckpt.save(r, fed.state)
    dt = time.time() - t0
    done = cfg.fed.num_rounds - start_round
    logging.info(
        "%d rounds in %.1fs (%.2f rounds/s)", done, dt, done / max(dt, 1e-9)
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
