"""``python -m fedtpu.cli.client`` — federated client agent.

Parity with ``python3 client.py -a localhost:50051`` (``src/client.py:55-71``):
hosts the ``Trainer`` gRPC server and trains on StartTrain. Unlike the
reference there are no import-time side effects (``src/client.py:9`` imports
``main``, which parses argv, downloads CIFAR, and builds the model at import —
SURVEY §3.2); everything is constructed explicitly here.
"""

from __future__ import annotations

import argparse
import logging

from fedtpu.cli.common import (
    add_compression_flags,
    add_model_flags,
    add_obs_flags,
    add_platform_flag,
    add_robustness_flags,
    add_telemetry_export_flags,
    apply_platform_flag,
    build_config,
    compress_enabled,
    install_final_flush,
    make_chaos,
    make_flight_recorder,
    start_obs_server,
)
from fedtpu.transport.federation import serve_client


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_compression_flags(p)
    p.add_argument(
        "--telemetry",
        default="basic",
        choices=["off", "basic", "trace"],
        help="client-side self-measurement level (fedtpu.obs). At 'trace' "
        "the client's spans adopt the coordinator's propagated trace "
        "context (fedtpu-trace-bin metadata), so its --trace-out dump "
        "merges under the coordinator's rounds via tools/trace_merge.py",
    )
    add_telemetry_export_flags(p)
    add_obs_flags(p)
    add_robustness_flags(p)
    p.add_argument("-a", "--address", default="localhost:50051",
                   help="bind address (doubles as the client's identity)")
    p.add_argument("--world", default=2, type=int,
                   help="total client count (for config only; actual world "
                   "arrives with each StartTrain)")
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg = build_config(args, num_clients=args.world)
    server, agent = serve_client(
        args.address, cfg, seed=args.seed, compress=compress_enabled(args),
        chaos=make_chaos(args, role=f"client-{args.address}"),
    )
    # A client agent exits via signal (it serves until terminated), so the
    # exporters ONLY fire through the SIGTERM/atexit flush.
    install_final_flush(args, agent.trainer.telemetry)
    flight = make_flight_recorder(
        f"client-{args.address}", telemetry=agent.trainer.telemetry
    )
    obs = start_obs_server(
        args, registry=agent.trainer.telemetry.registry,
        status_fn=agent.status_snapshot, flight=flight,
    )
    logging.info("client agent serving on %s", args.address)
    try:
        server.wait_for_termination()
    finally:
        if obs is not None:
            obs.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
