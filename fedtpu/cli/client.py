"""``python -m fedtpu.cli.client`` — federated client agent.

Parity with ``python3 client.py -a localhost:50051`` (``src/client.py:55-71``):
hosts the ``Trainer`` gRPC server and trains on StartTrain. Unlike the
reference there are no import-time side effects (``src/client.py:9`` imports
``main``, which parses argv, downloads CIFAR, and builds the model at import —
SURVEY §3.2); everything is constructed explicitly here.
"""

from __future__ import annotations

import argparse
import logging

from fedtpu.cli.common import (
    add_compression_flags,
    add_model_flags,
    add_obs_flags,
    add_platform_flag,
    add_robustness_flags,
    add_telemetry_export_flags,
    apply_platform_flag,
    build_config,
    compress_enabled,
    install_final_flush,
    make_chaos,
    make_flight_recorder,
    start_obs_server,
)
from fedtpu.transport.federation import serve_client


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    add_platform_flag(p)
    add_model_flags(p)
    add_compression_flags(p)
    p.add_argument(
        "--telemetry",
        default="basic",
        choices=["off", "basic", "trace"],
        help="client-side self-measurement level (fedtpu.obs). At 'trace' "
        "the client's spans adopt the coordinator's propagated trace "
        "context (fedtpu-trace-bin metadata), so its --trace-out dump "
        "merges under the coordinator's rounds via tools/trace_merge.py",
    )
    add_telemetry_export_flags(p)
    add_obs_flags(p)
    add_robustness_flags(p)
    p.add_argument("-a", "--address", default="localhost:50051",
                   help="bind address (doubles as the client's identity)")
    p.add_argument("--world", default=2, type=int,
                   help="total client count (for config only; actual world "
                   "arrives with each StartTrain)")
    p.add_argument(
        "--join", default=None, metavar="HOST:PORT",
        help="announce this client to the coordinator's membership gate "
        "(--gate on the server CLI) instead of requiring it in the "
        "server's --clients list: sends Join(address) with retries until "
        "admitted, after which the coordinator resyncs the global model "
        "and samples this client into rounds (docs/FAULT_TOLERANCE.md)",
    )
    p.add_argument(
        "--join-timeout", default=60.0, type=float, metavar="SECONDS",
        help="give up announcing after this long (the gate may start "
        "after the client; Join retries with backoff until then)",
    )
    p.add_argument(
        "--state-dir", default=None, metavar="DIR",
        help="persist this client's local training state (round counter, "
        "optimizer moments, PRNG stream, error-feedback residual) per "
        "round under DIR via the hardened generational checkpoint store, "
        "and restore it on startup: a restarted client then RESUMES its "
        "trajectory instead of silently diverging (fresh residual, "
        "replayed batch draws). The server still resyncs the weights; "
        "this covers the state only this process holds "
        "(docs/OPERATIONS.md §Disaster recovery)",
    )
    p.add_argument(
        "--leave-on-exit", action="store_true",
        help="send Leave(address) to the --join gate on shutdown, so the "
        "coordinator evicts this client (freeing its seat) instead of "
        "probing a silent departure forever",
    )
    args = p.parse_args(argv)
    apply_platform_flag(args)

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    cfg = build_config(args, num_clients=args.world)
    server, agent = serve_client(
        args.address, cfg, seed=args.seed, compress=compress_enabled(args),
        chaos=make_chaos(args, role=f"client-{args.address}"),
        state_dir=args.state_dir,
    )
    # A client agent exits via signal (it serves until terminated), so the
    # exporters ONLY fire through the SIGTERM/atexit flush.
    install_final_flush(args, agent.trainer.telemetry)
    flight = make_flight_recorder(
        f"client-{args.address}", telemetry=agent.trainer.telemetry
    )
    obs = start_obs_server(
        args, registry=agent.trainer.telemetry.registry,
        status_fn=agent.status_snapshot, flight=flight,
    )
    logging.info("client agent serving on %s", args.address)
    gate_stub = None
    if args.join:
        from fedtpu.transport import announce_join

        gate_stub = announce_join(
            args.join, args.address, timeout_s=args.join_timeout,
        )
        if gate_stub is None:
            logging.error("never admitted by gate %s; serving anyway "
                          "(the coordinator may still list us statically)",
                          args.join)
    try:
        server.wait_for_termination()
    finally:
        if args.leave_on_exit and gate_stub is not None:
            from fedtpu.transport import announce_leave

            announce_leave(gate_stub, args.address)
        if obs is not None:
            obs.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
